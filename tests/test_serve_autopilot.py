"""SLO autopilot integration (docs/autoscale.md): the closed control loop
running inside the real ServeController — burn-rate scale-up, idle
drain-down, adaptive WFQ weight broadcasts with the starvation floor, the
satellite regression that autoscale targets survive a controller SIGKILL
(KV-persisted, not snapped back to the declarative spec), and the legacy
autoscaler's target surviving an identical redeploy.

Deployments opt in by answering `autopilot_signals()`; the FakeEngine here
reads its pressure from a shared box actor so tests can turn SLO burn and
queue depth up and down like a dial — no model, no real traffic needed.
"""

import os
import signal
import time

import pytest

import ray_tpu
from ray_tpu import serve
from tests.conftest import _WORKER_ENV

# The autopilot flag + timing knobs must reach the CONTROLLER process (and
# every replica): CONFIG reads env per process. Tiny intervals/cooldowns so
# sustained-pressure hysteresis resolves in test time, with a long enough
# downscale cooldown that up and down phases don't interleave.
_AP_ENV = {
    **_WORKER_ENV,
    "RAY_TPU_SERVE_AUTOPILOT": "1",
    "RAY_TPU_SERVE_AUTOPILOT_INTERVAL_S": "0.1",
    "RAY_TPU_SERVE_AUTOPILOT_SUSTAIN_TICKS": "2",
    "RAY_TPU_SERVE_AUTOPILOT_UPSCALE_COOLDOWN_S": "0.2",
    "RAY_TPU_SERVE_AUTOPILOT_DOWNSCALE_COOLDOWN_S": "0.5",
    "RAY_TPU_SERVE_AUTOPILOT_COLD_START_GUARD_S": "1.0",
    "RAY_TPU_SERVE_AUTOPILOT_QUEUE_HIGH": "8",
}


@pytest.fixture(scope="module", autouse=True)
def _cluster():
    ray_tpu.init(num_cpus=6, num_tpus=0, worker_env=_AP_ENV)
    yield
    serve.shutdown()
    ray_tpu.shutdown()


@pytest.fixture(autouse=True)
def _fresh_apps():
    yield
    for app in list(serve.status()):
        serve.delete(app)


@ray_tpu.remote
class PressureBox:
    """Shared signal dial: replicas read their reported pressure here and
    record the weight broadcasts they receive."""

    def __init__(self):
        self._sig = {"queued": 0, "running": 1, "burn_rate": 0.0,
                     "tenant_burn": {}}
        self._weights = {}

    def set_pressure(self, **kw):
        self._sig.update(kw)

    def signals(self):
        return dict(self._sig)

    def note_weight(self, tenant, weight):
        self._weights.setdefault(tenant, []).append(weight)

    def weights(self):
        return dict(self._weights)


def _fake_engine(box):
    @serve.deployment(autoscaling_config={
        "min_replicas": 1, "max_replicas": 3,
        # Make the legacy ongoing-requests law inert so any scaling observed
        # is the autopilot's (the controller also stands the legacy law down
        # for managed deployments — that standdown is under test here).
        "target_ongoing_requests": 1e9,
    })
    class Engine:
        def __init__(self, pressure_box):
            self._box = pressure_box

        def pid(self):
            return os.getpid()

        def autopilot_signals(self):
            sig = ray_tpu.get(self._box.signals.remote())
            sig["role"] = "engine"
            return sig

        def set_tenant_weight(self, tenant, weight):
            ray_tpu.get(self._box.note_weight.remote(tenant, weight))
            return weight

        def __call__(self, x):
            return x

    return Engine.bind(box)


def _replica_count(app, deployment):
    st = serve.status()
    return (st.get(app, {}).get("deployments", {})
            .get(deployment, {}).get("num_replicas", 0))


def _wait_for(pred, timeout_s=60.0, interval_s=0.2):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        got = pred()
        if got:
            return got
        time.sleep(interval_s)
    return None


def _controller():
    from ray_tpu.serve._common import CONTROLLER_NAME, SERVE_NAMESPACE

    return ray_tpu.get_actor(CONTROLLER_NAME, namespace=SERVE_NAMESPACE)


def test_autopilot_scales_up_on_burn_and_back_down():
    box = PressureBox.remote()
    handle = serve.run(_fake_engine(box), name="ap-scale", route_prefix=None)
    assert handle.remote(1).result(timeout_s=60) == 1
    assert _replica_count("ap-scale", "Engine") == 1

    # Sustained burn + queue pressure: the autopilot must scale up.
    ray_tpu.get(box.set_pressure.remote(queued=30, burn_rate=3.0))
    assert _wait_for(
        lambda: _replica_count("ap-scale", "Engine") >= 2), \
        "autopilot never scaled up under sustained burn"

    # Pressure gone AND idle (no queued, no in-flight): drain back down.
    ray_tpu.get(box.set_pressure.remote(queued=0, running=0, burn_rate=0.0))
    assert _wait_for(
        lambda: _replica_count("ap-scale", "Engine") == 1, timeout_s=90), \
        "autopilot never drained idle replicas back down"

    # Every decision is on the record, with its actuation outcome.
    stats = ray_tpu.get(_controller().autopilot_stats.remote(), timeout=30)
    assert stats["enabled"]
    rules = {d["rule"] for d in stats["decisions"]}
    assert "replica_up" in rules and "replica_down" in rules
    applied = [d for d in stats["decisions"] if d["outcome"] == "applied"]
    assert applied, f"no decision recorded as applied: {stats['decisions']}"
    assert stats["targets"].get("ap-scale#Engine") == 1

    # The one-call operator snapshot surfaces the same plane.
    from ray_tpu.util.state import serve_stats

    snap = serve_stats(timeout_s=30)
    assert snap["autopilot"]["enabled"]
    assert "ap-scale#Engine" in snap["autopilot"]["targets"]


def test_autopilot_weight_broadcast_respects_floor():
    box = PressureBox.remote()
    serve.run(_fake_engine(box), name="ap-weights", route_prefix=None)

    # One tenant burns its SLO budget 3x over; one is comfortably inside.
    ray_tpu.get(box.set_pressure.remote(
        tenant_burn={"noisy": 3.0, "quiet": 0.1}))

    def noisy_boosted():
        w = ray_tpu.get(box.weights.remote())
        return [x for x in w.get("noisy", []) if x > 1.0]

    boosts = _wait_for(noisy_boosted)
    assert boosts, "breaching tenant's weight was never raised"

    weights = ray_tpu.get(box.weights.remote())
    from ray_tpu._private.config import CONFIG

    # No broadcast may push ANY tenant below the starvation floor, and the
    # compliant tenant is never demoted below its initial fair share.
    for tenant, history in weights.items():
        for w in history:
            assert w >= CONFIG.serve_autopilot_weight_floor
    assert all(w >= 1.0 for w in weights.get("quiet", []))

    stats = ray_tpu.get(_controller().autopilot_stats.remote(), timeout=30)
    assert stats["weights"]["ap-weights"]["noisy"] > 1.0


def test_autopilot_target_survives_controller_sigkill():
    """Satellite regression: kill the controller mid-scale-up — the
    autopilot-held target is KV-persisted in its own record, so the new
    incarnation must keep the scaled-up replica count instead of snapping
    back to the declarative spec's one replica."""
    box = PressureBox.remote()
    serve.run(_fake_engine(box), name="ap-restart", route_prefix=None)
    ray_tpu.get(box.set_pressure.remote(queued=30, burn_rate=3.0))
    assert _wait_for(lambda: _replica_count("ap-restart", "Engine") >= 2), \
        "no scale-up before the kill"
    scaled = _replica_count("ap-restart", "Engine")

    # Hold pressure NEUTRAL (not hot, not idle: in-flight work pins it) so
    # any replica-count change after the restart is a recovery bug, not a law
    # firing.
    ray_tpu.get(box.set_pressure.remote(queued=0, running=1, burn_rate=0.0))

    controller = _controller()
    old_pid = ray_tpu.get(controller.health.remote(), timeout=30)["pid"]
    os.kill(old_pid, signal.SIGKILL)
    assert _wait_for(
        lambda: _probe_pid(controller) not in (None, old_pid),
        timeout_s=90), "controller never restarted"

    # The recovered controller reconciles from the PERSISTED autopilot
    # target: the replica count must hold for several control-loop ticks.
    time.sleep(2.0)
    assert _replica_count("ap-restart", "Engine") == scaled, \
        "controller restart snapped the autopilot target back to the spec"
    stats = ray_tpu.get(_controller().autopilot_stats.remote(), timeout=30)
    assert stats["targets"].get("ap-restart#Engine") == scaled


def _probe_pid(controller):
    try:
        return ray_tpu.get(controller.health.remote(), timeout=10)["pid"]
    except Exception:
        return None


def test_legacy_autoscale_target_survives_identical_redeploy():
    """Satellite regression for the non-autopilot path: a replayed deploy of
    the identical app must ADOPT the current autoscale target from the
    previous spec, not reset the replica count to min_replicas."""

    @serve.deployment(autoscaling_config={
        "min_replicas": 1, "max_replicas": 3,
        "target_ongoing_requests": 1.0, "upscale_delay_s": 0.2,
    })
    class Slow:
        def __call__(self, x):
            time.sleep(0.4)
            return x

    handle = serve.run(Slow.bind(), name="ap-legacy", route_prefix=None)
    responses = [handle.remote(i) for i in range(12)]
    assert _wait_for(lambda: _replica_count("ap-legacy", "Slow") >= 2,
                     timeout_s=30), "legacy autoscaler never scaled up"
    scaled = _replica_count("ap-legacy", "Slow")
    assert sorted(r.result(timeout_s=60) for r in responses) == list(range(12))

    serve.run(Slow.bind(), name="ap-legacy", route_prefix=None)
    assert _replica_count("ap-legacy", "Slow") == scaled, \
        "identical redeploy reset the autoscale target to min_replicas"
