"""The last two BASELINE.md north stars, measured (writes BENCH_RLLIB.json).

1. `ppo_learner_samples_per_s` — RLlib PPO with CPU rollout workers feeding a
   learner on the default accelerator (the TPU chip on the bench host; env
   runners force the CPU backend by design — env_runner.py). Throughput is
   env samples consumed by the learner per wall second over whole train()
   iterations — the reference's learner_group env-steps-per-second semantics
   (rllib/core/learner/learner_group.py:96 lifetime counters / wall time).
   CartPole-v1 stands in for Atari: the image carries no ALE/ROM deps; the
   pipeline exercised (vector envs -> fragments -> GAE -> minibatch epochs on
   the learner) is identical, only the observation is 4-dim instead of
   84x84x4.

2. `mnist_mlp_parity` — Train DataParallelTrainer steps/s on an MNIST-shaped
   MLP (784-256-10) over 2 CPU workers, against the same model/batch stepped
   by torch (the reference's compute stack) in-process on the same host.
   vs_torch > 1 means the jax DataParallelTrainer out-steps single-process
   torch SGD despite paying the 2-worker allreduce.
"""

from __future__ import annotations

import json
import time


def ppo_learner_throughput(iters: int = 12):
    from ray_tpu.rllib import PPOConfig

    config = (
        PPOConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=2, num_envs_per_env_runner=2)
        .training(train_batch_size=2048, minibatch_size=512, num_epochs=4,
                  lr=3e-4)
        .debugging(seed=0)
    )
    algo = config.build_algo()
    try:
        algo.train()  # warm: compiles the learner step + spawns runners
        base = algo._total_timesteps
        t0 = time.perf_counter()
        returns = []
        for _ in range(iters):
            m = algo.train()
            returns.append(m.get("episode_return_mean"))
        dt = time.perf_counter() - t0
        measured = algo._total_timesteps - base
        return {
            "metric": "ppo_learner_samples_per_s",
            "value": round(measured / dt, 1),
            "unit": "env_samples/s",
            "iters": iters,
            "final_episode_return_mean": round(float(returns[-1]), 1),
            "config": {"env": "CartPole-v1", "env_runners": 2,
                       "envs_per_runner": 2, "train_batch_size": 2048,
                       "epochs": 4, "minibatch": 512},
            "note": "CartPole stands in for Atari (no ALE deps in image); "
                    "same sample->GAE->minibatch learner pipeline. Samples "
                    "counted at the learner, reference learner_group "
                    "semantics. On this host the TPU learner sits behind the "
                    "axon dispatch tunnel (100ms+ per update) and rollouts "
                    "share one CPU core — both dominate the absolute number, "
                    "as with BENCH_SERVE's concurrency-1 decode.",
        }
    finally:
        algo.stop()


def _mnist_data(n=4096, seed=0):
    import numpy as np

    rng = np.random.default_rng(seed)
    x = rng.normal(0, 1, (n, 784)).astype("float32")
    w_true = rng.normal(0, 1, (784, 10)).astype("float32")
    y = (x @ w_true).argmax(axis=1).astype("int64")
    return x, y


def mnist_jax_trainer(steps: int = 200, batch: int = 128, workers: int = 2):
    """DataParallelTrainer steps/s (jax CPU workers; >1 adds a per-step
    parameter allreduce)."""
    import ray_tpu
    from ray_tpu import train
    from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig

    def loop(config):
        import os as _os
        import time as _t

        # This north-star row is CPU workers: keep the remote-TPU tunnel (and
        # its 100ms+ per-dispatch latency) out of a 784-dim MLP step.
        _os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")
        import jax.numpy as jnp
        import numpy as np
        import optax

        from ray_tpu import train as T
        from ray_tpu.util import collective as col

        steps, batch = config["steps"], config["batch"]
        world = T.get_context().get_world_size()
        x, y = _mnist_data()
        rank = T.get_context().get_world_rank()
        if world > 1:
            col.init_collective_group(world, rank, backend="host",
                                      group_name="mnist-bench")

        def init(key):
            k1, k2 = jax.random.split(key)
            return {
                "w1": jax.random.normal(k1, (784, 256)) * 0.05,
                "b1": jnp.zeros((256,)),
                "w2": jax.random.normal(k2, (256, 10)) * 0.05,
                "b2": jnp.zeros((10,)),
            }

        params = init(jax.random.PRNGKey(0))  # same init on both ranks
        opt = optax.sgd(0.05)
        opt_state = opt.init(params)

        def loss_fn(p, xb, yb):
            h = jnp.tanh(xb @ p["w1"] + p["b1"])
            logits = h @ p["w2"] + p["b2"]
            logz = jax.nn.logsumexp(logits, axis=-1)
            gold = jnp.take_along_axis(logits, yb[:, None], axis=-1)[:, 0]
            return jnp.mean(logz - gold)

        @jax.jit
        def step(p, o, xb, yb):
            l, g = jax.value_and_grad(loss_fn)(p, xb, yb)
            upd, o = opt.update(g, o)
            return optax.apply_updates(p, upd), o, l

        leaves, treedef = jax.tree_util.tree_flatten(params)
        sizes = [leaf.size for leaf in leaves]
        shapes = [leaf.shape for leaf in leaves]

        def sync_params(params):
            if world == 1:
                return params
            # DDP-equivalent: one flat host allreduce of the params per step,
            # averaged across the workers.
            ls = jax.tree_util.tree_leaves(params)
            flat = np.concatenate([np.asarray(a).ravel() for a in ls])
            flat = np.asarray(
                col.allreduce(flat, group_name="mnist-bench")
            ) / world
            out, off = [], 0
            for sz, shp in zip(sizes, shapes):
                out.append(jnp.asarray(flat[off:off + sz]).reshape(shp))
                off += sz
            return jax.tree_util.tree_unflatten(treedef, out)

        # warm + first allreduce
        xb, yb = x[:batch], y[:batch]
        params, opt_state, l = step(params, opt_state, xb, yb)
        params = sync_params(params)
        t0 = _t.perf_counter()
        for i in range(steps):
            lo = (i * batch) % (len(x) - batch)
            params, opt_state, l = step(
                params, opt_state, x[lo:lo + batch], y[lo:lo + batch]
            )
            params = sync_params(params)
        dt = _t.perf_counter() - t0
        T.report({"steps_per_s": steps / dt, "final_loss": float(l)})

    result = JaxTrainer(
        loop,
        train_loop_config={"steps": steps, "batch": batch},
        scaling_config=ScalingConfig(num_workers=workers, use_tpu=False,
                                     resources_per_worker={"CPU": 1}),
        run_config=RunConfig(name=f"bench-mnist-{workers}",
                             storage_path="/tmp/ray_tpu_bench_mnist"),
    ).fit()
    if result.error is not None:
        raise RuntimeError(f"mnist trainer failed: {result.error}")
    return result.metrics


def mnist_torch_baseline(steps: int = 200, batch: int = 128):
    """Single-process torch SGD on the same model/batch: the reference-stack
    stand-in for 'steps/s parity'."""
    import torch

    torch.set_num_threads(2)  # match the 2-CPU budget of the jax run
    x_np, y_np = _mnist_data()
    x = torch.from_numpy(x_np)
    y = torch.from_numpy(y_np)
    model = torch.nn.Sequential(
        torch.nn.Linear(784, 256), torch.nn.Tanh(), torch.nn.Linear(256, 10)
    )
    opt = torch.optim.SGD(model.parameters(), lr=0.05)
    loss_fn = torch.nn.CrossEntropyLoss()
    # warm
    out = model(x[:batch])
    loss_fn(out, y[:batch]).backward()
    opt.step()
    t0 = time.perf_counter()
    for i in range(steps):
        lo = (i * batch) % (len(x) - batch)
        opt.zero_grad()
        loss = loss_fn(model(x[lo:lo + batch]), y[lo:lo + batch])
        loss.backward()
        opt.step()
    dt = time.perf_counter() - t0
    return {"steps_per_s": steps / dt, "final_loss": float(loss)}


def main():
    import ray_tpu

    results = {"bench": "rllib+train north stars"}
    ray_tpu.init(num_cpus=6, num_tpus=0)
    try:
        results["ppo_learner"] = ppo_learner_throughput()
    finally:
        ray_tpu.shutdown()
    # The MNIST row is CPU workers: train workers inherit the cluster's
    # worker env, and on this host jax initializes (onto the remote-TPU
    # tunnel) before the user loop runs — the env must be set at worker
    # spawn, not inside the loop.
    ray_tpu.init(num_cpus=6, num_tpus=0,
                 worker_env={"JAX_PLATFORMS": "cpu",
                             "PALLAS_AXON_POOL_IPS": ""})
    try:
        jx1 = mnist_jax_trainer(workers=1)
        jx2 = mnist_jax_trainer(workers=2)
        th = mnist_torch_baseline()
        results["mnist_mlp_parity"] = {
            "metric": "mnist_mlp_dataparallel_steps_per_s",
            "jax_1worker_steps_per_s": round(jx1["steps_per_s"], 1),
            "jax_2worker_steps_per_s": round(jx2["steps_per_s"], 1),
            "torch_1proc_steps_per_s": round(th["steps_per_s"], 1),
            "vs_torch_1worker": round(jx1["steps_per_s"] / th["steps_per_s"], 3),
            "vs_torch_2worker": round(jx2["steps_per_s"] / th["steps_per_s"], 3),
            "model": "784-256-10 MLP, batch 128, SGD",
            "note": "1-worker is the stack-vs-stack parity row (same host, "
                    "same batch); the 2-worker row adds a per-step host "
                    "allreduce (~5 ms) AND halves each worker's share of this "
                    "1-core host — on real multi-core hosts the 2-worker run "
                    "doubles sample throughput at the 1-worker step rate.",
        }
    finally:
        ray_tpu.shutdown()
    with open("BENCH_RLLIB.json", "w") as f:
        json.dump(results, f, indent=1)
    print(json.dumps(results))


if __name__ == "__main__":
    main()
