"""ray_tpu.train: distributed training orchestration (Ray Train parity, TPU-first).

Reference surface (python/ray/train/__init__.py + v2 api): report, get_context,
get_checkpoint, get_dataset_shard, Checkpoint, ScalingConfig/RunConfig/FailureConfig/
CheckpointConfig, Result, DataParallelTrainer, JaxTrainer (the flagship), backend SPI.
"""

from ray_tpu.train.backend import Backend, BackendConfig
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import (
    CheckpointConfig,
    FailureConfig,
    Result,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.train.context import (
    TrainContext,
    get_checkpoint,
    get_context,
    get_dataset_shard,
    report,
    train_stats,
)
from ray_tpu.train.data_parallel_trainer import DataParallelTrainer
from ray_tpu.train._internal.controller import TrainingFailedError
from ray_tpu.train.jax import JaxConfig, JaxTrainer

__all__ = [
    "Backend",
    "BackendConfig",
    "Checkpoint",
    "CheckpointConfig",
    "DataParallelTrainer",
    "FailureConfig",
    "JaxConfig",
    "JaxTrainer",
    "Result",
    "RunConfig",
    "ScalingConfig",
    "TrainContext",
    "TrainingFailedError",
    "get_checkpoint",
    "get_context",
    "get_dataset_shard",
    "report",
    "train_stats",
]
