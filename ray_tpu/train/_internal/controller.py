"""TrainController: the run loop that owns worker groups across restarts.

Design parity: reference `python/ray/train/v2/_internal/execution/controller/
controller.py:99` — run() :487 creates a worker group per attempt (ScalingPolicy),
polls worker health (:266), routes reported results to the CheckpointManager, and on
failure consults the FailurePolicy to restart from the latest checkpoint or raise.
By default the controller runs as a DETACHED named actor (`DetachedControllerRunner`,
reference :99 detached actor) so the run survives driver death; a driver that comes
back with the same run name re-attaches to the live controller.
"""

from __future__ import annotations

import os
import time

from ray_tpu.exceptions import RayTpuError
from ray_tpu.train.config import Result, RunConfig, ScalingConfig
from ray_tpu.train._internal.checkpoint_manager import CheckpointManager
from ray_tpu.train._internal.failure_policy import (
    DefaultFailurePolicy,
    FailureDecision,
    ScalingPolicy,
)
from ray_tpu.train._internal.worker_group import WorkerGroup


class TrainingFailedError(RayTpuError):
    """Parity: ray.train.base_trainer.TrainingFailedError."""


class TrainController:
    def __init__(
        self,
        *,
        train_fn,
        train_fn_config: dict | None,
        scaling_config: ScalingConfig,
        run_config: RunConfig,
        backend=None,
        backend_config=None,
        datasets: dict | None = None,
        poll_interval_s: float = 0.2,
        trial_info: dict | None = None,
        resume_from_storage: bool = False,
    ):
        self._train_fn = train_fn
        self._train_fn_config = train_fn_config
        self._scaling = scaling_config
        self._run_config = run_config
        self._backend = backend
        self._backend_config = backend_config
        self._datasets = datasets or {}
        self._poll_interval_s = poll_interval_s
        self._trial_info = trial_info
        self._failure_policy = DefaultFailurePolicy(
            run_config.failure_config.max_failures
        )
        if getattr(scaling_config, "min_workers", None) is not None:
            from ray_tpu.train._internal.failure_policy import ElasticScalingPolicy

            self._scaling_policy = ElasticScalingPolicy(
                scaling_config, scaling_config.min_workers
            )
        else:
            self._scaling_policy = ScalingPolicy(scaling_config)
        self._checkpoints = CheckpointManager(run_config.checkpoint_config)
        self._latest_metrics: dict | None = None
        self._flight_totals: dict[int, dict] = {}  # rank -> phase seconds
        self._flight_reports = 0
        self._experiment_name = run_config.name or f"train_{int(time.time())}"
        self._storage_path = os.path.expanduser(run_config.storage_path)
        # A RESTARTED detached controller (not a fresh fit with a reused name)
        # resumes from the latest committed checkpoint on storage instead of
        # restarting the run from scratch.
        self._resume_from_storage = resume_from_storage

    # ------------------------------------------------------------------ run

    def run(self) -> Result:
        failure_count = 0
        transient_restarts = 0
        attempt = 0
        if self._resume_from_storage:
            self._recover_committed_checkpoints()
        while True:
            group = None
            try:
                group = self._start_worker_group(attempt)
                error = self._monitor(group)
            except Exception as e:
                # Worker/actor death, placement timeouts, and startup-hook failures all
                # route through the failure policy like in-loop training failures.
                import traceback

                error = "".join(traceback.format_exception(e))
            finally:
                if group is not None:
                    if self._backend is not None:
                        try:
                            self._backend.on_shutdown(group, self._backend_config)
                        except Exception:
                            pass
                    group.shutdown()
            if error is None:
                return self._build_result(error=None)
            attempt += 1
            from ray_tpu.train._internal.failure_policy import (
                is_transient_infra_error,
            )

            if is_transient_infra_error(error) and transient_restarts < 3:
                # Control-plane outage, not a training failure: the workers
                # may even still be running. Restart from the latest committed
                # checkpoint WITHOUT burning the user's failure budget
                # (bounded so a permanently-broken fabric still surfaces).
                transient_restarts += 1
                continue
            failure_count += 1
            decision = self._failure_policy.make_decision(failure_count, error)
            if decision is FailureDecision.RAISE:
                return self._build_result(
                    error=TrainingFailedError(
                        f"training failed after {failure_count} failure(s); last error:\n{error}"
                    )
                )
            # else RESTART: loop re-creates the group from the latest checkpoint

    def _start_worker_group(self, attempt: int) -> WorkerGroup:
        import dataclasses

        # Copy: never mutate the caller's ScalingConfig (elastic attempts resize it).
        scaling = dataclasses.replace(
            self._scaling, num_workers=self._scaling_policy.world_size_for_attempt(attempt)
        )
        if attempt > 0:
            self._remove_orphan_checkpoints()
        group = WorkerGroup(scaling)
        try:
            group.start()
            if self._backend is not None:
                self._backend.on_start(group, self._backend_config)
            group.init_sessions(
                experiment_name=self._experiment_name,
                storage_path=self._storage_path,
                # Resume only from a COMMITTED checkpoint: a partial sharded
                # dir (crash mid-async-save) is never handed to a new attempt.
                latest_checkpoint=self._checkpoints.latest_committed,
                dataset_shards_per_worker=self._split_datasets(len(group)),
                trial_info=self._trial_info,
                report_index_offset=self._checkpoints.max_index,
            )
            if self._backend is not None:
                self._backend.on_training_start(group, self._backend_config)
            group.start_training(self._train_fn, self._train_fn_config)
        except BaseException:
            group.shutdown()
            raise
        return group

    def _remove_orphan_checkpoints(self):
        """Delete checkpoint_<n> dirs a dead attempt left behind.

        Two kinds of garbage: (1) dirs never registered (worker wrote files,
        group died before the controller polled the report) — the new attempt
        reuses those indices and must not merge into stale contents; compared
        against `highest_tracked_index` (-1 when nothing is tracked) so a dead
        FIRST attempt's checkpoint_0 is reaped too. (2) partial sharded saves —
        a sentinel but no MANIFEST.json means the commit never landed; those
        are garbage by definition even when tracked (the crash beat the
        async commit), so they are dropped from tracking and reaped."""
        import re
        import shutil

        from ray_tpu.checkpoint import is_partial

        self._checkpoints.drop_partials()
        exp_dir = os.path.join(self._storage_path, self._experiment_name)
        if not os.path.isdir(exp_dir):
            return
        highest = self._checkpoints.highest_tracked_index
        for entry in os.listdir(exp_dir):
            m = re.fullmatch(r"checkpoint_(\d+)", entry)
            if m is None:
                continue
            full = os.path.join(exp_dir, entry)
            if int(m.group(1)) > highest or is_partial(full):
                shutil.rmtree(full, ignore_errors=True)

    def _recover_committed_checkpoints(self):
        """Re-learn COMMITTED checkpoints from storage after a controller
        restart (the in-memory CheckpointManager died with the old process).

        Only committed dirs are registered — a partial sharded save (the crash
        beat its async commit) is garbage by definition and stays invisible,
        so the first attempt resumes from the newest state that actually
        persisted. Metrics are unknown ({}): retention scoring treats the
        recovered entries as worst-ranked, but the resume point is index-based
        and retention never deletes it."""
        import re

        from ray_tpu.checkpoint import is_partial
        from ray_tpu.train.checkpoint import Checkpoint

        exp_dir = os.path.join(self._storage_path, self._experiment_name)
        if not os.path.isdir(exp_dir):
            return
        recovered = 0
        for entry in sorted(os.listdir(exp_dir)):
            m = re.fullmatch(r"checkpoint_(\d+)", entry)
            if m is None:
                continue
            full = os.path.join(exp_dir, entry)
            if is_partial(full):
                continue
            self._checkpoints.register(
                int(m.group(1)), Checkpoint(full), {}, rank=0
            )
            recovered += 1
        if recovered:
            try:
                from ray_tpu.util.metrics import Counter

                Counter(
                    "controller_recoveries_total",
                    "control-plane recoveries from persisted state",
                    tag_keys=("plane",),
                ).inc(1.0, tags={"plane": "train"})
            except Exception:
                pass

    def _split_datasets(self, world_size: int) -> list[dict] | None:
        if not self._datasets:
            return None
        shards: list[dict] = [dict() for _ in range(world_size)]
        for name, ds in self._datasets.items():
            if hasattr(ds, "split"):
                parts = ds.split(world_size)
            else:
                parts = [ds] * world_size
            for rank in range(world_size):
                shards[rank][name] = parts[rank]
        return shards

    def _monitor(self, group: WorkerGroup) -> str | None:
        """Poll until every worker finishes or one errors. Returns error text or None.

        Transient control-plane unavailability (a GCS restart under a live
        run) must NOT read as worker death: the workers keep training on their
        raylets regardless. Poll failures that classify as transient are
        retried inside a grace window; only a window of CONSECUTIVE transient
        failures — or a definitive ActorDiedError — escapes to the failure
        policy."""
        from ray_tpu._private.config import CONFIG
        from ray_tpu.train._internal.failure_policy import is_transient_infra_error

        transient_deadline: float | None = None
        while True:
            try:
                statuses = group.poll()
            except Exception as e:
                if not is_transient_infra_error(e):
                    raise
                now = time.monotonic()
                if transient_deadline is None:
                    transient_deadline = now + 2.0 * CONFIG.gcs_rpc_timeout_s
                if now > transient_deadline:
                    import traceback as _tb

                    return "".join(_tb.format_exception(e))
                time.sleep(self._poll_interval_s)
                continue
            transient_deadline = None
            for status in statuses:
                for result in status.results:
                    self._ingest_result(result)
            errors = [s for s in statuses if s.state == "ERRORED"]
            if errors:
                return errors[0].error or "worker error"
            if all(s.state == "FINISHED" for s in statuses):
                return None
            time.sleep(self._poll_interval_s)

    def _ingest_result(self, result: dict):
        if result["rank"] == 0:
            self._latest_metrics = result["metrics"]
        if result.get("checkpoint") is not None:
            self._checkpoints.register(
                result["report_index"], result["checkpoint"], result["metrics"],
                rank=result["rank"],
            )
        flight = result.get("flight")
        if flight:
            # Aggregate each rank's per-step phase attribution so the final
            # Result can say where the run's wall time went without a live
            # worker to ask (docs/observability.md "compute plane").
            per_rank = self._flight_totals.setdefault(result["rank"], {})
            for key in ("data_wait_s", "step_compute_s",
                        "report_blocked_s", "checkpoint_blocked_s"):
                per_rank[key] = per_rank.get(key, 0.0) + flight.get(key, 0.0)
            self._flight_reports += 1

    def _build_result(self, error) -> Result:
        train_stats = None
        if self._flight_totals:
            train_stats = {
                "reports": self._flight_reports,
                "phases": {rank: dict(v)
                           for rank, v in sorted(self._flight_totals.items())},
            }
        return Result(
            metrics=self._latest_metrics,
            checkpoint=self._checkpoints.latest_committed,
            path=os.path.join(self._storage_path, self._experiment_name),
            error=error,
            best_checkpoints=self._checkpoints.best_checkpoints,
            train_stats=train_stats,
        )


class DetachedControllerRunner:
    """Actor hosting a TrainController so the run survives driver death.

    Reference: the v2 TrainController is spawned as a detached actor
    (data_parallel_trainer.py:268) and the driver merely polls it. Named actors
    in this runtime are not fate-shared with the driver, so the run continues if
    the driver disappears; a new driver re-attaches by run name.

    Name-reuse caveat: if a driver dies in the window between run completion and
    result harvest, the finished actor persists; the NEXT fit() with the same run
    name harvests that earlier run's Result (and frees the name) instead of
    training — run names identify experiments, reuse them only for re-attach.

    Restart recovery: the actor runs with max_restarts=-1 and writes a
    run-in-progress marker to GCS KV when the run starts. A restarted
    incarnation (its __init__ finds the marker) knows it is resuming an
    interrupted run — it re-learns committed checkpoints from storage and the
    next attempt continues from the newest one instead of from scratch. The
    marker is deleted when the driver harvests the Result.
    """

    KV_NS = "train_ctrl"

    def __init__(self, kwargs_blob: bytes, run_name: str = ""):
        import cloudpickle
        import threading

        self._run_name = run_name
        resume = False
        if run_name:
            try:
                import ray_tpu

                marker = ray_tpu.global_worker().gcs_kv_get(
                    self.KV_NS, self._marker_key()
                )
                resume = marker is not None
            except Exception:
                resume = False  # GCS briefly unreachable: treat as fresh
        self._controller = TrainController(
            **cloudpickle.loads(kwargs_blob), resume_from_storage=resume
        )
        self._result: Result | None = None
        self._run_error: str | None = None
        self._started = False
        self._start_lock = threading.Lock()
        self._done = threading.Event()

    def _marker_key(self) -> bytes:
        return f"run:{self._run_name}".encode()

    def start(self) -> bool:
        with self._start_lock:  # concurrent attachers must not double-start
            if self._started:
                return False  # already running (re-attach)
            self._started = True
        if self._run_name:
            try:
                import ray_tpu

                ray_tpu.global_worker().gcs_kv_put(
                    self.KV_NS, self._marker_key(), b"1"
                )
            except Exception:
                pass  # marker is best-effort: losing it only costs auto-resume
        import threading

        def run():
            try:
                self._result = self._controller.run()
            except BaseException:
                import traceback

                self._run_error = traceback.format_exc()
            finally:
                self._done.set()

        threading.Thread(target=run, daemon=True, name="train-controller").start()
        return True

    def clear_marker(self) -> bool:
        if self._run_name:
            try:
                import ray_tpu

                ray_tpu.global_worker().gcs_call(
                    "kv_del", self.KV_NS, self._marker_key()
                )
            except Exception:
                return False
        return True

    def is_done(self) -> bool:
        # Auto-start on a restarted incarnation: the original driver called
        # start() once and now only polls — without this, a controller that
        # died mid-run would sit idle forever after its restart.
        if not self._started:
            self.start()
        return self._done.is_set()

    def status(self) -> dict:
        """Run summary for the dashboard's train view (reference: the train
        dashboard module reads run state from the controller)."""
        import os

        c = self._controller
        return {
            "experiment_name": c._experiment_name,
            "pid": os.getpid(),  # chaos tests SIGKILL the controller by pid
            "started": self._started,
            "done": self._done.is_set(),
            "num_workers": getattr(c._scaling, "num_workers", None),
            "latest_metrics": c._latest_metrics,
            "storage_path": c._storage_path,
            "error_tail": (self._run_error or "")[-400:] or None,
        }

    def result_blob(self) -> bytes:
        import cloudpickle

        return cloudpickle.dumps((self._result, self._run_error))


def run_controller_detached(kwargs: dict, run_name: str, poll_interval_s: float = 0.5) -> Result:
    """Start (or re-attach to) a detached controller actor and block for its Result."""
    import cloudpickle

    import ray_tpu

    blob = cloudpickle.dumps(kwargs)
    runner_cls = ray_tpu.remote(num_cpus=0)(DetachedControllerRunner)
    actor = runner_cls.options(
        name=f"TRAIN_CONTROLLER:{run_name}",
        namespace="_train",
        get_if_exists=True,
        max_concurrency=8,
        # The run must survive the controller process: a SIGKILLed controller
        # restarts, detects its run-in-progress marker, and resumes from the
        # latest committed checkpoint (docs/fault_tolerance.md).
        max_restarts=-1,
    ).remote(blob, run_name)
    ray_tpu.get(actor.start.remote())
    from ray_tpu._private import rpc as _rpc

    while True:
        # Transient slowness (loaded node, GCS restart) must not abort the
        # poll: killing a live run over a slow reply — or over a control-plane
        # hiccup — would defeat detaching. A restarting controller resolves
        # through wait_actor_alive; only repeated hard failures escape.
        try:
            if ray_tpu.get(actor.is_done.remote(), timeout=60):
                break
        except (ray_tpu.exceptions.GetTimeoutError, _rpc.ConnectionLost,
                ray_tpu.exceptions.ActorUnavailableError):
            continue
        except ray_tpu.exceptions.ActorDiedError:
            # max_restarts=-1: a died-but-restartable controller surfaces here
            # only in the narrow window before the restart schedules. Give it
            # a beat and re-poll; a permanently dead actor (cluster teardown)
            # keeps raising and eventually surfaces via result_blob below.
            time.sleep(1.0)
            try:
                ray_tpu.get_actor(f"TRAIN_CONTROLLER:{run_name}", namespace="_train")
                continue
            except Exception:
                raise
        time.sleep(poll_interval_s)
    result, run_error = cloudpickle.loads(ray_tpu.get(actor.result_blob.remote()))
    # The run is complete and its Result is in hand: clear the resume marker
    # and release the actor so the name can be reused. A driver killed
    # mid-poll never reaches this, leaving the controller alive — that is the
    # point of detaching.
    try:
        ray_tpu.get(actor.clear_marker.remote(), timeout=15)
    except Exception:
        pass
    try:
        ray_tpu.kill(actor)
    except Exception:
        pass
    if result is None:
        raise TrainingFailedError(f"controller crashed:\n{run_error}")
    return result
