"""Failure and scaling policies for the train controller.

Design parity: reference `python/ray/train/v2/_internal/execution/failure_handling/
failure_policy.py:14` (FailurePolicy ABC, decisions RETRY/RAISE) with the default
max-failure counting policy (`default.py:24`), and `.../scaling_policy/` (fixed world
size now; the interface leaves room for elastic sizes).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass


class FailureDecision(enum.Enum):
    RESTART = "RESTART"
    RAISE = "RAISE"


class FailurePolicy:
    def make_decision(self, failure_count: int, error: str) -> FailureDecision:
        raise NotImplementedError


@dataclass
class DefaultFailurePolicy(FailurePolicy):
    max_failures: int = 0

    def make_decision(self, failure_count: int, error: str) -> FailureDecision:
        if self.max_failures < 0 or failure_count <= self.max_failures:
            return FailureDecision.RESTART
        return FailureDecision.RAISE


class ScalingPolicy:
    """Decides the world size for (re)starts. Fixed for now; elastic policies return a
    different size after failures (reference scaling_policy/)."""

    def __init__(self, scaling_config):
        self.scaling_config = scaling_config

    def world_size_for_attempt(self, attempt: int) -> int:
        return self.scaling_config.num_workers
