"""Failure and scaling policies for the train controller.

Design parity: reference `python/ray/train/v2/_internal/execution/failure_handling/
failure_policy.py:14` (FailurePolicy ABC, decisions RETRY/RAISE) with the default
max-failure counting policy (`default.py:24`), and `.../scaling_policy/` (fixed world
size now; the interface leaves room for elastic sizes).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass


class FailureDecision(enum.Enum):
    RESTART = "RESTART"
    RAISE = "RAISE"


# Error shapes that mean the CONTROL PLANE (GCS / RPC fabric) hiccuped, not
# that training failed: worker processes keep running through a GCS restart,
# so the monitor must ride these out instead of declaring the gang dead and
# burning the user's failure budget (reference: GCS clients buffer+retry
# through GCS downtime; workers are only dead when their raylet says so).
_TRANSIENT_MARKERS = (
    "ConnectionLost",
    "GetTimeoutError",
    "ActorUnavailableError",
    "gcs unavailable",
    "connection lost",
)


def is_transient_infra_error(error) -> bool:
    """True when an exception (or formatted error text) looks like transient
    control-plane unavailability rather than a real training/worker failure.
    ActorDiedError is explicitly NOT transient: the raylet confirmed death."""
    if isinstance(error, BaseException):
        from ray_tpu.exceptions import ActorDiedError, GetTimeoutError

        if isinstance(error, ActorDiedError):
            return False
        if isinstance(error, GetTimeoutError):
            return True
        try:
            from ray_tpu._private import rpc

            if isinstance(error, rpc.ConnectionLost):
                return True
        except Exception:
            pass
        error = f"{type(error).__name__}: {error}"
    text = str(error)
    if "ActorDiedError" in text:
        return False
    return any(marker in text for marker in _TRANSIENT_MARKERS)


class FailurePolicy:
    def make_decision(self, failure_count: int, error: str) -> FailureDecision:
        raise NotImplementedError


@dataclass
class DefaultFailurePolicy(FailurePolicy):
    max_failures: int = 0

    def make_decision(self, failure_count: int, error: str) -> FailureDecision:
        if self.max_failures < 0 or failure_count <= self.max_failures:
            return FailureDecision.RESTART
        return FailureDecision.RAISE


class ScalingPolicy:
    """Decides the world size for (re)starts (reference scaling_policy/):
    the fixed policy always returns the configured size."""

    def __init__(self, scaling_config):
        self.scaling_config = scaling_config

    def world_size_for_attempt(self, attempt: int) -> int:
        return self.scaling_config.num_workers


class ElasticScalingPolicy(ScalingPolicy):
    """Resize the world at restart to what the cluster can actually place.

    Reference: python/ray/train/v2/_internal/execution/scaling_policy/ — a
    lost node means the next attempt continues at reduced size (bounded below
    by min_workers) from the latest checkpoint; when capacity returns, a later
    restart scales back toward the configured size. Feasibility is computed
    from the live per-node available-resource view, packing worker bundles
    greedily the way the placement group will.
    """

    def __init__(self, scaling_config, min_workers: int):
        super().__init__(scaling_config)
        self.min_workers = max(1, int(min_workers))

    def world_size_for_attempt(self, attempt: int) -> int:
        target = self.scaling_config.num_workers
        if attempt == 0:
            return target
        import ray_tpu

        demand = self.scaling_config._resources_per_worker_not_none
        feasible = 0
        try:
            view = ray_tpu.nodes()
        except Exception:
            return target
        for node in view:
            if not node.get("alive"):
                continue
            # CAPACITY of live nodes, not instantaneous availability: the dead
            # attempt's placement group may not have released its bundles yet,
            # and elasticity is about cluster membership, not transient load.
            total = dict(node.get("resources_total") or {})
            while feasible < target and all(
                total.get(r, 0.0) + 1e-9 >= amt for r, amt in demand.items()
            ):
                for r, amt in demand.items():
                    total[r] = total.get(r, 0.0) - amt
                feasible += 1
            if feasible >= target:
                break
        return max(self.min_workers, min(target, feasible))
