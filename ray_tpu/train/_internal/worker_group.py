"""WorkerGroup: the gang of training worker actors behind a trainer.

Design parity: reference `python/ray/train/v2/_internal/execution/worker_group/
worker_group.py:104` — creates a placement group from the ScalingConfig, spawns one
`RayTrainWorker` actor per bundle, assigns world/local/node ranks (sorted by node so
local ranks are contiguous), runs backend hooks, and launches the user train loop in a
background thread per worker (reference thread_runner.py) so health polling stays live.
"""

from __future__ import annotations

import threading
import traceback
from dataclasses import dataclass
from typing import Any, Callable, Optional

import ray_tpu
from ray_tpu.train import context as train_ctx
from ray_tpu.util.placement_group import placement_group, remove_placement_group


class RayTrainWorker:
    """Actor hosting one training worker. The user loop runs in a daemon thread so the
    actor stays responsive to poll()/execute() (max_concurrency stays 1: methods are
    serialized, but none of them block on the training thread)."""

    def __init__(self):
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[str] = None
        self._finished = False

    def get_metadata(self) -> dict:
        import os

        worker = ray_tpu._private.worker.global_worker()
        return {"node_id": worker.node_id.hex(), "pid": os.getpid()}

    def init_session(self, **kwargs):
        train_ctx.init_session(**kwargs)
        return True

    def _drain_checkpoints(self):
        """On clean train-fn exit, block until every async sharded save this
        worker enqueued is persisted (rank 0: committed). A failed background
        save fails the run — a FINISHED run's last checkpoint is committed."""
        session = train_ctx.get_session()
        if session is not None:
            session.wait_for_checkpoints()

    def execute(self, fn: Callable, *args, **kwargs):
        """Run an arbitrary function in the worker process (backend hooks etc.)."""
        return fn(*args, **kwargs)

    def start_train_fn(self, train_fn: Callable, config: dict | None):
        def run():
            clean = False
            try:
                import inspect

                sig = inspect.signature(train_fn)
                if len(sig.parameters) == 0:
                    train_fn()
                else:
                    train_fn(config or {})
                clean = True
            except SystemExit:
                clean = True
            except BaseException:
                self._error = traceback.format_exc()
            if clean:
                # Errored exits skip the drain: their partial saves stay
                # uncommitted on purpose (restore ignores them, cleanup reaps).
                try:
                    self._drain_checkpoints()
                except BaseException:
                    self._error = traceback.format_exc()
            self._finished = True

        self._finished = False
        self._error = None
        self._thread = threading.Thread(target=run, daemon=True, name="train-fn")
        self._thread.start()
        return True

    def poll(self) -> dict:
        session = train_ctx.get_session()
        results = []
        if session is not None:
            while not session.result_queue.empty():
                results.append(session.result_queue.get_nowait())
        state = "RUNNING"
        if self._finished:
            state = "ERRORED" if self._error else "FINISHED"
        return {"state": state, "results": results, "error": self._error}

    def request_stop(self):
        session = train_ctx.get_session()
        if session is not None:
            session.stop_event.set()
        return True

    def train_stats(self) -> Optional[dict]:
        """Report path: this worker's per-step flight totals + recorder
        ring + program/memory reports (docs/observability.md)."""
        return train_ctx.train_stats()

    def capture_profile(self, duration_s: float = 3.0,
                        log_dir: Optional[str] = None) -> dict:
        """On-demand profiler capture on this worker (the fleet surface
        `util.state.capture_profile` fans out to): blocks the actor — not
        the training thread — for duration_s and returns the trace
        artifacts inline."""
        from ray_tpu.util import xprof

        return xprof.capture(duration_s, log_dir)

    def shutdown(self):
        train_ctx.shutdown_session()
        return True


@dataclass
class WorkerStatus:
    rank: int
    state: str
    results: list
    error: Optional[str]


class WorkerGroup:
    def __init__(self, scaling_config):
        self._scaling = scaling_config
        self._pg = None
        self._workers: list = []
        self._sync_actor = None
        self._metadata: list[dict] = []

    # ------------------------------------------------------------------ lifecycle

    def start(self, pg_timeout: float = 120.0):
        from ray_tpu.train._internal.sync_actor import SynchronizationActor

        bundles = self._scaling.bundles()
        self._pg = placement_group(bundles, strategy=self._scaling.pg_strategy)
        try:
            if not self._pg.ready(timeout=pg_timeout):
                raise TimeoutError(
                    f"placement group for {len(bundles)} training workers "
                    f"({bundles[0]}) not ready within {pg_timeout}s"
                )
            self._sync_actor = (
                ray_tpu.remote(SynchronizationActor).options(num_cpus=0).remote()
            )
            worker_cls = ray_tpu.remote(RayTrainWorker)
            self._workers = []
            for i, bundle in enumerate(bundles):
                opts = {k: v for k, v in bundle.items() if k not in ("CPU", "TPU")}
                self._workers.append(
                    worker_cls.options(
                        num_cpus=bundle.get("CPU", 0),
                        num_tpus=bundle.get("TPU", 0),
                        resources=opts or None,
                        placement_group=self._pg,
                        placement_group_bundle_index=i,
                    ).remote()
                )
            self._metadata = ray_tpu.get(
                [w.get_metadata.remote() for w in self._workers], timeout=60.0
            )
            self._assign_ranks()
        except BaseException:
            self.shutdown()
            raise

    def _assign_ranks(self):
        """Sort workers by node so world ranks are contiguous per host, with bundle 0's
        node (the slice head when a topology bundle pinned it) ordered first — so world
        rank 0 is on the head node and rank = f(node_rank, local_rank) stays consistent."""
        head_node = self._metadata[0]["node_id"]
        order = sorted(
            range(len(self._workers)),
            key=lambda i: (self._metadata[i]["node_id"] != head_node,
                           self._metadata[i]["node_id"], i),
        )
        self._rank_of = {idx: rank for rank, idx in enumerate(order)}
        node_ids = []
        for i in order:
            nid = self._metadata[i]["node_id"]
            if nid not in node_ids:
                node_ids.append(nid)
        self._node_rank_of = {
            i: node_ids.index(self._metadata[i]["node_id"]) for i in range(len(self._workers))
        }
        local_counter: dict[str, int] = {}
        self._local_rank_of = {}
        for i in order:
            nid = self._metadata[i]["node_id"]
            self._local_rank_of[i] = local_counter.get(nid, 0)
            local_counter[nid] = self._local_rank_of[i] + 1
        self._local_world = {
            nid: local_counter[nid] for nid in local_counter
        }

    def init_sessions(
        self,
        *,
        experiment_name: str,
        storage_path: str,
        latest_checkpoint=None,
        dataset_shards_per_worker: list[dict] | None = None,
        trial_info: dict | None = None,
        report_index_offset: int = 0,
    ):
        calls = []
        for i, w in enumerate(self._workers):
            rank = self._rank_of[i]
            shards = (
                dataset_shards_per_worker[rank]
                if dataset_shards_per_worker is not None
                else None
            )
            calls.append(
                w.init_session.remote(
                    world_size=len(self._workers),
                    world_rank=rank,
                    local_rank=self._local_rank_of[i],
                    local_world_size=self._local_world[self._metadata[i]["node_id"]],
                    node_rank=self._node_rank_of[i],
                    experiment_name=experiment_name,
                    storage_path=storage_path,
                    sync_actor=self._sync_actor,
                    latest_checkpoint=latest_checkpoint,
                    dataset_shards=shards,
                    trial_info=trial_info,
                    report_index_offset=report_index_offset,
                )
            )
        ray_tpu.get(calls, timeout=60.0)

    # ------------------------------------------------------------------ ops

    def __len__(self):
        return len(self._workers)

    @property
    def sorted_workers(self) -> list:
        """Workers in world-rank order."""
        by_rank = sorted(range(len(self._workers)), key=lambda i: self._rank_of[i])
        return [self._workers[i] for i in by_rank]

    def execute(self, fn: Callable, *args, **kwargs) -> list:
        """Run fn on every worker (world-rank order), blocking."""
        return ray_tpu.get(
            [w.execute.remote(fn, *args, **kwargs) for w in self.sorted_workers],
            timeout=300.0,
        )

    def execute_single(self, rank: int, fn: Callable, *args, **kwargs) -> Any:
        return ray_tpu.get(
            self.sorted_workers[rank].execute.remote(fn, *args, **kwargs), timeout=300.0
        )

    def start_training(self, train_fn: Callable, config: dict | None):
        ray_tpu.get(
            [w.start_train_fn.remote(train_fn, config) for w in self.sorted_workers],
            timeout=60.0,
        )

    def poll(self, timeout_s: float = 60.0) -> list[WorkerStatus]:
        """One health/result sweep over the gang.

        Error contract for the controller's monitor loop: a raised
        GetTimeoutError / ConnectionLost here means the CONTROL PLANE is slow
        or down (workers submit over direct connections and keep training
        through a GCS restart) and is retried under a grace window; an
        ActorDiedError means a worker's raylet confirmed its death and routes
        to the failure policy immediately."""
        out = []
        replies = ray_tpu.get(
            [w.poll.remote() for w in self.sorted_workers], timeout=timeout_s
        )
        for rank, r in enumerate(replies):
            out.append(WorkerStatus(rank, r["state"], r["results"], r["error"]))
        return out

    def train_stats(self, timeout_s: float = 60.0) -> list:
        """Per-worker train_stats() in world-rank order (report path)."""
        return ray_tpu.get(
            [w.train_stats.remote() for w in self.sorted_workers],
            timeout=timeout_s,
        )

    def shutdown(self):
        try:
            for w in self._workers:
                try:
                    w.shutdown.remote()  # raylint: disable=RL501 (best-effort graceful stop; kill() follows)
                except Exception:
                    pass
            for w in self._workers:
                try:
                    ray_tpu.kill(w)
                except Exception:
                    pass
            if self._sync_actor is not None:
                try:
                    ray_tpu.kill(self._sync_actor)
                except Exception:
                    pass
        finally:
            self._workers = []
            self._sync_actor = None
            if self._pg is not None:
                try:
                    remove_placement_group(self._pg)
                except Exception:
                    pass
                self._pg = None
