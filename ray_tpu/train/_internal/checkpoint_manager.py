"""CheckpointManager: tracks reported checkpoints, retention, and the best/latest.

Design parity: reference `python/ray/train/v2/_internal/execution/checkpoint/
checkpoint_manager.py` — dedupes per report (all ranks persist into the same directory),
enforces CheckpointConfig.num_to_keep scored by checkpoint_score_attribute.

Committed-vs-partial: sharded saves (ray_tpu.checkpoint) commit atomically via
their manifest. The manager tracks every reported checkpoint, but resume flows
through `latest_committed` — a tracked directory whose async commit never
landed (worker died mid-save) is never handed back to a restarted attempt.
"""

from __future__ import annotations

import shutil

from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import CheckpointConfig


class _Tracked:
    def __init__(self, checkpoint: Checkpoint, metrics: dict, index: int):
        self.checkpoint = checkpoint
        self.metrics = metrics
        self.index = index


class CheckpointManager:
    def __init__(self, config: CheckpointConfig):
        self._config = config
        self._tracked: dict[int, _Tracked] = {}  # report_index -> entry

    def register(self, report_index: int, checkpoint: Checkpoint, metrics: dict,
                 rank: int = 0):
        existing = self._tracked.get(report_index)
        if existing is not None:
            # Another rank reporting the same round (same directory). Scoring must be
            # deterministic: rank 0's metrics win regardless of arrival order.
            if rank == 0:
                existing.metrics = metrics
                self._enforce_retention()
            return
        self._tracked[report_index] = _Tracked(checkpoint, metrics, report_index)
        self._enforce_retention()

    def _score(self, t: _Tracked):
        attr = self._config.checkpoint_score_attribute
        if attr is None:
            return t.index
        value = t.metrics.get(attr)
        if value is None:
            # Metric missing from this report: rank it worst rather than mixing the
            # raw index into the metric's scale (which would pin it as "best").
            return float("-inf")
        return value if self._config.checkpoint_score_order == "max" else -value

    def _enforce_retention(self):
        keep = self._config.num_to_keep
        if keep is None or len(self._tracked) <= keep:
            return
        entries = sorted(self._tracked.values(), key=self._score, reverse=True)
        # Never delete a resume point: the latest (it may still be committing
        # asynchronously) and the latest COMMITTED one both survive scoring.
        protected = {
            c.path for c in (self.latest, self.latest_committed) if c is not None
        }
        for victim in entries[keep:]:
            if victim.checkpoint.path in protected:
                continue
            self._tracked.pop(victim.index, None)
            shutil.rmtree(victim.checkpoint.path, ignore_errors=True)

    @property
    def max_index(self) -> int:
        """Highest report index seen — restart attempts resume numbering above it."""
        return max(self._tracked, default=0)

    @property
    def highest_tracked_index(self) -> int:
        """Highest report index actually TRACKED, or -1 when nothing is.

        Distinct from `max_index` (which floors at 0 for the numbering offset):
        orphan cleanup compares against this, so a dead first attempt's
        `checkpoint_0` dir — index 0, nothing tracked — is reaped rather than
        surviving the `0 > 0` comparison."""
        return max(self._tracked, default=-1)

    @property
    def latest(self) -> Checkpoint | None:
        if not self._tracked:
            return None
        return self._tracked[max(self._tracked)].checkpoint

    @property
    def latest_committed(self) -> Checkpoint | None:
        """Newest checkpoint that is safe to resume from: committed sharded
        save, or a plain directory checkpoint. Partial (manifest-less sharded)
        dirs are garbage by definition and never returned."""
        from ray_tpu.checkpoint import is_partial

        for index in sorted(self._tracked, reverse=True):
            ckpt = self._tracked[index].checkpoint
            if not is_partial(ckpt.path):
                return ckpt
        return None

    def drop_partials(self) -> list[str]:
        """Untrack and delete tracked-but-uncommitted sharded dirs (a crash
        beat their async commit). Returns the reaped paths."""
        from ray_tpu.checkpoint import is_partial

        reaped = []
        for index in list(self._tracked):
            path = self._tracked[index].checkpoint.path
            if is_partial(path):
                self._tracked.pop(index, None)
                shutil.rmtree(path, ignore_errors=True)
                reaped.append(path)
        return reaped

    @property
    def best(self) -> Checkpoint | None:
        if not self._tracked:
            return None
        return max(self._tracked.values(), key=self._score).checkpoint

    @property
    def best_checkpoints(self) -> list[tuple[Checkpoint, dict]]:
        return [
            (t.checkpoint, t.metrics)
            for t in sorted(self._tracked.values(), key=self._score, reverse=True)
        ]
