"""SynchronizationActor: barrier + rank-0 broadcast for a training worker gang.

Design parity: reference `python/ray/train/v2/_internal/execution/checkpoint/sync_actor.py`
(SynchronizationActor) backing `ray.train.collective.barrier`/`broadcast_from_rank_zero`
(reference train/collective/collectives.py:14,56). Async actor: calls park on asyncio
events rather than blocking threads. Rounds are garbage-collected once the last waiter
leaves, so memory stays flat over arbitrarily long runs.
"""

from __future__ import annotations

import asyncio


class SynchronizationActor:
    def __init__(self):
        self._rounds: dict[str, dict] = {}
        self._lock = asyncio.Lock()

    def _round(self, key: str) -> dict:
        if key not in self._rounds:
            self._rounds[key] = {"count": 0, "event": asyncio.Event(), "data": None}
        return self._rounds[key]

    async def _arrive(self, key: str, world_size: int) -> dict:
        async with self._lock:
            r = self._round(key)
            r["count"] += 1
            if r["count"] >= world_size:
                r["event"].set()
        return r

    async def _leave(self, key: str, world_size: int):
        async with self._lock:
            r = self._rounds.get(key)
            if r is not None:
                r["left"] = r.get("left", 0) + 1
                if r["left"] >= world_size:
                    del self._rounds[key]

    async def barrier(self, world_size: int, key: str) -> bool:
        r = await self._arrive(key, world_size)
        await r["event"].wait()
        await self._leave(key, world_size)
        return True

    async def broadcast(self, world_size: int, key: str, rank: int, value=None):
        """All workers call; the rank-0 value is returned to everyone."""
        async with self._lock:
            r = self._round(key)
            if rank == 0:
                r["data"] = value
            r["count"] += 1
            if r["count"] >= world_size:
                r["event"].set()
        await r["event"].wait()
        data = r["data"]
        await self._leave(key, world_size)
        return data

    async def reset(self):
        self._rounds.clear()
        return True
