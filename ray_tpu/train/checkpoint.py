"""Checkpoint: a directory-of-files abstraction.

Design parity: reference `python/ray/train/_checkpoint.py` — Checkpoint.from_directory /
to_directory / as_directory over a filesystem path. Orbax/msgpack-friendly: the directory
contents are opaque to the framework; JAX users typically put an orbax or
`flax.serialization` blob inside.
"""

from __future__ import annotations

import contextlib
import os
import shutil
import tempfile
import uuid


class Checkpoint:
    """A reference to a directory tree persisted under the run storage path."""

    def __init__(self, path: str):
        self.path = os.path.abspath(os.path.expanduser(path))

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    def to_directory(self, path: str | None = None) -> str:
        """Copy checkpoint contents into `path` (or a fresh temp dir) and return it."""
        target = path or os.path.join(
            tempfile.gettempdir(), f"rtpu_ckpt_{uuid.uuid4().hex[:8]}"
        )
        if os.path.abspath(target) != self.path:
            shutil.copytree(self.path, target, dirs_exist_ok=True)
        return target

    @contextlib.contextmanager
    def as_directory(self):
        """Context manager yielding a local directory with the checkpoint contents.

        Local-filesystem storage means no copy is needed; yield the path directly.
        """
        yield self.path

    def __repr__(self):
        return f"Checkpoint(path={self.path!r})"

    def __eq__(self, other):
        return isinstance(other, Checkpoint) and other.path == self.path

    def __reduce__(self):
        return (Checkpoint, (self.path,))
