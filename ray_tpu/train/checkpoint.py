"""Checkpoint: a directory-of-files abstraction.

Design parity: reference `python/ray/train/_checkpoint.py` — Checkpoint.from_directory /
to_directory / as_directory over a filesystem path. The directory contents are
opaque to the framework EXCEPT for the sharded format (`ray_tpu.checkpoint`,
marked by its sentinel/manifest files): those directories are committed
atomically and restore through `to_pytree` with elastic resharding.
"""

from __future__ import annotations

import contextlib
import os
import shutil
import tempfile
import uuid


class Checkpoint:
    """A reference to a directory tree persisted under the run storage path."""

    def __init__(self, path: str):
        self.path = os.path.abspath(os.path.expanduser(path))

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    def to_directory(self, path: str | None = None) -> str:
        """Copy checkpoint contents into `path` (or a fresh temp dir) and return it.

        The target is CLEARED first: restoring over a non-empty directory must
        not let stale files from a previous restore survive into the "restored"
        tree (they would silently mix two checkpoints' state).
        """
        target = path or os.path.join(
            tempfile.gettempdir(), f"rtpu_ckpt_{uuid.uuid4().hex[:8]}"
        )
        if os.path.abspath(target) != self.path:
            if os.path.isdir(target):
                shutil.rmtree(target)
            shutil.copytree(self.path, target)
        return target

    @contextlib.contextmanager
    def as_directory(self):
        """Context manager yielding a local directory with the checkpoint contents.

        Local-filesystem storage means no copy is needed; yield the path directly.
        """
        yield self.path

    # ---------------------------------------------------------------- sharded

    @property
    def is_sharded(self) -> bool:
        """True when this directory holds (or was targeted by) a sharded save."""
        from ray_tpu.checkpoint import is_sharded

        return is_sharded(self.path)

    @property
    def is_committed(self) -> bool:
        """True when this checkpoint is safe to restore from: a committed
        sharded save, or a plain (non-sharded) directory checkpoint."""
        from ray_tpu.checkpoint import is_partial

        return not is_partial(self.path)

    def to_pytree(self, *, shardings=None, mesh=None):
        """Restore a sharded checkpoint as a pytree — host numpy by default,
        or redistributed onto the current mesh via ``shardings``/``mesh``
        (see ray_tpu.checkpoint.restore). Raises for non-sharded or
        uncommitted directories."""
        from ray_tpu.checkpoint import restore

        return restore(self.path, shardings=shardings, mesh=mesh)

    def __repr__(self):
        return f"Checkpoint(path={self.path!r})"

    def __eq__(self, other):
        return isinstance(other, Checkpoint) and other.path == self.path

    def __reduce__(self):
        return (Checkpoint, (self.path,))
