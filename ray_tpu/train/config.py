"""Shared Train/Tune configuration dataclasses.

Design parity: reference `python/ray/air/config.py` (ScalingConfig/RunConfig/
FailureConfig/CheckpointConfig) and `python/ray/train/v2/api/config.py`. TPU-first
divergence: `ScalingConfig` speaks TPU — `use_tpu` + `topology` (e.g. "v4-16") reserve a
whole slice via the slice-head resource (reference tpu.py:131-197 precedent), one SPMD
worker per host.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Optional


@dataclass
class ScalingConfig:
    """How many training workers, and what each one needs.

    On TPU: one worker per *host* (each host owns all its chips — the SPMD model),
    so ``num_workers`` counts hosts, and ``topology`` ("v4-16", "v5e-64", ...) can be
    given instead to derive the host count and gang-reserve the slice atomically.
    """

    num_workers: Optional[int] = None
    use_tpu: bool = False
    topology: Optional[str] = None  # e.g. "v4-16": reserve one whole slice
    # Multi-slice (DCN) training: gang-reserve this many whole slices of the
    # topology; workers = hosts_per_slice * num_slices, and the training loop
    # typically maps a dp axis across slices via create_mesh(dcn_axes=...)
    # (reference precedent: python/ray/_private/accelerators/tpu.py:482-547
    # multi-slice gang scheduling).
    num_slices: int = 1
    resources_per_worker: Optional[dict] = None
    placement_strategy: str = "PACK"
    chips_per_host: int = 4
    # Elastic training: restarts may resize the world down to min_workers when
    # capacity is lost and back up when it returns (reference:
    # train/v2/_internal/execution/scaling_policy/). None = fixed size.
    min_workers: Optional[int] = None

    def __post_init__(self):
        if self.num_workers is None and self.topology is None:
            self.num_workers = 1
        if (
            self.min_workers is not None
            and self.num_workers is not None
            and self.min_workers > self.num_workers
        ):
            raise ValueError(
                f"min_workers ({self.min_workers}) must be <= num_workers "
                f"({self.num_workers})"
            )
        if self.num_slices < 1:
            raise ValueError(f"num_slices must be >= 1, got {self.num_slices}")
        if self.num_slices > 1 and self.topology is None:
            raise ValueError("num_slices > 1 requires a topology")
        self._workers_explicit = self.num_workers is not None
        if self.topology is not None:
            # "v4-16" -> 16 cores -> hosts = cores / (2 cores-per-chip * chips-per-host)
            # Keep the simple public convention: N in vX-N counts chips for v5e/v6e and
            # cores (2/chip) for v4/v5p. Hosts = chips / chips_per_host.
            gen, _, n = self.topology.partition("-")
            n = int(n)
            chips = n if gen in ("v5e", "v5litepod", "v6e") else n // 2
            hosts = max(1, chips // self.chips_per_host)
            self.hosts_per_slice = hosts
            if self.num_workers is None:
                self.num_workers = hosts * self.num_slices
            elif self.num_slices > 1 and self.num_workers != hosts * self.num_slices:
                # Silently under-provisioning head bundles would reserve fewer
                # slices than configured.
                raise ValueError(
                    f"num_workers ({self.num_workers}) must equal "
                    f"hosts_per_slice ({hosts}) * num_slices ({self.num_slices}) "
                    "for a multi-slice gang"
                )
            self.use_tpu = True

    @property
    def _resources_per_worker_not_none(self) -> dict:
        if self.resources_per_worker is not None:
            resources = dict(self.resources_per_worker)
        elif self.use_tpu:
            resources = {"CPU": 1, "TPU": float(self.chips_per_host)}
        else:
            resources = {"CPU": 1}
        return {k: float(v) for k, v in resources.items() if v}

    def bundles(self) -> list[dict]:
        """Placement-group bundles for the worker gang. With a topology, the
        first bundle of EACH slice's host block claims the slice-head resource
        (advertised once per slice, on TPU_WORKER_ID==0), so k slices are
        reserved atomically and no two head bundles can land on one slice."""
        per = self._resources_per_worker_not_none
        bundles = [dict(per) for _ in range(self.num_workers)]
        if self.topology:
            # __post_init__ validated num_workers == hosts * num_slices for
            # k > 1, so every head index is in range.
            hosts = getattr(self, "hosts_per_slice", self.num_workers)
            for s in range(self.num_slices):
                bundles[s * hosts][f"TPU-{self.topology}-head"] = 1.0
        return bundles

    @property
    def pg_strategy(self) -> str:
        if self.use_tpu:
            return "SPREAD"  # one SPMD worker per host
        return self.placement_strategy


@dataclass
class FailureConfig:
    """Parity: reference air/config.py FailureConfig (max_failures) — how many worker
    group failures to tolerate by restarting from the latest checkpoint.
    -1 means retry forever."""

    max_failures: int = 0


@dataclass
class CheckpointConfig:
    """Parity: reference air/config.py CheckpointConfig."""

    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"

    def __post_init__(self):
        if self.checkpoint_score_order not in ("max", "min"):
            raise ValueError("checkpoint_score_order must be 'max' or 'min'")


@dataclass
class RunConfig:
    """Parity: reference air/config.py RunConfig."""

    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = field(default_factory=CheckpointConfig)
    verbose: int = 0
    # Tune: stop condition — {"metric": threshold} (stop when reached) or
    # callable(trial_id, result) -> bool. Parity: air RunConfig.stop.
    stop: Optional[Any] = None
    # Run the controller as a detached named actor so the run survives driver
    # death (reference: v2 TrainController detached actor). None = auto: detach
    # when fit() is called from a driver, run in-process when already inside an
    # actor/worker (e.g. a Tune trial, which is driver-independent anyway).
    detach_controller: Optional[bool] = None

    def __post_init__(self):
        if self.storage_path is None:
            self.storage_path = os.path.expanduser(
                os.environ.get("RAY_TPU_STORAGE_PATH", "~/ray_tpu_results")
            )


@dataclass
class Result:
    """Parity: reference python/ray/air/result.py Result."""

    metrics: Optional[dict] = None
    checkpoint: Optional[Any] = None
    path: Optional[str] = None
    error: Optional[BaseException] = None
    metrics_dataframe: Optional[Any] = None
    best_checkpoints: list = field(default_factory=list)
    # Per-step flight attribution aggregated over the run (docs/
    # observability.md "compute plane"): {"reports", "phases": {rank:
    # {data_wait_s, step_compute_s, report_blocked_s,
    # checkpoint_blocked_s}}} — where a slow run's wall time went.
    train_stats: Optional[dict] = None

    @property
    def config(self) -> Optional[dict]:
        return (self.metrics or {}).get("config")
