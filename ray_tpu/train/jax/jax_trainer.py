"""JaxTrainer: the flagship SPMD trainer — one worker per TPU host of a slice.

Design parity: reference `python/ray/train/v2/jax/jax_trainer.py:19` (JaxTrainer) and
`v2/jax/config.py:16,38-58` (JaxConfig/_JaxBackend calling jax.distributed.initialize on
each worker). TPU-first: workers are hosts (all local chips per process); the backend
rendezvous wires `jax.distributed.initialize(coordinator, num_processes, process_id)` so
in-graph XLA collectives ride ICI within the slice and DCN across slices. Inside the
loop, users build a global mesh via `ray_tpu.parallel.mesh.create_mesh` and pjit —
the framework only does control plane, matching the reference's division of labor.
"""

from __future__ import annotations

import socket
from dataclasses import dataclass
from typing import Callable, Optional

from ray_tpu.train.backend import Backend, BackendConfig
from ray_tpu.train.config import RunConfig, ScalingConfig
from ray_tpu.train.data_parallel_trainer import DataParallelTrainer


@dataclass
class JaxConfig(BackendConfig):
    """Parity: reference v2/jax/config.py JaxConfig."""

    coordinator_port: int = 0  # 0: pick a free port on the rank-0 host
    distributed: Optional[bool] = None  # None: auto (world_size > 1 and TPU present)

    def backend_cls(self):
        return _JaxBackend


def _find_free_port() -> int:
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _host_ip() -> str:
    """The host's outbound-route IP (gethostbyname(hostname) resolves to loopback on
    Debian-style /etc/hosts, which would advertise an unreachable coordinator)."""
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("8.8.8.8", 80))  # no packets sent; just picks a route
            return s.getsockname()[0]
    except OSError:
        return socket.gethostbyname(socket.gethostname())


def _rendezvous_info(port_hint: int) -> tuple[str, int]:
    port = port_hint or _find_free_port()
    return _host_ip(), port


def _setup_jax_distributed(coordinator: str, num_processes: int, process_id: int):
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    return len(jax.devices())


def _has_local_tpu() -> bool:
    import os

    return bool(os.environ.get("TPU_ACCELERATOR_TYPE") or os.environ.get("TPU_NAME"))


class _JaxBackend(Backend):
    def on_training_start(self, worker_group, backend_config: JaxConfig):
        n = len(worker_group)
        distributed = backend_config.distributed
        if distributed is None:
            # Single-process JAX needs no coordinator; multi-host SPMD does. Only
            # auto-enable on real TPU hosts — CPU test gangs share one machine where
            # concurrent jax.distributed runtimes would fight over devices.
            distributed = n > 1 and worker_group.execute_single(0, _has_local_tpu)
        if not distributed:
            return
        host, port = worker_group.execute_single(
            0, _rendezvous_info, backend_config.coordinator_port
        )
        coordinator = f"{host}:{port}"
        import ray_tpu

        calls = [
            w.execute.remote(_setup_jax_distributed, coordinator, n, rank)
            for rank, w in enumerate(worker_group.sorted_workers)
        ]
        ray_tpu.get(calls, timeout=300.0)


class JaxTrainer(DataParallelTrainer):
    """SPMD training over a TPU slice (or CPU gang in tests).

    Example (with elastic sharded checkpointing — docs/checkpoint.md)::

        def loop(config):
            from ray_tpu import checkpoint as ckpt

            mesh = mesh_lib.create_mesh({"dp": -1})
            state = ...init...
            prev = ray_tpu.train.get_checkpoint()
            if prev is not None and prev.is_sharded:
                # Elastic resume: redistributes the saved shards onto THIS
                # attempt's mesh, whatever world size it came up at.
                state = prev.to_pytree(shardings=my_shardings(mesh))
            for step in ...:
                ...pjit train steps...
                # Each host persists only its addressable shards; the write
                # runs async behind one batched device->host snapshot.
                ray_tpu.train.report({"loss": ...},
                                     checkpoint=ckpt.ShardedState(state))

        JaxTrainer(loop, scaling_config=ScalingConfig(topology="v4-16")).fit()
    """

    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: Optional[dict] = None,
        jax_config: Optional[JaxConfig] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        datasets: Optional[dict] = None,
        num_slices: Optional[int] = None,
    ):
        if num_slices is not None:
            # Multi-slice convenience: gang-reserve k slices of the configured
            # topology; the loop maps dp across slices via
            # create_mesh(dcn_axes={"dp": k}).
            if scaling_config is None:
                raise ValueError("num_slices requires a scaling_config with a topology")
            from dataclasses import replace

            # An explicitly-set worker count is honored (and validated against
            # hosts_per_slice * num_slices in ScalingConfig); a derived one is
            # recomputed for the new slice count.
            explicit = getattr(scaling_config, "_workers_explicit", False)
            scaling_config = replace(
                scaling_config,
                num_slices=num_slices,
                num_workers=scaling_config.num_workers if explicit else None,
            )
        super().__init__(
            train_loop_per_worker,
            train_loop_config=train_loop_config,
            scaling_config=scaling_config or ScalingConfig(num_workers=1, use_tpu=True),
            run_config=run_config,
            backend_config=jax_config or JaxConfig(),
            datasets=datasets,
        )
