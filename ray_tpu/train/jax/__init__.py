from ray_tpu.train.jax.jax_trainer import JaxConfig, JaxTrainer

__all__ = ["JaxConfig", "JaxTrainer"]
