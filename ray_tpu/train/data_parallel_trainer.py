"""DataParallelTrainer: run one train loop per worker over a gang of actors.

Design parity: reference `python/ray/train/v2/api/data_parallel_trainer.py:64`
(`fit()` :152) — wraps `train_loop_per_worker`, builds the controller, blocks until the
run finishes, and surfaces a Result. The backend hook point matches
`python/ray/train/backend.py`.
"""

from __future__ import annotations

from typing import Callable, Optional

from ray_tpu.train.backend import BackendConfig
from ray_tpu.train.config import Result, RunConfig, ScalingConfig
from ray_tpu.train._internal.controller import TrainController, TrainingFailedError


class DataParallelTrainer:
    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: Optional[dict] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        backend_config: Optional[BackendConfig] = None,
        datasets: Optional[dict] = None,
    ):
        self._train_loop = train_loop_per_worker
        self._train_loop_config = train_loop_config
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self._backend_config = backend_config or BackendConfig()
        self._datasets = datasets or {}

    @property
    def train_loop_config(self) -> Optional[dict]:
        return self._train_loop_config

    def with_overrides(self, *, train_loop_config: Optional[dict] = None):
        """A copy of this trainer with a different per-worker config (Tune HPO hook)."""
        return type(self)(
            self._train_loop,
            train_loop_config=(
                train_loop_config if train_loop_config is not None
                else self._train_loop_config
            ),
            scaling_config=self.scaling_config,
            run_config=self.run_config,
            backend_config=self._backend_config,
            datasets=self._datasets,
        )

    def fit(self) -> Result:
        import ray_tpu
        from ray_tpu._private import usage_stats

        usage_stats.record_library_usage("train")
        from ray_tpu.train._internal.controller import run_controller_detached

        backend = self._backend_config.backend_cls()()
        kwargs = dict(
            train_fn=self._train_loop,
            train_fn_config=self._train_loop_config,
            scaling_config=self.scaling_config,
            run_config=self.run_config,
            backend=backend,
            backend_config=self._backend_config,
            datasets=self._datasets,
        )
        detach = self.run_config.detach_controller
        if detach is None:
            # Auto: detach only for NAMED, driver-initiated runs. Re-attach — the
            # payoff of detaching — needs a name the user knows; and a fit()
            # already inside an actor (e.g. a Tune trial) is driver-independent,
            # so nesting another actor would only add spawn latency.
            w = ray_tpu.global_worker_or_none()
            detach = (
                w is not None and w.mode == "driver" and self.run_config.name is not None
            )
        if detach:
            run_name = self.run_config.name or f"train_{int(__import__('time').time() * 1000)}"
            result = run_controller_detached(kwargs, run_name)
        else:
            result = TrainController(**kwargs).run()
        if result.error is not None:
            raise result.error
        return result
