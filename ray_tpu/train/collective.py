"""Control-plane collectives among training workers.

Design parity: reference `python/ray/train/collective/collectives.py`
(broadcast_from_rank_zero :14, barrier :56) implemented over the gang's
SynchronizationActor (reference sync_actor.py), not the data-plane mesh — these are for
small control values (rendezvous info, booleans), never tensors.
"""

from __future__ import annotations

import ray_tpu
from ray_tpu.train.context import get_session


def barrier(timeout_s: float = 600.0):
    s = get_session()
    if s is None:
        raise RuntimeError("barrier() called outside a training worker")
    key = f"user-barrier-{_next_key(s, 'barrier')}"
    ray_tpu.get(s.sync_actor.barrier.remote(s.world_size, key), timeout=timeout_s)


def broadcast_from_rank_zero(value=None, timeout_s: float = 600.0):
    s = get_session()
    if s is None:
        raise RuntimeError("broadcast_from_rank_zero() called outside a training worker")
    key = f"user-bcast-{_next_key(s, 'bcast')}"
    return ray_tpu.get(
        s.sync_actor.broadcast.remote(s.world_size, key, s.world_rank, value),
        timeout=timeout_s,
    )


def _next_key(session, kind: str) -> int:
    counters = session.collective_counters
    counters[kind] = counters.get(kind, 0) + 1
    return counters[kind]
