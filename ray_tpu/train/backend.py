"""Backend SPI: per-framework setup hooks around the worker group.

Design parity: reference `python/ray/train/backend.py` (Backend :16 / BackendConfig :32)
— on_start (process-group rendezvous), on_training_start, on_shutdown.
"""

from __future__ import annotations


class BackendConfig:
    def backend_cls(self):
        return Backend


class Backend:
    def on_start(self, worker_group, backend_config: BackendConfig):
        """Called after workers exist, before sessions start (rendezvous setup)."""

    def on_training_start(self, worker_group, backend_config: BackendConfig):
        """Called after sessions are initialized, before the user loop launches."""

    def on_shutdown(self, worker_group, backend_config: BackendConfig):
        """Called before the worker group is torn down."""
