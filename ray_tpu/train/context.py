"""Worker-side training session and context.

Design parity: reference `python/ray/train/v2/api/context.py` (TrainContext) +
`train_fn_utils.py` (ray.train.report / get_context / get_checkpoint) and the v1
`session.py`. The session lives in the worker actor; `report()` is a synchronization
point across all workers (every worker must call it the same number of times), matching
the reference's semantics.
"""

from __future__ import annotations

import os
import queue
import shutil
import threading
import time
from typing import Optional

from ray_tpu.train.checkpoint import Checkpoint

_session_lock = threading.Lock()
_session: Optional["_TrainSession"] = None


class _TrainSession:
    """Per-worker training state: identity, report queue, sync actor handle."""

    def __init__(
        self,
        *,
        world_size: int,
        world_rank: int,
        local_rank: int,
        local_world_size: int,
        node_rank: int,
        experiment_name: str,
        storage_path: str,
        sync_actor=None,
        latest_checkpoint: Checkpoint | None = None,
        dataset_shards: dict | None = None,
        trial_info: dict | None = None,
        report_index_offset: int = 0,
    ):
        self.world_size = world_size
        self.world_rank = world_rank
        self.local_rank = local_rank
        self.local_world_size = local_world_size
        self.node_rank = node_rank
        self.experiment_name = experiment_name
        self.storage_path = storage_path
        self.sync_actor = sync_actor
        self.latest_checkpoint = latest_checkpoint
        self.dataset_shards = dataset_shards or {}
        self.trial_info = trial_info or {}
        self.result_queue: "queue.Queue[dict]" = queue.Queue()
        # Restart attempts continue numbering where the previous attempt stopped so
        # checkpoint_<n> dirs never collide across attempts.
        self.report_count = report_index_offset
        self.stop_event = threading.Event()
        self.collective_counters: dict[str, int] = {}  # user barrier/broadcast rounds
        self._ckpt_writer = None  # lazy AsyncCheckpointWriter (sharded saves)
        # Per-step flight record (docs/observability.md "compute plane"):
        # every report() retires one record attributing the step's wall time
        # to data-wait / step-compute / checkpoint-blocked / report-blocked
        # phases — always-cheap host arithmetic riding the serve stack's
        # FlightRecorder ring, exported only from train_stats()/Result.
        from ray_tpu._private.config import CONFIG
        from ray_tpu.llm.flight_recorder import FlightRecorder

        self.recorder = FlightRecorder(
            name=f"train-rank{world_rank}",
            capacity=CONFIG.train_flight_records,
        )
        self._step_t0 = time.monotonic()
        self._data_wait_s = 0.0
        self._flight_totals = {
            "data_wait_s": 0.0, "step_compute_s": 0.0,
            "report_blocked_s": 0.0, "checkpoint_blocked_s": 0.0,
        }

    def note_data_wait(self, seconds: float):
        """Accrued by the timed dataset-shard iterator wrapper; folded into
        the current step's flight record at the next report()."""
        self._data_wait_s += seconds

    # ------------------------------------------------------------------ report

    def report(self, metrics: dict, checkpoint=None,
               checkpoint_dir_name: str | None = None):
        from ray_tpu.checkpoint import ShardedState

        # Phase attribution for the step that just ended: everything since
        # the last report that was NOT data wait is step compute; the
        # persist and barrier below are measured directly.
        step_wall = time.monotonic() - self._step_t0
        data_wait = self._data_wait_s
        self._data_wait_s = 0.0
        compute = max(0.0, step_wall - data_wait)
        self.report_count += 1
        persisted = None
        t_ck = time.monotonic()
        if isinstance(checkpoint, ShardedState):
            persisted = self._persist_sharded(checkpoint, checkpoint_dir_name)
        elif checkpoint is not None:
            persisted = self._persist_checkpoint(checkpoint, checkpoint_dir_name)
        ckpt_blocked = time.monotonic() - t_ck
        t_bar = time.monotonic()
        if self.sync_actor is not None:
            # Lockstep across the gang: report is a barrier (reference semantics).
            import ray_tpu

            ray_tpu.get(
                self.sync_actor.barrier.remote(self.world_size, f"report-{self.report_count}"),
                timeout=600.0,
            )
        report_blocked = time.monotonic() - t_bar
        flight = {
            "data_wait_s": data_wait, "step_compute_s": compute,
            "checkpoint_blocked_s": ckpt_blocked,
            "report_blocked_s": report_blocked,
            "report_index": self.report_count, "rank": self.world_rank,
        }
        for k in self._flight_totals:
            self._flight_totals[k] += flight[k]
        self._record_flight(flight)
        self._step_t0 = time.monotonic()
        self.result_queue.put(
            {
                "metrics": dict(metrics),
                "checkpoint": persisted,
                "report_index": self.report_count,
                "rank": self.world_rank,
                "flight": flight,
            }
        )
        if self.stop_event.is_set():
            raise SystemExit(0)

    def _record_flight(self, flight: dict):
        """One ring record per report: the phase spans are laid out end to
        end against wall-clock so timeline/trace export renders them."""
        rec = self.recorder.start(
            f"step-{flight['report_index']}",
            tenant=f"rank{self.world_rank}", route="train",
        )
        if rec is None:
            return
        t1 = time.time()
        spans = [
            ("report-blocked", flight["report_blocked_s"]),
            ("checkpoint-blocked", flight["checkpoint_blocked_s"]),
            ("step-compute", flight["step_compute_s"]),
            ("data-wait", flight["data_wait_s"]),
        ]
        for name, seconds in spans:  # newest phase first, walking backwards
            rec.span(name, t1 - seconds, t1)
            t1 -= seconds
        self.recorder.finish(rec)

    def train_stats(self) -> dict:
        """Report path (the train analogue of scheduler_stats()): flushes
        the recorder's pending exports and joins the per-step phase totals
        with the process's program registry and memory ledger."""
        from ray_tpu.util import xprof

        self.recorder.flush_task_events()
        return {
            "rank": self.world_rank,
            "reports": self.report_count,
            "phases": dict(self._flight_totals),
            "recorder": self.recorder.stats(),
            "records": self.recorder.records(16),
            "programs": xprof.registry().report(),
            "memory": xprof.device_memory_report(),
        }

    def _persist_checkpoint(self, checkpoint: Checkpoint, dir_name: str | None) -> Checkpoint:
        """Move the worker's local checkpoint dir under the experiment storage path.

        Every reporting worker writes into the same checkpoint_<n> dir under distinct
        file names by convention (rank-prefixed files); on a shared filesystem this is
        the reference's StorageContext layout (train/v2 storage.py).
        """
        name = dir_name or f"checkpoint_{self.report_count:06d}"
        target = os.path.join(self.storage_path, self.experiment_name, name)
        os.makedirs(target, exist_ok=True)
        if os.path.abspath(checkpoint.path) != os.path.abspath(target):
            shutil.copytree(checkpoint.path, target, dirs_exist_ok=True)
        return Checkpoint(target)

    # ------------------------------------------------------------ sharded path

    def _checkpoint_writer(self):
        if self._ckpt_writer is None:
            from ray_tpu.checkpoint import AsyncCheckpointWriter

            self._ckpt_writer = AsyncCheckpointWriter()
        return self._ckpt_writer

    def _persist_sharded(self, state, dir_name: str | None) -> Checkpoint:
        """Sharded save: this rank persists only its owned shards of the pytree
        into the shared checkpoint_<n> dir; rank 0 commits the manifest once
        every rank's shards (their process specs) are durable — a filesystem
        commit barrier, so the async path never blocks the step loop on peers.
        """
        from ray_tpu._private.config import CONFIG

        name = dir_name or f"checkpoint_{self.report_count:06d}"
        target = os.path.join(self.storage_path, self.experiment_name, name)
        if self.world_size > 1:
            pi, pc = self.world_rank, self.world_size
        else:
            pi = pc = None
        writer = self._checkpoint_writer()
        if CONFIG.train_ckpt_async:
            writer.save(target, state.tree, process_index=pi, process_count=pc)
        else:
            writer.save_sync(target, state.tree, process_index=pi,
                             process_count=pc)
        return Checkpoint(target)

    def wait_for_checkpoints(self):
        """Barrier for in-flight async sharded saves; raises if any failed.
        Called by the worker on clean train-fn exit so a run never FINISHES
        with its last checkpoint uncommitted."""
        if self._ckpt_writer is not None:
            self._ckpt_writer.wait_until_finished()


def init_session(**kwargs) -> _TrainSession:
    global _session
    with _session_lock:
        _session = _TrainSession(**kwargs)
    return _session


def shutdown_session():
    global _session
    with _session_lock:
        if _session is not None:
            # Retire live flight records so leaksan's books balance on
            # worker shutdown exactly as they do on engine shutdown.
            _session.recorder.close()
        _session = None


def get_session() -> Optional[_TrainSession]:
    return _session


class TrainContext:
    """Parity: reference ray.train.get_context() (v2/api/context.py)."""

    def __init__(self, session: _TrainSession):
        self._s = session

    def get_world_size(self) -> int:
        return self._s.world_size

    def get_world_rank(self) -> int:
        return self._s.world_rank

    def get_local_rank(self) -> int:
        return self._s.local_rank

    def get_local_world_size(self) -> int:
        return self._s.local_world_size

    def get_node_rank(self) -> int:
        return self._s.node_rank

    def get_experiment_name(self) -> str:
        return self._s.experiment_name

    def get_storage(self):
        return self._s.storage_path

    def get_trial_name(self):
        return self._s.trial_info.get("name")

    def get_trial_id(self):
        return self._s.trial_info.get("id")

    def get_trial_resources(self):
        return self._s.trial_info.get("resources")


def get_context() -> TrainContext:
    s = get_session()
    if s is None:
        raise RuntimeError(
            "ray_tpu.train.get_context() called outside a training worker"
        )
    return TrainContext(s)


def report(metrics: dict, checkpoint=None, *,
           checkpoint_dir_name: str | None = None):
    """Parity: ray.train.report — report metrics (+ optional checkpoint); acts as a
    barrier across the worker gang.

    ``checkpoint`` is either a :class:`Checkpoint` (directory copy, every rank
    writes its own files) or a :class:`ray_tpu.checkpoint.ShardedState` pytree
    wrapper — the sharded path, where each rank persists only its addressable
    shards (asynchronously under the ``train_ckpt_async`` flag) and rank 0
    atomically commits the manifest (docs/checkpoint.md)."""
    s = get_session()
    if s is None:
        raise RuntimeError("ray_tpu.train.report() called outside a training worker")
    s.report(metrics, checkpoint, checkpoint_dir_name)


def get_checkpoint() -> Optional[Checkpoint]:
    """Parity: ray.train.get_checkpoint — the latest checkpoint to resume from."""
    s = get_session()
    if s is None:
        return None
    return s.latest_checkpoint


class _TimedShard:
    """Dataset-shard proxy that charges iteration stalls to the session's
    data-wait phase (per-item `next()` wall time). Everything else falls
    through to the real shard, so it is substitutable anywhere."""

    def __init__(self, shard, session: _TrainSession):
        self._shard = shard
        self._session = session

    def _timed(self, it):
        while True:
            t0 = time.monotonic()
            try:
                item = next(it)
            except StopIteration:
                return
            self._session.note_data_wait(time.monotonic() - t0)
            yield item

    def __iter__(self):
        return self._timed(iter(self._shard))

    def __len__(self):
        return len(self._shard)

    def __getattr__(self, name):
        attr = getattr(self._shard, name)
        if name in ("iter_batches", "iter_rows", "iter_torch_batches"):
            def wrapped(*args, **kwargs):
                return self._timed(iter(attr(*args, **kwargs)))

            return wrapped
        return attr


def get_dataset_shard(dataset_name: str = "train"):
    """Parity: ray.train.get_dataset_shard — this worker's split of a Dataset.

    The returned shard is wrapped so time blocked on `next()` accrues to the
    step's data-wait phase in the flight record (docs/observability.md)."""
    s = get_session()
    if s is None:
        raise RuntimeError("get_dataset_shard() called outside a training worker")
    shard = s.dataset_shards.get(dataset_name)
    if shard is None:
        raise KeyError(
            f"no dataset {dataset_name!r} was passed to the trainer "
            f"(available: {list(s.dataset_shards)})"
        )
    return _TimedShard(shard, s)


def train_stats() -> Optional[dict]:
    """Worker-side report path: the current session's per-step flight
    totals + recorder ring + program/memory reports (None off-worker)."""
    s = get_session()
    return s.train_stats() if s is not None else None
