"""Worker-side training session and context.

Design parity: reference `python/ray/train/v2/api/context.py` (TrainContext) +
`train_fn_utils.py` (ray.train.report / get_context / get_checkpoint) and the v1
`session.py`. The session lives in the worker actor; `report()` is a synchronization
point across all workers (every worker must call it the same number of times), matching
the reference's semantics.
"""

from __future__ import annotations

import os
import queue
import shutil
import threading
from typing import Optional

from ray_tpu.train.checkpoint import Checkpoint

_session_lock = threading.Lock()
_session: Optional["_TrainSession"] = None


class _TrainSession:
    """Per-worker training state: identity, report queue, sync actor handle."""

    def __init__(
        self,
        *,
        world_size: int,
        world_rank: int,
        local_rank: int,
        local_world_size: int,
        node_rank: int,
        experiment_name: str,
        storage_path: str,
        sync_actor=None,
        latest_checkpoint: Checkpoint | None = None,
        dataset_shards: dict | None = None,
        trial_info: dict | None = None,
        report_index_offset: int = 0,
    ):
        self.world_size = world_size
        self.world_rank = world_rank
        self.local_rank = local_rank
        self.local_world_size = local_world_size
        self.node_rank = node_rank
        self.experiment_name = experiment_name
        self.storage_path = storage_path
        self.sync_actor = sync_actor
        self.latest_checkpoint = latest_checkpoint
        self.dataset_shards = dataset_shards or {}
        self.trial_info = trial_info or {}
        self.result_queue: "queue.Queue[dict]" = queue.Queue()
        # Restart attempts continue numbering where the previous attempt stopped so
        # checkpoint_<n> dirs never collide across attempts.
        self.report_count = report_index_offset
        self.stop_event = threading.Event()
        self.collective_counters: dict[str, int] = {}  # user barrier/broadcast rounds
        self._ckpt_writer = None  # lazy AsyncCheckpointWriter (sharded saves)

    # ------------------------------------------------------------------ report

    def report(self, metrics: dict, checkpoint=None,
               checkpoint_dir_name: str | None = None):
        from ray_tpu.checkpoint import ShardedState

        self.report_count += 1
        persisted = None
        if isinstance(checkpoint, ShardedState):
            persisted = self._persist_sharded(checkpoint, checkpoint_dir_name)
        elif checkpoint is not None:
            persisted = self._persist_checkpoint(checkpoint, checkpoint_dir_name)
        if self.sync_actor is not None:
            # Lockstep across the gang: report is a barrier (reference semantics).
            import ray_tpu

            ray_tpu.get(
                self.sync_actor.barrier.remote(self.world_size, f"report-{self.report_count}"),
                timeout=600.0,
            )
        self.result_queue.put(
            {
                "metrics": dict(metrics),
                "checkpoint": persisted,
                "report_index": self.report_count,
                "rank": self.world_rank,
            }
        )
        if self.stop_event.is_set():
            raise SystemExit(0)

    def _persist_checkpoint(self, checkpoint: Checkpoint, dir_name: str | None) -> Checkpoint:
        """Move the worker's local checkpoint dir under the experiment storage path.

        Every reporting worker writes into the same checkpoint_<n> dir under distinct
        file names by convention (rank-prefixed files); on a shared filesystem this is
        the reference's StorageContext layout (train/v2 storage.py).
        """
        name = dir_name or f"checkpoint_{self.report_count:06d}"
        target = os.path.join(self.storage_path, self.experiment_name, name)
        os.makedirs(target, exist_ok=True)
        if os.path.abspath(checkpoint.path) != os.path.abspath(target):
            shutil.copytree(checkpoint.path, target, dirs_exist_ok=True)
        return Checkpoint(target)

    # ------------------------------------------------------------ sharded path

    def _checkpoint_writer(self):
        if self._ckpt_writer is None:
            from ray_tpu.checkpoint import AsyncCheckpointWriter

            self._ckpt_writer = AsyncCheckpointWriter()
        return self._ckpt_writer

    def _persist_sharded(self, state, dir_name: str | None) -> Checkpoint:
        """Sharded save: this rank persists only its owned shards of the pytree
        into the shared checkpoint_<n> dir; rank 0 commits the manifest once
        every rank's shards (their process specs) are durable — a filesystem
        commit barrier, so the async path never blocks the step loop on peers.
        """
        from ray_tpu._private.config import CONFIG

        name = dir_name or f"checkpoint_{self.report_count:06d}"
        target = os.path.join(self.storage_path, self.experiment_name, name)
        if self.world_size > 1:
            pi, pc = self.world_rank, self.world_size
        else:
            pi = pc = None
        writer = self._checkpoint_writer()
        if CONFIG.train_ckpt_async:
            writer.save(target, state.tree, process_index=pi, process_count=pc)
        else:
            writer.save_sync(target, state.tree, process_index=pi,
                             process_count=pc)
        return Checkpoint(target)

    def wait_for_checkpoints(self):
        """Barrier for in-flight async sharded saves; raises if any failed.
        Called by the worker on clean train-fn exit so a run never FINISHES
        with its last checkpoint uncommitted."""
        if self._ckpt_writer is not None:
            self._ckpt_writer.wait_until_finished()


def init_session(**kwargs) -> _TrainSession:
    global _session
    with _session_lock:
        _session = _TrainSession(**kwargs)
    return _session


def shutdown_session():
    global _session
    with _session_lock:
        _session = None


def get_session() -> Optional[_TrainSession]:
    return _session


class TrainContext:
    """Parity: reference ray.train.get_context() (v2/api/context.py)."""

    def __init__(self, session: _TrainSession):
        self._s = session

    def get_world_size(self) -> int:
        return self._s.world_size

    def get_world_rank(self) -> int:
        return self._s.world_rank

    def get_local_rank(self) -> int:
        return self._s.local_rank

    def get_local_world_size(self) -> int:
        return self._s.local_world_size

    def get_node_rank(self) -> int:
        return self._s.node_rank

    def get_experiment_name(self) -> str:
        return self._s.experiment_name

    def get_storage(self):
        return self._s.storage_path

    def get_trial_name(self):
        return self._s.trial_info.get("name")

    def get_trial_id(self):
        return self._s.trial_info.get("id")

    def get_trial_resources(self):
        return self._s.trial_info.get("resources")


def get_context() -> TrainContext:
    s = get_session()
    if s is None:
        raise RuntimeError(
            "ray_tpu.train.get_context() called outside a training worker"
        )
    return TrainContext(s)


def report(metrics: dict, checkpoint=None, *,
           checkpoint_dir_name: str | None = None):
    """Parity: ray.train.report — report metrics (+ optional checkpoint); acts as a
    barrier across the worker gang.

    ``checkpoint`` is either a :class:`Checkpoint` (directory copy, every rank
    writes its own files) or a :class:`ray_tpu.checkpoint.ShardedState` pytree
    wrapper — the sharded path, where each rank persists only its addressable
    shards (asynchronously under the ``train_ckpt_async`` flag) and rank 0
    atomically commits the manifest (docs/checkpoint.md)."""
    s = get_session()
    if s is None:
        raise RuntimeError("ray_tpu.train.report() called outside a training worker")
    s.report(metrics, checkpoint, checkpoint_dir_name)


def get_checkpoint() -> Optional[Checkpoint]:
    """Parity: ray.train.get_checkpoint — the latest checkpoint to resume from."""
    s = get_session()
    if s is None:
        return None
    return s.latest_checkpoint


def get_dataset_shard(dataset_name: str = "train"):
    """Parity: ray.train.get_dataset_shard — this worker's split of a Dataset."""
    s = get_session()
    if s is None:
        raise RuntimeError("get_dataset_shard() called outside a training worker")
    shard = s.dataset_shards.get(dataset_name)
    if shard is None:
        raise KeyError(
            f"no dataset {dataset_name!r} was passed to the trainer "
            f"(available: {list(s.dataset_shards)})"
        )
    return shard
