"""leaksan: runtime leak sanitizer for the lease/pin/stream planes.

The LeakSanitizer-style counterpart of `raylint`'s RL8xx static family: the
resource classes leaklint reasons about statically (SlotView ring-slot
leases, PrefixLease KV pins, native-arena pins, device-object stream pumps,
rpc connections, checkpoint writer jobs, dp replica-rank tokens) register
their live handles here, and a test fixture (tests/conftest.py
`leaksan_guard`) snapshots the registry around each test and fails on
growth.

Zero overhead unless enabled: every `track`/`untrack` call starts with one
enabled() check (an env read / cached bool); nothing is allocated and no
lock is taken when the sanitizer is off. Enable with `RAY_TPU_LEAKSAN=1` in
the environment, or programmatically with `enable()` (what the pytest
fixture does).

Two ways a handle is accounted:

- **object-tracked** (`track(kind, obj)`): a weakref with a death callback.
  An explicit release untracks it; an object that is garbage-collected
  WITHOUT having been released moves to the `<kind>:gc` bucket — for a
  cross-process resource that is a leak the GC hid (a SlotView collected
  without release never published its ack; a PrefixLease collected without
  release pins its blocks forever), so the fixture fails on those too.
- **token-tracked** (`track(kind, token=...)`): a counted key for resources
  with no dedicated Python handle (arena pins by object id, stream pumps,
  rank tokens). `untrack` decrements; counts never go negative.

`leak_report()` lists what is live (and what leaked through GC) with the
detail string each site registered; `live_counts()` is the cheap summary;
both also export the `leaksan_live_handles{kind}` gauge via util.metrics.
"""

from __future__ import annotations

import gc
import os
import threading
import time
import weakref
from typing import Dict, Iterable, List, Optional

# RLock: a weakref death callback can fire on THIS thread mid-track (GC
# triggered by an allocation inside the critical section) and re-enter.
_lock = threading.RLock()
_enabled_override: Optional[bool] = None
# kind -> {id(obj): (weakref, detail)} for object-tracked handles
_objects: Dict[str, Dict[int, tuple]] = {}
# kind -> {token: count} for token-tracked handles
_tokens: Dict[str, Dict[object, int]] = {}
# kind -> count of objects GC'd while still tracked (released by nobody)
_gc_leaked: Dict[str, int] = {}

#: Thread-name prefixes that belong to the resource planes leaksan audits;
#: the pytest fixture counts only these (worker/executor threads are
#: process-lifetime by design and would make growth checks meaningless).
THREAD_PREFIXES = ("devobj-stream", "ckpt-writer", "chan-pump", "kv-spill")


def enabled() -> bool:
    if _enabled_override is not None:
        return _enabled_override
    return os.environ.get("RAY_TPU_LEAKSAN", "") == "1"


def enable() -> None:
    global _enabled_override
    _enabled_override = True


def disable() -> None:
    """Stop tracking NEW handles. Untrack keeps working so handles acquired
    while enabled still balance their books."""
    global _enabled_override
    _enabled_override = False


def reset() -> None:
    """Drop every tracked handle and gc-leak tally (test isolation)."""
    with _lock:
        _objects.clear()
        _tokens.clear()
        _gc_leaked.clear()


def track(kind: str, obj: object = None, *, token: object = None,
          detail: str = "") -> None:
    """Register a live handle. No-op (and allocation-free) when disabled."""
    if not enabled():
        return
    if obj is not None:
        oid = id(obj)

        def _on_gc(_ref, _kind=kind, _oid=oid):
            with _lock:
                entries = _objects.get(_kind)
                if entries is not None and entries.pop(_oid, None) is not None:
                    # died tracked = never released: the GC hid a leak
                    _gc_leaked[_kind] = _gc_leaked.get(_kind, 0) + 1

        ref = weakref.ref(obj, _on_gc)
        with _lock:
            _objects.setdefault(kind, {})[oid] = (ref, detail)
    elif token is not None:
        with _lock:
            bucket = _tokens.setdefault(kind, {})
            bucket[token] = bucket.get(token, 0) + 1


def untrack(kind: str, obj: object = None, *, token: object = None) -> None:
    """Balance a `track`. Runs even when disabled (consistent books for
    handles acquired while enabled); never throws, never goes negative.
    Pure dict work: gauges export from live_counts(), never from data paths
    (a release can run on an io-loop thread mid-connection-teardown, where a
    metrics flush — a blocking GCS RPC — would deadlock the loop)."""
    with _lock:
        if obj is not None:
            entries = _objects.get(kind)
            if entries is not None:
                entries.pop(id(obj), None)
        elif token is not None:
            bucket = _tokens.get(kind)
            if bucket is not None and token in bucket:
                bucket[token] -= 1
                if bucket[token] <= 0:
                    del bucket[token]


def live_counts() -> Dict[str, int]:
    """{kind: live handles} plus `<kind>:gc` buckets for handles that were
    garbage-collected without ever being released."""
    with _lock:
        out: Dict[str, int] = {}
        for kind, entries in _objects.items():
            # drop entries whose referent died but whose callback hasn't run
            live = {k: v for k, v in entries.items() if v[0]() is not None}
            if len(live) != len(entries):
                _gc_leaked[kind] = _gc_leaked.get(kind, 0) + (
                    len(entries) - len(live)
                )
                _objects[kind] = live
            if live:
                out[kind] = len(live)
        for kind, bucket in _tokens.items():
            n = sum(bucket.values())
            if n:
                out[kind] = out.get(kind, 0) + n
        for kind, n in _gc_leaked.items():
            if n:
                out[f"{kind}:gc"] = n
    _export_gauges(out)
    return out


def leak_report() -> Dict[str, List[str]]:
    """{kind: [detail, ...]} for every live handle (token kinds render as
    `token xN`); includes the `<kind>:gc` buckets."""
    counts = live_counts()  # refreshes dead weakrefs first
    with _lock:
        report: Dict[str, List[str]] = {}
        for kind, entries in _objects.items():
            details = [
                d or f"handle@{oid:x}" for oid, (r, d) in entries.items()
                if r() is not None
            ]
            if details:
                report[kind] = details
        for kind, bucket in _tokens.items():
            items = [f"{tok!r} x{n}" for tok, n in bucket.items()]
            if items:
                report.setdefault(kind, []).extend(items)
        for kind, n in counts.items():
            if kind.endswith(":gc"):
                report[kind] = [f"{n} handle(s) garbage-collected unreleased"]
        return report


def tracked_threads() -> List[str]:
    """Live threads belonging to the audited resource planes."""
    return sorted(
        t.name for t in threading.enumerate()
        if t.is_alive() and t.name.startswith(THREAD_PREFIXES)
    )


def snapshot() -> Dict[str, object]:
    """What the pytest fixture compares across a test: live handle counts
    (incl. gc-leak buckets) and the audited thread names."""
    return {"handles": live_counts(), "threads": tracked_threads()}


def check_growth(before: Dict[str, object], *, settle_s: float = 3.0,
                 ignore: Iterable[str] = ("rpc_conn",)) -> Dict[str, object]:
    """Compare the registry against `before`, giving async teardown (stream
    pump threads, background release callbacks, GC) up to `settle_s` seconds
    to drain. Returns {} when clean, else {kind: (before, after)} growth plus
    a "report" key with per-handle detail.

    `rpc_conn` is ignored by default: connections are deliberately cached
    per (process, peer address) for the process lifetime, so a test that
    dials a new peer legitimately grows the cache (docs/raylint.md)."""
    deadline = time.monotonic() + max(0.0, settle_s)
    ignore = set(ignore)
    while True:
        gc.collect()
        after = snapshot()
        growth: Dict[str, object] = {}
        b_handles: Dict[str, int] = dict(before.get("handles", {}))
        for kind, n in after["handles"].items():
            if kind in ignore or kind.split(":", 1)[0] in ignore:
                continue
            if n > b_handles.get(kind, 0):
                growth[kind] = (b_handles.get(kind, 0), n)
        b_threads = set(before.get("threads", []))
        new_threads = [t for t in after["threads"] if t not in b_threads]
        if new_threads:
            growth["threads"] = (sorted(b_threads), after["threads"])
        if not growth or time.monotonic() >= deadline:
            if growth:
                growth["report"] = leak_report()
            return growth
        time.sleep(0.05)


_gauge = None
_gauge_kinds_seen: set = set()


def _export_gauges(counts: Dict[str, int]) -> None:
    """Best-effort `leaksan_live_handles{kind}` export via util.metrics.

    Deliberately runs ONLY from live_counts()/snapshot() (caller threads, on
    their own schedule): track/untrack fire on data-plane and io-loop threads
    where a metrics flush — a blocking GCS round-trip — must never run. A
    kind that drops to zero is still exported (the gauge falls, not
    disappears)."""
    global _gauge
    if not enabled():
        return
    try:
        if _gauge is None:
            from ray_tpu.util import metrics

            _gauge = metrics.Gauge(
                "leaksan_live_handles",
                "live acquire/release-paired resource handles (leaksan)",
                tag_keys=("kind",),
            )
        with _lock:
            _gauge_kinds_seen.update(counts)
            kinds = set(_gauge_kinds_seen)
        # set() outside the lock: a gauge flush is a GCS round-trip
        for kind in kinds:
            _gauge.set(float(counts.get(kind, 0)), tags={"kind": kind})  # raylint: disable=RL901 (this IS leaksan's report path: _export_gauges runs only from the live_counts()/snapshot() export, never per-acquire)
    except Exception:
        pass  # observability must never break the workload
