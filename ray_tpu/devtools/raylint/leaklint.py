"""leaklint: the RL8xx checker family — resource-lifetime hazards.

The runtime is built on acquire/release-paired resources that outlive the
Python object holding them: shm ring-slot leases whose ack publishes at
release (`SlotView`), ref-counted KV prefix leases that pin evictable blocks
(`PrefixLease`), native-arena pins, device-object stream pumps, RPC
connections, checkpoint writers, DP replica-rank tokens, raylet resource
leases. One missed release on an error path is silent back-pressure, wedged
eviction, or unbounded HBM/shm growth — never a crash, which is exactly why
review misses it. leaklint is the static half (Infer-style per-function
path reasoning over a declarative resource table); `ray_tpu/devtools/
leaksan.py` is the runtime half (LeakSanitizer-style live-handle
accounting).

Shared model:

- **Resource table** (`RESOURCE_TABLE`): maps acquire APIs to their release
  obligation. Handle-returning acquires (`Channel.read_view` ->
  `SlotView.release`, `PrefixCacheManager.lookup` -> `PrefixLease.release`,
  `rpc.connect` -> `Connection.close`, `DeviceChannel.create` -> `destroy`,
  `AsyncCheckpointWriter()` -> `wait_until_finished`/`close`) bind the
  obligation to the returned handle; arg-keyed acquires (`shmstore.pin` ->
  `release`, `KVBlockPool.incref` -> `decref`, raylet `resources.acquire`
  -> `resources.release`, `DPRankAssigner.assign` -> `release`) bind it to
  (receiver, first argument).
- **Ownership escape** discharges the per-function obligation: the handle is
  returned/yielded, stored onto `self`/a container, passed to another
  callable, or captured by a nested function — the resource's lifetime is
  then the owner's problem (and RL802 checks the owner's class).
- **Class-managed** arg-keyed resources (the enclosing class calls the
  paired release in some non-`__del__` method) are exempt from the
  per-function RL801 check: cross-method acquire/release is the normal shape
  for stateful owners, and RL802 catches the GC-only degenerate case.

Checkers:

- RL801 unreleased-acquire: an acquired resource is, on some path, neither
  released nor escaped — never released at all, released only under an
  unrelated condition, or released on the fall-through path with raise-capable
  statements in between and no `finally`/`with`.
- RL802 release-via-gc-only: a cross-process release reachable only from
  `__del__` — GC timing (or a never-collected cycle) then decides when the
  peer's pin/slot/rank frees.
- RL803 use-after-release / double-release along a straight-line path.
- RL804 fragile-release: a release whose failure is silently swallowed by an
  undocumented broad `except`, or a release performed under a different lock
  than its acquire.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu.devtools.raylint.checkers import (
    _base_ident,
    _ident_parts,
    _is_lockish,
    _root_name,
)
from ray_tpu.devtools.raylint.core import FileContext, Finding


@dataclass(frozen=True)
class ResourceSpec:
    """One row of the acquire->release contract table."""

    kind: str                      # human name used in messages
    acquire: str                   # method/ctor name (leading "_" ignored)
    hints: tuple = ()              # receiver-ident words; () = match anywhere
    release: tuple = ()            # methods on the returned handle
    receiver_release: tuple = ()   # methods on the SAME receiver (arg-keyed)
    arg_keyed: bool = False        # obligation keyed by (receiver, arg0)


RESOURCE_TABLE: Tuple[ResourceSpec, ...] = (
    ResourceSpec("shm ring-slot lease (SlotView)", "read_view",
                 release=("release",)),
    ResourceSpec("KV prefix lease (PrefixLease)", "lookup",
                 hints=("cache", "prefix"), release=("release",)),
    ResourceSpec("native-arena pin", "pin",
                 receiver_release=("release",), arg_keyed=True),
    ResourceSpec("KV block refcount", "incref", hints=("pool",),
                 receiver_release=("decref",), arg_keyed=True),
    ResourceSpec("device stream channel", "create", hints=("channel",),
                 release=("destroy", "close")),
    ResourceSpec("rpc connection", "connect", hints=("rpc",),
                 release=("close",)),
    ResourceSpec("async checkpoint writer", "AsyncCheckpointWriter",
                 release=("wait_until_finished", "close")),
    ResourceSpec("LoRA adapter pin (AdapterHandle)", "acquire",
                 hints=("adapter", "adapters"), release=("release",)),
    ResourceSpec("dp replica-rank token", "assign", hints=("assigner",),
                 receiver_release=("release",), arg_keyed=True),
    ResourceSpec("raylet resource lease", "acquire", hints=("resources",),
                 receiver_release=("release",), arg_keyed=True),
    ResourceSpec("GCS replication peer link (PeerLink)", "open_peer",
                 release=("close",)),
    ResourceSpec("GCS primary lease (LeaseToken)", "acquire_lease",
                 release=("release",)),
    # Round 15 (docs/serving_tp.md): a TP engine's mesh-resident KV shard
    # pool. A forgotten free() strands tp * layers * 2 device buffers that
    # no host object names once the engine drops — the drain-and-retire path
    # of every TP replica must discharge it.
    ResourceSpec("mesh-sharded KV pool (ShardedKVPool)", "ShardedKVPool",
                 release=("free",)),
    # Round 17 (docs/kvcache.md): the tiered KV store + multicast plane. A
    # spill handle closed by nobody leaks an fd AND leaves a tmp orphan; an
    # unreleased multicast subscription back-pressures the writer's ring
    # forever; an unreleased prefix-fetch lease pins the exported chain
    # against eviction for the engine's life.
    ResourceSpec("disk-spill file handle (SpillFile)", "open_spill",
                 release=("commit", "close")),
    ResourceSpec("multicast subscription (Subscription)", "subscribe",
                 release=("unsubscribe",)),
    ResourceSpec("cross-replica prefix-fetch lease (PrefixLease)",
                 "lease_prefix", hints=("cache", "prefix", "engine"),
                 release=("release",)),
    # Round 18 (docs/observability.md "compute plane"): an xprof profiler
    # capture handle. A capture never stopped keeps jax.profiler tracing for
    # the rest of the process's life — every later dispatch pays the
    # instrumentation tax and the trace dir grows without bound. `capture()`
    # wraps the pair; any direct start_capture() must stop_capture()/close().
    ResourceSpec("profiler capture (ProfilerCapture)", "start_capture",
                 release=("stop_capture", "close")),
    # Round 20 (docs/autoscale.md): an autopilot scale-op token. Every
    # begin_scale_op() must resolve to commit() (decision applied, persisted)
    # or abort() (target rolled back). A dropped token leaves the decision
    # log entry "pending" forever and — worse — a half-applied replica
    # target that the next controller restart replays.
    ResourceSpec("autopilot scale-op token (ScaleOp)", "begin_scale_op",
                 release=("commit", "abort")),
    # Round 22 (docs/generation.md): the generation-modes plane. An
    # open_stream() nobody closes orphans a decode slot behind a vanished
    # consumer — the slot, its prefix lease, and its adapter pin stay live
    # until max_tokens runs out (or forever on a stalled constraint). A
    # guided-decoding ConstraintState begun but never released keeps its
    # token-DFA walk (and the leaksan book entry) past the request's life.
    ResourceSpec("engine token stream (TokenStream)", "open_stream",
                 release=("close", "cancel")),
    ResourceSpec("guided-decoding constraint state (ConstraintState)",
                 "begin", hints=("constraint", "guided"),
                 release=("release",)),
)

#: Methods that release SOMETHING in this codebase's vocabulary; RL802/RL803
#: key off these (union of the table plus the teardown verbs owners use).
RELEASE_NAMES: Set[str] = set()
for _spec in RESOURCE_TABLE:
    RELEASE_NAMES.update(_spec.release)
    RELEASE_NAMES.update(_spec.receiver_release)
RELEASE_NAMES.update({"destroy", "free", "shutdown", "wait_until_finished"})

#: The subset whose silent failure RL804 cares about (a swallowed `close` on
#: teardown is routine; a swallowed lease/pin release is a wedge).
_RL804_RELEASE_NAMES = {"release", "decref", "destroy", "free",
                        "wait_until_finished"}

_BROAD_EXC = {"Exception", "BaseException"}


def _strip_remote(func: ast.expr) -> Tuple[Optional[str], Optional[ast.expr]]:
    """(method name, receiver expr) of a call func, looking through the
    actor-call `.remote` hop (`assigner.release.remote(tok)` -> release)."""
    if isinstance(func, ast.Attribute):
        name, recv = func.attr, func.value
        if name == "remote" and isinstance(recv, ast.Attribute):
            name, recv = recv.attr, recv.value
        return name, recv
    if isinstance(func, ast.Name):
        return func.id, None
    return None, None


def _recv_parts(recv: Optional[ast.expr]) -> Set[str]:
    """Ident words of the whole receiver chain (`self._prefix_cache` ->
    {prefix, cache, self})."""
    parts: Set[str] = set()
    e = recv
    while isinstance(e, (ast.Attribute, ast.Subscript)):
        if isinstance(e, ast.Attribute):
            parts |= _ident_parts(e.attr)
        e = e.value
    if isinstance(e, ast.Name):
        parts |= _ident_parts(e.id)
    return parts


def _spec_for_call(call: ast.Call) -> Optional[ResourceSpec]:
    name, recv = _strip_remote(call.func)
    if name is None:
        return None
    stripped = name.lstrip("_") or name
    for spec in RESOURCE_TABLE:
        if spec.acquire not in (name, stripped):
            continue
        if spec.hints and not (_recv_parts(recv) & set(spec.hints)):
            continue
        return spec
    return None


def _contains_call(node: ast.AST) -> bool:
    return any(isinstance(n, ast.Call) for n in ast.walk(node))


def _bare_names(expr: ast.expr) -> Set[str]:
    """Names appearing as direct values (possibly inside container displays)
    — NOT as attribute/subscript bases. `lease` in `return lease` or
    `f(lease)` escapes ownership; `lease.matched_tokens` does not."""
    out: Set[str] = set()
    stack = [expr]
    while stack:
        e = stack.pop()
        if isinstance(e, ast.Name):
            out.add(e.id)
        elif isinstance(e, (ast.Tuple, ast.List, ast.Set)):
            stack.extend(e.elts)
        elif isinstance(e, ast.Dict):
            stack.extend(v for v in e.values if v is not None)
        elif isinstance(e, ast.Starred):
            stack.append(e.value)
        elif isinstance(e, (ast.Await, ast.NamedExpr)):
            stack.append(e.value)
        elif isinstance(e, ast.IfExp):
            stack.extend((e.body, e.orelse))
    return out


class _Acquire:
    __slots__ = ("spec", "handle", "aliases", "token", "recv_parts",
                 "line", "col", "lock_stack", "call")

    def __init__(self, spec, handle, token, recv_parts, line, col,
                 lock_stack, call):
        self.spec = spec
        self.handle = handle          # local name, or None for arg-keyed
        self.aliases: Set[str] = {handle} if handle else set()
        self.token = token            # first-arg dump, for arg-keyed
        self.recv_parts = recv_parts
        self.line = line
        self.col = col
        self.lock_stack = lock_stack  # innermost-last tuple of lock idents
        self.call = call


class _Release:
    __slots__ = ("name", "recv", "recv_parts", "base_name", "token", "line",
                 "in_finally", "in_except", "if_tests", "lock_stack",
                 "swallowed_line")

    def __init__(self, name, recv, recv_parts, base_name, token, line,
                 in_finally, in_except, if_tests, lock_stack, swallowed_line):
        self.name = name              # release method name
        self.recv = recv
        self.recv_parts = recv_parts
        self.base_name = base_name    # root Name of receiver ("lease")
        self.token = token            # first-arg dump (or None)
        self.line = line
        self.in_finally = in_finally
        self.in_except = in_except
        self.if_tests: List[ast.expr] = if_tests
        self.lock_stack = lock_stack
        # set when the release sits alone in a try whose broad handler
        # swallows silently (RL804a); value is the handler's lineno
        self.swallowed_line = swallowed_line


class _FunctionScan(ast.NodeVisitor):
    """One pass over a single function body (nested defs excluded) that
    collects acquires, releases, calls, loads, assigns, and escapes."""

    def __init__(self, fn: ast.AST):
        self.fn = fn
        self.acquires: List[_Acquire] = []
        self.releases: List[_Release] = []
        self.call_lines: List[int] = []       # every Call's lineno
        self.loads: List[Tuple[str, int, bool]] = []  # (name, line, is_rel_base)
        self.assign_lines: Dict[str, List[int]] = {}
        self.escaped: Set[str] = set()
        self.aliases: Dict[str, str] = {}     # alias -> original
        self.returns_while: List[int] = []    # linenos of return/raise stmts
        self._lock_stack: List[str] = []
        self._finally_depth = 0
        self._except_depth = 0
        self._if_tests: List[ast.expr] = []
        self._with_acquire_calls: Set[int] = set()   # id() of safe with-acquires
        self._swallow_trys: Dict[int, int] = {}      # id(stmt in try body)->line
        self._scan()

    # -- structure ----------------------------------------------------------

    def _scan(self):
        for stmt in self.fn.body:
            self.visit(stmt)

    def _skip(self, node):  # nested scopes analyzed on their own
        # closure capture = ownership escape for anything acquired out here
        for n in ast.walk(node):
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
                self.escaped.add(n.id)

    visit_FunctionDef = _skip
    visit_AsyncFunctionDef = _skip
    visit_Lambda = _skip
    visit_ClassDef = _skip

    def visit_With(self, node):
        lockish = [item.context_expr for item in node.items
                   if _is_lockish(item.context_expr)]
        for item in node.items:
            ce = item.context_expr
            if isinstance(ce, ast.Call) and _spec_for_call(ce) is not None:
                self._with_acquire_calls.add(id(ce))
        for ce in lockish:
            self._lock_stack.append(_base_ident(ce) or "<lock>")
        self.generic_visit(node)
        for _ in lockish:
            self._lock_stack.pop()

    visit_AsyncWith = visit_With

    def visit_Try(self, node):
        # mark the try-body statements of a silent broad-except swallow
        swallows = False
        for h in node.handlers:
            broad = h.type is None or (
                isinstance(h.type, ast.Name) and h.type.id in _BROAD_EXC
            )
            if not broad:
                continue
            body_is_silent = all(
                isinstance(s, ast.Pass)
                or (isinstance(s, ast.Expr)
                    and isinstance(s.value, ast.Constant))
                for s in h.body
            )
            if body_is_silent:
                swallows = True
                handler_line = h.body[0].lineno if h.body else h.lineno
        if swallows:
            for s in node.body:
                self._swallow_trys[id(s)] = handler_line
        for s in node.body:
            self.visit(s)
        self._except_depth += 1
        for h in node.handlers:
            for s in h.body:
                self.visit(s)
        self._except_depth -= 1
        for s in node.orelse:
            self.visit(s)
        self._finally_depth += 1
        for s in node.finalbody:
            self.visit(s)
        self._finally_depth -= 1

    def visit_If(self, node):
        self.visit(node.test)
        self._if_tests.append(node.test)
        for s in node.body:
            self.visit(s)
        self._if_tests.pop()
        self._if_tests.append(ast.UnaryOp(op=ast.Not(), operand=node.test))
        for s in node.orelse:
            self.visit(s)
        self._if_tests.pop()

    # -- events -------------------------------------------------------------

    def visit_Assign(self, node):
        self.visit(node.value)
        for t in node.targets:
            self.visit(t)
        value = node.value
        handled = self._bind_acquires(node)
        # alias tracking: `v = lease` makes v carry the same obligation
        if isinstance(value, ast.Name):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    self.aliases[t.id] = self.aliases.get(value.id, value.id)
        for t in node.targets:
            if isinstance(t, ast.Name):
                self.assign_lines.setdefault(t.id, []).append(node.lineno)
            elif isinstance(t, (ast.Attribute, ast.Subscript)):
                # stored onto an object/container: ownership escapes
                self.escaped |= _bare_names(value)
        if isinstance(value, (ast.Dict, ast.List, ast.Tuple, ast.Set)):
            # packed into a container display: the container (not the bare
            # name) now carries the handle, and tracking where IT goes is
            # beyond a per-function pass — treat as ownership escape
            self.escaped |= _bare_names(value)
        if handled:
            return

    def _bind_acquires(self, assign: ast.Assign) -> bool:
        """Acquire calls anywhere in the RHS of `name = ...` bind the target
        name as the handle (wrappers like `io.run(rpc.connect(...))` or
        `await connect(...)` keep the resource behind the outer result)."""
        hit = False
        targets = [t for t in assign.targets if isinstance(t, ast.Name)]
        attr_target = any(isinstance(t, (ast.Attribute, ast.Subscript))
                          for t in assign.targets)
        for call in ast.walk(assign.value):
            if not isinstance(call, ast.Call) or id(call) in self._with_acquire_calls:
                continue
            spec = _spec_for_call(call)
            if spec is None or spec.arg_keyed:
                continue  # arg-keyed acquires are recorded from _on_call
            hit = True
            if attr_target and not targets:
                continue  # self.x = acquire(): ownership escapes
            if targets:
                self._record_acquire(spec, targets[0].id, call)
            else:
                self._record_acquire(spec, None, call)
        return hit

    def _record_acquire(self, spec, handle, call):
        token = None
        if spec.arg_keyed and call.args:
            token = ast.dump(call.args[0])
        _name, recv = _strip_remote(call.func)
        self.acquires.append(_Acquire(
            spec, handle, token, _recv_parts(recv), call.lineno,
            call.col_offset, tuple(self._lock_stack), call,
        ))

    def visit_Expr(self, node):
        # bare-statement acquire: handle (if any) is discarded on the spot
        swallow_line = self._swallow_trys.get(id(node))
        call = node.value
        while isinstance(call, ast.Await):
            call = call.value
        if isinstance(call, ast.Call) and id(call) not in self._with_acquire_calls:
            spec = _spec_for_call(call)
            if spec is not None and not spec.arg_keyed:
                self._record_acquire(spec, None, call)
        self._visit_expr_tree(node.value, swallow_line)

    def visit_Return(self, node):
        if node.value is not None:
            self.escaped |= _bare_names(node.value)
            self.visit(node.value)
        self.returns_while.append(node.lineno)

    def visit_Raise(self, node):
        self.generic_visit(node)
        self.returns_while.append(node.lineno)

    def _visit_expr_tree(self, expr, swallow_line=None):
        self.visit(expr) if not isinstance(expr, ast.Call) else None
        if isinstance(expr, ast.Call):
            self._on_call(expr, swallow_line)
            for a in expr.args:
                self.visit(a)
            for kw in expr.keywords:
                self.visit(kw.value)
            self.visit(expr.func)

    def visit_Call(self, node):
        self._on_call(node, None)
        self.generic_visit(node)

    def _on_call(self, node: ast.Call, swallow_line):
        self.call_lines.append(node.lineno)
        # Arg-keyed acquires (pin/incref/resources.acquire/assign) carry no
        # handle, so they are tracked from any expression position — an
        # `if not srv.pin(key):` guard is as much an acquire as a bare call.
        if id(node) not in self._with_acquire_calls:
            spec = _spec_for_call(node)
            if spec is not None and spec.arg_keyed:
                self._record_acquire(spec, None, node)
        name, recv = _strip_remote(node.func)
        if name in RELEASE_NAMES:
            base = _root_name(recv) if recv is not None else None
            token = ast.dump(node.args[0]) if node.args else None
            self.releases.append(_Release(
                name, recv, _recv_parts(recv), base, token, node.lineno,
                self._finally_depth > 0, self._except_depth > 0,
                list(self._if_tests), tuple(self._lock_stack), swallow_line,
            ))
        # call-arg escape: f(handle) hands ownership to the callee
        for a in node.args:
            self.escaped |= _bare_names(a)
        for kw in node.keywords:
            self.escaped |= _bare_names(kw.value)

    def visit_Name(self, node):
        if isinstance(node.ctx, ast.Load):
            self.loads.append((node.id, node.lineno, False))

    def visit_Attribute(self, node):
        # record the base name of `<name>.<rel>()` loads separately so the
        # double-release check can tell them from value uses
        if isinstance(node.value, ast.Name) and isinstance(
            node.value.ctx, ast.Load
        ):
            self.loads.append(
                (node.value.id, node.lineno, node.attr in RELEASE_NAMES)
            )
            return
        self.generic_visit(node)

    def visit_Yield(self, node):
        if node.value is not None:
            self.escaped |= _bare_names(node.value)
        self.generic_visit(node)

    def visit_YieldFrom(self, node):
        self.escaped |= _bare_names(node.value)
        self.generic_visit(node)


def _test_mentions(test: ast.expr, names: Set[str]) -> bool:
    for n in ast.walk(test):
        if isinstance(n, ast.Name) and n.id in names:
            return True
    return False


class _ClassInventory:
    """Per-class release-call facts for the class-managed exemption and
    RL802."""

    def __init__(self, tree: ast.AST):
        # class name -> method name -> list of (base ident, recv parts, rel)
        self.releases: Dict[str, Dict[str, List[Tuple[str, Set[str], str]]]] = {}
        self.methods: Dict[str, Set[str]] = {}
        for node in ast.walk(tree):
            if not isinstance(node, ast.ClassDef):
                continue
            per_method: Dict[str, List[Tuple[str, Set[str], str]]] = {}
            names: Set[str] = set()
            for item in node.body:
                if not isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                names.add(item.name)
                calls = []
                for n in ast.walk(item):
                    if not isinstance(n, ast.Call):
                        continue
                    name, recv = _strip_remote(n.func)
                    if name in RELEASE_NAMES and recv is not None:
                        calls.append((
                            _base_ident(recv) or "", _recv_parts(recv), name,
                        ))
                per_method[item.name] = calls
            self.releases[node.name] = per_method
            self.methods[node.name] = names

    def class_managed(self, cls: Optional[str], recv_parts: Set[str],
                      rel_names: tuple) -> bool:
        """Does `cls` release this receiver in any non-__del__ method?"""
        if cls is None:
            return False
        for method, calls in self.releases.get(cls, {}).items():
            if method == "__del__":
                continue
            for _base, parts, rel in calls:
                if rel in rel_names and parts & recv_parts:
                    return True
        return False


class _LeakChecker:
    def __init__(self, ctx: FileContext, inv: _ClassInventory):
        self.ctx = ctx
        self.inv = inv
        self.findings: List[Finding] = []

    def check_module(self) -> "_LeakChecker":
        self._walk(self.ctx.tree, scope=[], cls=None)
        return self

    def _walk(self, node, scope: List[str], cls: Optional[str]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                self._check_class(child, scope + [child.name])
                self._walk(child, scope + [child.name], child.name)
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._check_function(child, scope + [child.name], cls)
                self._walk(child, scope + [child.name], None)
            else:
                self._walk(child, scope, cls)

    def _emit(self, line: int, code: str, message: str, scope: List[str]):
        self.findings.append(Finding(
            self.ctx.relpath, line, code, message,
            ".".join(scope) if scope else "<module>",
        ))

    # -- RL802 ---------------------------------------------------------------

    def _check_class(self, node: ast.ClassDef, scope: List[str]):
        per_method = self.inv.releases.get(node.name, {})
        del_calls = per_method.get("__del__")
        if not del_calls:
            return
        dels = next(
            (m for m in node.body
             if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))
             and m.name == "__del__"),
            None,
        )
        if dels is None:
            return
        for n in ast.walk(dels):
            if not isinstance(n, ast.Call):
                continue
            name, recv = _strip_remote(n.func)
            if name not in RELEASE_NAMES or recv is None:
                continue
            base = _base_ident(recv) or ""
            # delegation to the class's own public release path is the fix,
            # not the bug: `self.release()` in __del__ is a GC backstop
            if (_root_name(recv) == "self" and isinstance(recv, ast.Name)
                    and name in self.inv.methods.get(node.name, set())):
                continue
            elsewhere = False
            for method, calls in per_method.items():
                if method == "__del__":
                    continue
                if any(b == base and rel == name for b, _p, rel in calls):
                    elsewhere = True
                    break
            if not elsewhere:
                self._emit(
                    n.lineno, "RL802",
                    f"`{base}.{name}()` is reachable only from __del__: for a "
                    "cross-process resource (pin/lease/rank/conn) GC timing — "
                    "or a reference cycle that never collects — decides when "
                    "the peer frees it; add an explicit release path and keep "
                    "__del__ as the backstop",
                    scope + ["__del__"],
                )

    # -- per-function checks -------------------------------------------------

    def _check_function(self, fn, scope: List[str], cls: Optional[str]):
        scan = _FunctionScan(fn)
        canonical_escaped = {
            scan.aliases.get(n, n) for n in scan.escaped
        } | scan.escaped
        for acq in scan.acquires:
            if acq.handle is not None:
                acq.aliases = {
                    a for a, orig in scan.aliases.items()
                    if orig == acq.handle
                } | {acq.handle}
            if acq.spec.arg_keyed:
                self._check_arg_keyed(acq, scan, scope, cls, fn)
            else:
                self._check_handle(acq, scan, scope, canonical_escaped)
        self._check_rl804_swallow(scan, scope)

    def _releases_for_handle(self, acq: _Acquire, scan: _FunctionScan):
        return [
            r for r in scan.releases
            if r.base_name in acq.aliases and r.name in acq.spec.release
            and r.line >= acq.line
        ]

    def _check_handle(self, acq, scan, scope, escaped: Set[str]):
        if acq.handle is None:
            self._emit(
                acq.line, "RL801",
                f"{acq.spec.kind} acquired by `{acq.spec.acquire}(...)` and "
                "discarded: the handle (and its release obligation) is lost "
                "on the spot — bind it and release in a finally, or use "
                "`with`",
                scope,
            )
            return
        if acq.aliases & escaped:
            return  # ownership left this function
        rels = self._releases_for_handle(acq, scan)
        if not rels:
            self._emit(
                acq.line, "RL801",
                f"{acq.spec.kind} `{acq.handle}` is never released on any "
                f"path of this function (and neither returned, stored, nor "
                f"passed on): release it in a finally or use `with "
                f"{acq.spec.acquire}(...)`",
                scope,
            )
            return
        if any(r.in_finally for r in rels):
            self._check_rl803(acq, rels, scan, scope)
            self._check_rl804_locks(acq, rels, scope)
            return
        # conditional release: guarded by something other than the handle
        handle_names = set(acq.aliases)
        conditional = [
            r for r in rels
            if r.in_except or any(
                not _test_mentions(t, handle_names) for t in r.if_tests
            )
        ]
        if len(conditional) == len(rels):
            self._emit(
                acq.line, "RL801",
                f"{acq.spec.kind} `{acq.handle}` is released only on some "
                "paths (the release sits under a condition/except that does "
                "not test the handle itself): paths that skip it leak the "
                "resource — release in a finally",
                scope,
            )
            return
        first = min(r.line for r in rels if r not in conditional)
        risky = [
            ln for ln in scan.call_lines
            if acq.line < ln < first
        ]
        if risky:
            self._emit(
                acq.line, "RL801",
                f"{acq.spec.kind} `{acq.handle}` is released only on the "
                f"fall-through path: the call(s) between acquire (line "
                f"{acq.line}) and release (line {first}) can raise and leak "
                "it — move the release into a finally or use `with`",
                scope,
            )
        self._check_rl803(acq, rels, scan, scope)
        self._check_rl804_locks(acq, rels, scope)

    def _check_arg_keyed(self, acq, scan, scope, cls, fn):
        if self.inv.class_managed(cls, acq.recv_parts,
                                  acq.spec.receiver_release):
            return
        rels = [
            r for r in scan.releases
            if r.name in acq.spec.receiver_release
            and r.recv_parts & acq.recv_parts
            and (acq.token is None or r.token == acq.token)
            and r.line >= acq.line
        ]
        if not rels:
            self._emit(
                acq.line, "RL801",
                f"{acq.spec.kind} acquired here is never released in this "
                f"function (no matching "
                f"`.{'/'.join(acq.spec.receiver_release)}(...)` on the same "
                "receiver and key), and no owning class provides a release "
                "path: pair it in a finally or give the owner an explicit "
                "release method",
                scope,
            )
            return
        if any(r.in_finally for r in rels):
            self._check_rl804_locks(acq, rels, scope)
            return
        first = min(r.line for r in rels)
        risky = [ln for ln in scan.call_lines if acq.line < ln < first]
        if risky:
            self._emit(
                acq.line, "RL801",
                f"{acq.spec.kind} acquired on line {acq.line} is released on "
                f"line {first} with raise-capable calls in between and no "
                "finally: the error path leaks it",
                scope,
            )
        self._check_rl804_locks(acq, rels, scope)

    def _check_rl803(self, acq, rels, scan, scope):
        """Straight-line use-after-release / double-release, forgiving
        rebinds (`v = chan.read_view()` again) between the two sites."""
        first_rel = min(r.line for r in rels)
        assigns = []
        for name in acq.aliases:
            assigns.extend(scan.assign_lines.get(name, []))
        reported_double = False
        for name, line, is_rel_base in scan.loads:
            if name not in acq.aliases or line <= first_rel:
                continue
            if any(first_rel < a <= line for a in assigns):
                continue
            if is_rel_base:
                if any(r.line == line and r.in_finally for r in rels):
                    continue  # the finally release IS the first release
                if not reported_double:
                    self._emit(
                        line, "RL803",
                        f"`{name}` is released again on line {line} after the "
                        f"release on line {first_rel} (no re-acquire in "
                        "between): double-release — even an idempotent "
                        "release here usually means two owners disagree",
                        scope,
                    )
                    reported_double = True
            else:
                self._emit(
                    line, "RL803",
                    f"`{name}` is used on line {line} after its release on "
                    f"line {first_rel}: the slot/blocks behind it may already "
                    "be recycled — move the use before the release",
                    scope,
                )

    def _check_rl804_locks(self, acq, rels, scope):
        if not acq.lock_stack:
            return
        for r in rels:
            if r.lock_stack and r.lock_stack[-1] != acq.lock_stack[-1]:
                self._emit(
                    r.line, "RL804",
                    f"release performed under lock `{r.lock_stack[-1]}` but "
                    f"the acquire on line {acq.line} ran under "
                    f"`{acq.lock_stack[-1]}`: the two sections do not "
                    "exclude each other, so release can race the acquire's "
                    "bookkeeping — use one lock for both sides",
                    scope,
                )

    def _check_rl804_swallow(self, scan, scope):
        for r in scan.releases:
            if r.swallowed_line is None:
                continue
            if r.name not in _RL804_RELEASE_NAMES:
                continue
            # an explanatory comment in the handler documents the swallow
            if any(
                ln in self.ctx.comment_lines
                for ln in range(r.line, r.swallowed_line + 2)
            ):
                continue
            self._emit(
                r.line, "RL804",
                f"a failing `.{r.name}()` is silently swallowed by the bare "
                "except below: if the release raises, the resource stays "
                "held and nothing ever reports it — log, comment, or "
                "narrow the except",
                scope,
            )


def check_leak_file(ctx: FileContext) -> List[Finding]:
    inv = _ClassInventory(ctx.tree)
    checker = _LeakChecker(ctx, inv).check_module()
    # __del__ bodies are exempt from the swallow check (a destructor must
    # never raise; RL802 owns the __del__ plane), so drop those here where
    # the symbol is known.
    return [
        f for f in checker.findings
        if not (f.code == "RL804" and f.symbol.endswith("__del__"))
    ]
