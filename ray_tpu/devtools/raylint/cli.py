"""raylint command line: `python -m ray_tpu.devtools.raylint <paths...>`.

Exit-status contract (stable; CI consumers key off it):

- 0 — clean: every finding is suppressed inline or grandfathered in the
  baseline (a run with ONLY baselined findings exits 0, with or without
  `--no-baseline` — that flag widens what is *reported*, never what fails).
- 1 — at least one non-baselined violation (or, with `--fail-stale`,
  a stale baseline entry).
- 2 — usage error (unknown code in --select, --only pattern matching no
  code, bad flag value).

`--select` (exact codes), `--only` (patterns like RL8xx), and `--family`
(concurrency/jax/leak) narrow which findings and stale entries COUNT; they
never change how the exit status is derived — each lint plane can therefore
run and be gated independently under the same contract.

Output formats:

- text (default): one `file:line CODE message` per violation — what editors
  and humans consume. `--no-baseline` additionally prints grandfathered
  findings with a trailing `[baselined]` marker.
- `--format json`: a single JSON document with `violations`, `baselined`,
  `stale_baseline_entries`, `summary`, and `exit` keys — what CI consumes.
"""

from __future__ import annotations

import argparse
import json
import sys

from ray_tpu.devtools.raylint.core import (
    CODES,
    FAMILIES,
    Finding,
    emit_baseline,
    lint_paths,
    load_baseline,
    partition_baselined,
)


def _expand_only(patterns: str) -> set[str] | None:
    """`--only RL8xx,RL101` -> concrete code set. A trailing run of `x`s is a
    wildcard over the tail (`RL8xx` = every RL8 code); unknown patterns are a
    usage error (None)."""
    out: set[str] = set()
    for raw in patterns.split(","):
        pat = raw.strip()
        if not pat:
            continue
        stripped = pat.rstrip("xX")
        matched = {
            c for c in CODES
            if c == pat or (len(stripped) < len(pat) and c.startswith(stripped)
                            and len(c) == len(pat))
        }
        if not matched:
            return None
        out |= matched
    return out


def _changed_python_files() -> list[str] | None:
    """The union of unstaged, staged, and untracked .py files in the git
    repository at the current directory (for `--changed` pre-commit runs).
    Returns None when git is unavailable — a usage error upstream."""
    import os
    import subprocess

    cmds = [
        ["git", "rev-parse", "--show-toplevel"],
        ["git", "diff", "--name-only", "--diff-filter=d"],
        ["git", "diff", "--name-only", "--diff-filter=d", "--cached"],
        ["git", "ls-files", "--others", "--exclude-standard"],
    ]
    outputs = []
    for cmd in cmds:
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True)
        except OSError:
            return None
        if proc.returncode != 0:
            return None
        outputs.append(proc.stdout)
    root = outputs[0].strip()
    seen: set[str] = set()
    out: list[str] = []
    for listing in outputs[1:]:
        for rel in listing.splitlines():
            rel = rel.strip()
            if not rel.endswith(".py") or rel in seen:
                continue
            seen.add(rel)
            abspath = os.path.join(root, rel)
            if os.path.isfile(abspath):
                out.append(abspath)
    return sorted(out)


def _finding_dict(f: Finding) -> dict:
    return {"file": f.path, "line": f.line, "code": f.code,
            "symbol": f.symbol, "message": f.message}


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="raylint",
        description="framework-aware static analysis for the ray_tpu "
                    "control plane (RL1xx-RL5xx), JAX compute plane "
                    "(RL6xx/RL7xx), resource-lifetime plane (RL8xx), "
                    "distributed-contract plane (RL9xx), and cross-process "
                    "call-contract plane (RL10xx)",
    )
    parser.add_argument("paths", nargs="*", default=["ray_tpu"],
                        help="files or directories to lint")
    parser.add_argument("--baseline", default=None,
                        help="baseline JSON path (default: the checked-in "
                             "ray_tpu/devtools/raylint/baseline.json)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="also REPORT grandfathered findings (marked "
                             "[baselined]); does not change the exit status")
    parser.add_argument("--emit-baseline", action="store_true",
                        help="print a baseline JSON scaffold for the current "
                             "findings and exit 0 (justifications must be "
                             "filled in by hand)")
    parser.add_argument("--select", default=None,
                        help="comma-separated codes to run (default: all)")
    parser.add_argument("--only", default=None,
                        help="comma-separated code patterns to run; a "
                             "trailing run of x's wildcards the tail "
                             "(e.g. RL8xx = the whole leaklint family)")
    parser.add_argument("--family", default=None,
                        help="run one or more checker families, comma-"
                             "separated (concurrency = RL1xx-RL5xx, jax = "
                             "RL6xx/RL7xx, leak = RL8xx, dist = RL9xx, "
                             "api = RL10xx); "
                             "composable with --select/--only (union). The "
                             "exit contract is unchanged: filters narrow "
                             "which findings (and stale entries) count, "
                             "never how the exit status is derived")
    parser.add_argument("--changed", action="store_true",
                        help="lint only the .py files git reports as "
                             "changed (unstaged + staged + untracked) in "
                             "the repository at the current directory — the "
                             "fast pre-commit run. Positional paths are "
                             "ignored; findings, baseline, and exit "
                             "contract are unchanged")
    parser.add_argument("--codes", action="store_true",
                        help="list checker codes and exit")
    parser.add_argument("--format", choices=("text", "json"), default="text",
                        help="output format (json emits one document with "
                             "violations/baselined/stale/summary/exit)")
    parser.add_argument("--show-stale", action="store_true",
                        help="also report baseline entries that no longer "
                             "match any finding")
    parser.add_argument("--fail-stale", action="store_true",
                        help="exit 1 when stale baseline entries exist even "
                             "if there are no violations")
    args = parser.parse_args(argv)

    if args.codes:
        for code in sorted(CODES):
            print(f"{code}  {CODES[code]}")
        return 0

    codes = None
    selected: set[str] = set()
    if args.select:
        picked = {c.strip() for c in args.select.split(",") if c.strip()}
        unknown = picked - set(CODES)
        if unknown:
            print(f"unknown code(s): {sorted(unknown)}", file=sys.stderr)
            return 2
        selected |= picked
    if args.only:
        expanded = _expand_only(args.only)
        if expanded is None:
            print(f"--only pattern matches no known code: {args.only}",
                  file=sys.stderr)
            return 2
        selected |= expanded
    if args.family:
        picked = {f.strip() for f in args.family.split(",") if f.strip()}
        unknown = picked - set(FAMILIES)
        if unknown:
            print(
                f"unknown family(ies): {sorted(unknown)} "
                f"(known: {', '.join(sorted(FAMILIES))})", file=sys.stderr,
            )
            return 2
        for fam in picked:
            selected |= FAMILIES[fam]
    if selected:
        codes = selected

    paths = args.paths
    if args.changed:
        paths = _changed_python_files()
        if paths is None:
            print("--changed requires a git checkout (git not available or "
                  "not a repository)", file=sys.stderr)
            return 2

    findings = lint_paths(paths, codes=codes)

    if args.emit_baseline:
        json.dump(emit_baseline(findings), sys.stdout, indent=2)
        print()
        return 0

    entries = load_baseline(args.baseline)
    violations, grandfathered, stale = partition_baselined(findings, entries)
    # A --select run only sees a slice of the findings, so entries covering
    # unselected codes are not "stale" in any actionable sense.
    if codes:
        stale = [e for e in stale if e.get("code") in codes]
    # A --changed run only sees a slice of the files: entries for the
    # unchanged rest of the tree never had the chance to match.
    if args.changed:
        stale = []

    rc = 1 if violations or (args.fail_stale and stale) else 0

    if args.format == "json":
        doc = {
            "violations": [_finding_dict(f) for f in violations],
            "baselined": [_finding_dict(f) for f in grandfathered],
            "stale_baseline_entries": stale,
            "summary": {
                "violations": len(violations),
                "baselined": len(grandfathered),
                "stale": len(stale),
            },
            "exit": rc,
        }
        json.dump(doc, sys.stdout, indent=2)
        print()
        return rc

    for f in violations:
        print(f.render())
    if args.no_baseline:
        for f in grandfathered:
            print(f"{f.render()} [baselined]")
    if args.show_stale or args.fail_stale:
        for e in stale:
            print(
                f"stale baseline entry: {e.get('file')} {e.get('code')} "
                f"{e.get('symbol')} ({e.get('reason')})",
                file=sys.stderr,
            )
    if violations:
        print(
            f"raylint: {len(violations)} violation(s) "
            f"({len(grandfathered)} baselined)",
            file=sys.stderr,
        )
    return rc


if __name__ == "__main__":
    sys.exit(main())
