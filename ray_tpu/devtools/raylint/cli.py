"""raylint command line: `python -m ray_tpu.devtools.raylint <paths...>`.

Exit-status contract (stable; CI consumers key off it):

- 0 — clean: every finding is suppressed inline or grandfathered in the
  baseline (a run with ONLY baselined findings exits 0, with or without
  `--no-baseline` — that flag widens what is *reported*, never what fails).
- 1 — at least one non-baselined violation (or, with `--fail-stale`,
  a stale baseline entry).
- 2 — usage error (unknown code in --select, bad flag value).

Output formats:

- text (default): one `file:line CODE message` per violation — what editors
  and humans consume. `--no-baseline` additionally prints grandfathered
  findings with a trailing `[baselined]` marker.
- `--format json`: a single JSON document with `violations`, `baselined`,
  `stale_baseline_entries`, `summary`, and `exit` keys — what CI consumes.
"""

from __future__ import annotations

import argparse
import json
import sys

from ray_tpu.devtools.raylint.core import (
    CODES,
    Finding,
    emit_baseline,
    lint_paths,
    load_baseline,
    partition_baselined,
)


def _finding_dict(f: Finding) -> dict:
    return {"file": f.path, "line": f.line, "code": f.code,
            "symbol": f.symbol, "message": f.message}


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="raylint",
        description="framework-aware static analysis for the ray_tpu "
                    "control plane (RL1xx-RL5xx) and JAX compute plane "
                    "(RL6xx/RL7xx)",
    )
    parser.add_argument("paths", nargs="*", default=["ray_tpu"],
                        help="files or directories to lint")
    parser.add_argument("--baseline", default=None,
                        help="baseline JSON path (default: the checked-in "
                             "ray_tpu/devtools/raylint/baseline.json)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="also REPORT grandfathered findings (marked "
                             "[baselined]); does not change the exit status")
    parser.add_argument("--emit-baseline", action="store_true",
                        help="print a baseline JSON scaffold for the current "
                             "findings and exit 0 (justifications must be "
                             "filled in by hand)")
    parser.add_argument("--select", default=None,
                        help="comma-separated codes to run (default: all)")
    parser.add_argument("--codes", action="store_true",
                        help="list checker codes and exit")
    parser.add_argument("--format", choices=("text", "json"), default="text",
                        help="output format (json emits one document with "
                             "violations/baselined/stale/summary/exit)")
    parser.add_argument("--show-stale", action="store_true",
                        help="also report baseline entries that no longer "
                             "match any finding")
    parser.add_argument("--fail-stale", action="store_true",
                        help="exit 1 when stale baseline entries exist even "
                             "if there are no violations")
    args = parser.parse_args(argv)

    if args.codes:
        for code in sorted(CODES):
            print(f"{code}  {CODES[code]}")
        return 0

    codes = None
    if args.select:
        codes = {c.strip() for c in args.select.split(",") if c.strip()}
        unknown = codes - set(CODES)
        if unknown:
            print(f"unknown code(s): {sorted(unknown)}", file=sys.stderr)
            return 2

    findings = lint_paths(args.paths, codes=codes)

    if args.emit_baseline:
        json.dump(emit_baseline(findings), sys.stdout, indent=2)
        print()
        return 0

    entries = load_baseline(args.baseline)
    violations, grandfathered, stale = partition_baselined(findings, entries)
    # A --select run only sees a slice of the findings, so entries covering
    # unselected codes are not "stale" in any actionable sense.
    if codes:
        stale = [e for e in stale if e.get("code") in codes]

    rc = 1 if violations or (args.fail_stale and stale) else 0

    if args.format == "json":
        doc = {
            "violations": [_finding_dict(f) for f in violations],
            "baselined": [_finding_dict(f) for f in grandfathered],
            "stale_baseline_entries": stale,
            "summary": {
                "violations": len(violations),
                "baselined": len(grandfathered),
                "stale": len(stale),
            },
            "exit": rc,
        }
        json.dump(doc, sys.stdout, indent=2)
        print()
        return rc

    for f in violations:
        print(f.render())
    if args.no_baseline:
        for f in grandfathered:
            print(f"{f.render()} [baselined]")
    if args.show_stale or args.fail_stale:
        for e in stale:
            print(
                f"stale baseline entry: {e.get('file')} {e.get('code')} "
                f"{e.get('symbol')} ({e.get('reason')})",
                file=sys.stderr,
            )
    if violations:
        print(
            f"raylint: {len(violations)} violation(s) "
            f"({len(grandfathered)} baselined)",
            file=sys.stderr,
        )
    return rc


if __name__ == "__main__":
    sys.exit(main())
