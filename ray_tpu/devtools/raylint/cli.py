"""raylint command line: `python -m ray_tpu.devtools.raylint <paths...>`.

Exit status: 0 when every finding is suppressed or baselined, 1 otherwise
(2 for usage errors). Output is one `file:line CODE message` per violation —
the format the tier-1 gate and editors both consume.
"""

from __future__ import annotations

import argparse
import json
import sys

from ray_tpu.devtools.raylint.core import (
    CODES,
    emit_baseline,
    lint_paths,
    load_baseline,
    partition_baselined,
)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="raylint",
        description="framework-aware static analysis for the ray_tpu "
                    "control plane",
    )
    parser.add_argument("paths", nargs="*", default=["ray_tpu"],
                        help="files or directories to lint")
    parser.add_argument("--baseline", default=None,
                        help="baseline JSON path (default: the checked-in "
                             "ray_tpu/devtools/raylint/baseline.json)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="report grandfathered findings too")
    parser.add_argument("--emit-baseline", action="store_true",
                        help="print a baseline JSON scaffold for the current "
                             "findings and exit 0 (justifications must be "
                             "filled in by hand)")
    parser.add_argument("--select", default=None,
                        help="comma-separated codes to run (default: all)")
    parser.add_argument("--codes", action="store_true",
                        help="list checker codes and exit")
    parser.add_argument("--show-stale", action="store_true",
                        help="also report baseline entries that no longer "
                             "match any finding")
    args = parser.parse_args(argv)

    if args.codes:
        for code in sorted(CODES):
            print(f"{code}  {CODES[code]}")
        return 0

    codes = None
    if args.select:
        codes = {c.strip() for c in args.select.split(",") if c.strip()}
        unknown = codes - set(CODES)
        if unknown:
            print(f"unknown code(s): {sorted(unknown)}", file=sys.stderr)
            return 2

    findings = lint_paths(args.paths, codes=codes)

    if args.emit_baseline:
        json.dump(emit_baseline(findings), sys.stdout, indent=2)
        print()
        return 0

    entries = [] if args.no_baseline else load_baseline(args.baseline)
    violations, grandfathered, stale = partition_baselined(findings, entries)

    for f in violations:
        print(f.render())
    if args.show_stale:
        for e in stale:
            print(
                f"stale baseline entry: {e.get('file')} {e.get('code')} "
                f"{e.get('symbol')} ({e.get('reason')})",
                file=sys.stderr,
            )
    if violations:
        print(
            f"raylint: {len(violations)} violation(s) "
            f"({len(grandfathered)} baselined)",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
