"""distlint: the RL9xx distributed-contract family.

Five PRs in a row re-learned the same distributed-plane invariants by
review comment; these checkers make them machine-enforced:

- **RL901** metric mutation outside a report path. Every `Counter.inc` /
  `Gauge.set` / `Histogram.observe` may flush — and a flush IS a blocking
  GCS RPC (`util/metrics.py _maybe_flush`). Mutations are therefore only
  allowed from the declared report-path roster (`stats`, `scheduler_stats`,
  `recorder_stats`, `report`, `control_plane_stats`) and from helpers the
  call graph proves are reached exclusively from those (the same fixpoint
  shape as jaxlint's hot-context analysis, inverted).
- **RL902** blocking control-plane RPC (`gcs_call`, KV verbs, by-name actor
  lookup, rpc `connect`) in a `__del__`/weakref finalizer, under a held
  sync lock, or in a scheduler/decode hot context.
- **RL903** exception classes that don't survive a `.remote()`/RPC hop:
  a custom `__init__` whose `super().__init__(...)` args are not exactly
  its own positional parameters means default pickling re-calls the class
  with the FORMATTED message, shifting it into the first parameter slot —
  define `__reduce__` (the `exceptions.py` idiom) or forward args verbatim.
- **RL904** trace context read on the wrong side of an executor/thread
  boundary: `tracing.current()` / `tracing.propagation_context()` inside a
  callback handed to `run_in_executor` / `executor.submit` /
  `Thread(target=...)` reads an EMPTY context (contextvars do not cross
  threads) — capture `trace_ctx` before the hop and pass it explicitly.
- **RL905** `await` of a cross-process call (`.remote()`, gcs verbs, or an
  in-file helper that transitively performs one) while holding an
  `async with <lock>` — the RL101 contract extended to the RPC layer —
  plus the interprocedural shape RL902 can't see: a call under a held sync
  lock to an in-file helper that transitively blocks on the control plane.

All five run over every file (no import gate): the contracts are properties
of the control plane, not of any one library's API.
"""

from __future__ import annotations

import ast
from typing import Optional

from ray_tpu.devtools.raylint.core import FileContext, Finding

from ray_tpu.devtools.raylint.checkers import (  # shared identity helpers
    _base_ident,
    _ident_parts,
    _is_lockish,
    _root_name,
)

#: The declared report-path roster (docs/raylint.md §RL901): functions whose
#: JOB is to assemble/flush observability state, where a metrics flush (a GCS
#: round-trip) is the contract rather than a hazard.
REPORT_ROSTER = frozenset({
    "stats", "scheduler_stats", "recorder_stats", "report",
    "control_plane_stats",
})

_METRIC_CTORS = frozenset({"Counter", "Gauge", "Histogram"})
#: Metric mutators (and the explicit flush): each one may perform the
#: rate-limited GCS kv_put.
_METRIC_MUTATORS = frozenset({"inc", "set", "observe", "flush"})

_KV_VERBS = frozenset({"kv_get", "kv_put", "kv_del", "kv_keys"})
#: Receiver ident parts that mark a bare `connect()` as a control-plane dial.
_RPC_RECEIVER_PARTS = frozenset({
    "gcs", "rpc", "conn", "client", "stub", "channel", "raylet",
})
#: Function name parts that mark a frame as a scheduler/decode hot context.
_HOT_NAME_PARTS = frozenset({"decode", "schedule", "scheduler"})

_TRACE_READS = frozenset({"current", "propagation_context"})

#: `leaf name -> positional index of the callback` for executor/thread
#: hand-off calls (run_in_executor's arg 0 is the executor itself).
_HANDOFF_CALLBACK_POS = {"run_in_executor": 1, "submit": 0}
_SUBMIT_RECEIVER_PARTS = frozenset({"executor", "executors", "pool", "pools"})


def _leaf_name(func: ast.expr) -> Optional[str]:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _is_gcs_rpc(node: ast.Call) -> Optional[str]:
    """The control-plane RPC verbs RL902/RL905 reason about. Returns a short
    description or None."""
    leaf = _leaf_name(node.func)
    if leaf == "gcs_call":
        verb = ""
        if node.args and isinstance(node.args[0], ast.Constant) and isinstance(
            node.args[0].value, str
        ):
            verb = f"({node.args[0].value!r})"
        return f"gcs_call{verb}"
    if leaf in _KV_VERBS:
        return leaf
    if leaf == "get_actor" and node.args and isinstance(
        node.args[0], ast.Constant
    ) and isinstance(node.args[0].value, str):
        return "by-name get_actor"
    if leaf == "connect" and isinstance(node.func, ast.Attribute):
        receiver = _base_ident(node.func.value)
        root = _root_name(node.func.value)
        parts = set()
        if receiver:
            parts |= _ident_parts(receiver)
        if root:
            parts |= _ident_parts(root)
        if parts & _RPC_RECEIVER_PARTS:
            return "rpc connect"
    return None


def _is_remote_call(node: ast.Call) -> bool:
    """`handle.method.remote(...)` / `actor.remote(...)` — a cross-process
    submission."""
    return isinstance(node.func, ast.Attribute) and node.func.attr == "remote"


def _is_tracing_read(node: ast.Call) -> bool:
    func = node.func
    if not isinstance(func, ast.Attribute) or func.attr not in _TRACE_READS:
        return False
    root = _root_name(func.value)
    return root == "tracing" or _base_ident(func.value) == "tracing"


def _contains_metric_ctor(value: ast.expr) -> bool:
    for node in ast.walk(value):
        if isinstance(node, ast.Call):
            leaf = _leaf_name(node.func)
            if leaf in _METRIC_CTORS:
                return True
    return False


def _is_hot_named(name: str) -> bool:
    return bool(_ident_parts(name) & _HOT_NAME_PARTS)


class _Prepass(ast.NodeVisitor):
    """File-wide facts the per-node checks key off: which names hold metrics,
    the in-file call graph (and its report-path / rpc / trace-read closures),
    and which functions are weakref finalizers."""

    def __init__(self, tree: ast.AST):
        # -- metric identity -------------------------------------------------
        self.metric_attrs: set[str] = set()     # self.<attr> = Counter(...)
        self.metric_names: set[str] = set()     # NAME = Gauge(...)
        self.metric_factories: set[str] = set()  # def f(): return {..Counter..}
        self._assigned_from_call: list[tuple[str, str]] = []  # (name, callee)
        # -- call graph ------------------------------------------------------
        self._calls_all: dict[str, set[str]] = {}
        self._calls_in_loops: dict[str, set[str]] = {}
        # -- per-function direct facts ---------------------------------------
        self._direct_rpc: set[str] = set()
        self._direct_remote: set[str] = set()
        self._direct_trace_read: set[str] = set()
        self.finalizer_funcs: set[str] = set()  # weakref.finalize callbacks
        self.defined_funcs: set[str] = set()
        self.async_funcs: set[str] = set()      # bare call = coroutine object
        self._scope: list[str] = []
        self._loop_depth = 0
        self.visit(tree)
        self._close()

    def _fn_key(self) -> str:
        return ".".join(self._scope)

    # -- structure -----------------------------------------------------------

    def visit_ClassDef(self, node: ast.ClassDef):
        self._scope.append(node.name)
        self.generic_visit(node)
        self._scope.pop()

    def _visit_fn(self, node):
        self.defined_funcs.add(node.name)
        self._scope.append(node.name)
        saved = self._loop_depth
        self._loop_depth = 0
        self._calls_all.setdefault(self._fn_key(), set())
        self.generic_visit(node)
        self._loop_depth = saved
        self._scope.pop()

    visit_FunctionDef = _visit_fn

    def visit_AsyncFunctionDef(self, node):
        self.async_funcs.add(node.name)
        self._visit_fn(node)

    def _visit_loop(self, node):
        self._loop_depth += 1
        self.generic_visit(node)
        self._loop_depth -= 1

    visit_For = _visit_loop
    visit_AsyncFor = _visit_loop
    visit_While = _visit_loop

    # -- facts ---------------------------------------------------------------

    def _note_metric_target(self, target: ast.expr):
        if isinstance(target, ast.Name):
            self.metric_names.add(target.id)
        elif isinstance(target, ast.Attribute) and _root_name(target) in (
            "self", "cls"
        ):
            self.metric_attrs.add(target.attr)
        elif isinstance(target, ast.Subscript):
            ident = _base_ident(target)
            if ident:
                self.metric_attrs.add(ident)

    def visit_Assign(self, node: ast.Assign):
        if _contains_metric_ctor(node.value):
            for t in node.targets:
                self._note_metric_target(t)
        elif isinstance(node.value, ast.Call):
            callee = _leaf_name(node.value.func)
            if callee:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self._assigned_from_call.append((t.id, callee))
                    elif isinstance(t, ast.Attribute) and _root_name(t) in (
                        "self", "cls"
                    ):
                        self._assigned_from_call.append((t.attr, callee))
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign):
        if node.value is not None and _contains_metric_ctor(node.value):
            self._note_metric_target(node.target)
        self.generic_visit(node)

    def visit_Return(self, node: ast.Return):
        if node.value is not None and self._scope and _contains_metric_ctor(
            node.value
        ):
            self.metric_factories.add(self._scope[-1])
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        leaf = _leaf_name(node.func)
        # weakref.finalize(obj, callback, ...): the callback runs at GC time
        # with the same constraints as __del__.
        if leaf == "finalize" and len(node.args) >= 2:
            cb = node.args[1]
            cb_leaf = (cb.id if isinstance(cb, ast.Name)
                       else cb.attr if isinstance(cb, ast.Attribute) else None)
            if cb_leaf:
                self.finalizer_funcs.add(cb_leaf)
        if self._scope:
            key = self._fn_key()
            if _is_gcs_rpc(node):
                self._direct_rpc.add(self._scope[-1])
            if _is_remote_call(node):
                self._direct_remote.add(self._scope[-1])
            if _is_tracing_read(node):
                self._direct_trace_read.add(self._scope[-1])
            callee = None
            if isinstance(node.func, ast.Name):
                callee = node.func.id
            elif isinstance(node.func, ast.Attribute) and _root_name(
                node.func
            ) in ("self", "cls"):
                callee = node.func.attr
            if callee:
                self._calls_all.setdefault(key, set()).add(callee)
                if self._loop_depth:
                    self._calls_in_loops.setdefault(key, set()).add(callee)
        self.generic_visit(node)

    # -- closures ------------------------------------------------------------

    def _close(self):
        # Resolve `m = self._metrics()` once factories are known (one round
        # is enough: factories are direct `return {…Counter…}` shapes).
        for name, callee in self._assigned_from_call:
            if callee in self.metric_factories:
                self.metric_names.add(name)

        # callers map by trailing name segment (self.foo() can't see which
        # class defines foo — same convention as jaxlint).
        callers: dict[str, set[str]] = {}
        for key, callees in self._calls_all.items():
            leaf = key.rsplit(".", 1)[-1]
            for callee in callees:
                callers.setdefault(callee, set()).add(leaf)

        # report paths: the roster, plus functions whose every in-file caller
        # is already a report path (and that have at least one caller).
        report = {f for f in self.defined_funcs if f in REPORT_ROSTER}
        report |= REPORT_ROSTER
        changed = True
        while changed:
            changed = False
            for fn in self.defined_funcs:
                if fn in report:
                    continue
                cs = callers.get(fn)
                if cs and cs <= report:
                    report.add(fn)
                    changed = True
        self.report_paths = report

        # upward closure: a function that calls an rpc/trace-reading helper
        # has the property itself.
        def up_close(seed: set[str]) -> set[str]:
            out = set(seed)
            changed = True
            while changed:
                changed = False
                for key, callees in self._calls_all.items():
                    leaf = key.rsplit(".", 1)[-1]
                    if leaf not in out and callees & out:
                        out.add(leaf)
                        changed = True
            return out

        self.rpc_funcs = up_close(self._direct_rpc)
        self.crossproc_funcs = up_close(self._direct_rpc | self._direct_remote)
        self.trace_read_funcs = up_close(self._direct_trace_read)

        # hot contexts: loop-called callees of hot-named functions, closed
        # downward over the call graph (jaxlint's _compute_hot, seeded by
        # name instead of by any loop). Report paths are exempt from seeding:
        # `scheduler_stats` is named for the scheduler but IS the report
        # path, where control-plane round-trips are the contract.
        hot: set[str] = set()
        for key, callees in self._calls_in_loops.items():
            leaf = key.rsplit(".", 1)[-1]
            if _is_hot_named(leaf) and leaf not in self.report_paths:
                hot |= callees
        hot -= self.report_paths
        changed = True
        while changed:
            changed = False
            for key, callees in self._calls_all.items():
                leaf = key.rsplit(".", 1)[-1]
                if leaf in hot:
                    new = callees - hot
                    if new:
                        hot |= new
                        changed = True
        self.hot_funcs = hot


class _DistChecker(ast.NodeVisitor):
    def __init__(self, ctx: FileContext, pre: _Prepass):
        self.ctx = ctx
        self.pre = pre
        self.findings: list[Finding] = []
        self._scope: list[str] = []
        self._class_stack: list[str] = []
        self._fn_stack: list[str] = []       # function leaf names
        self._sync_locks = 0                 # held `with <lockish>:` depth
        self._async_locks = 0                # held `async with <lockish>:` depth
        self._loop_depth = 0

    # -- bookkeeping ---------------------------------------------------------

    def _symbol(self) -> str:
        return ".".join(self._scope) if self._scope else "<module>"

    def _emit(self, node: ast.AST, code: str, message: str):
        self.findings.append(Finding(
            self.ctx.relpath, getattr(node, "lineno", 0), code, message,
            self._symbol(),
        ))

    def visit_ClassDef(self, node: ast.ClassDef):
        self._scope.append(node.name)
        self._check_rl903_class(node)
        self._class_stack.append(node.name)
        self.generic_visit(node)
        self._class_stack.pop()
        self._scope.pop()

    def _visit_fn(self, node):
        self._scope.append(node.name)
        self._fn_stack.append(node.name)
        saved_loops, saved_sync, saved_async = (
            self._loop_depth, self._sync_locks, self._async_locks
        )
        self._loop_depth = 0
        # Locks held by the enclosing frame still constrain a nested def only
        # if it runs inline; a nested def is usually a callback — reset.
        self._sync_locks = self._async_locks = 0
        self.generic_visit(node)
        self._loop_depth, self._sync_locks, self._async_locks = (
            saved_loops, saved_sync, saved_async
        )
        self._fn_stack.pop()
        self._scope.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    def visit_Lambda(self, node: ast.Lambda):
        # A lambda body runs when the lambda is CALLED, not where it is
        # written: `conn.on_close(lambda c: self._lost(c))` under a lock
        # registers a callback — the lock is long released when it fires.
        saved_sync, saved_async = self._sync_locks, self._async_locks
        self._sync_locks = self._async_locks = 0
        self.generic_visit(node)
        self._sync_locks, self._async_locks = saved_sync, saved_async

    def _visit_loop(self, node):
        self._loop_depth += 1
        self.generic_visit(node)
        self._loop_depth -= 1

    visit_For = _visit_loop
    visit_AsyncFor = _visit_loop
    visit_While = _visit_loop

    def _visit_with(self, node, is_async: bool):
        lockish = sum(1 for item in node.items if _is_lockish(
            item.context_expr.func if isinstance(item.context_expr, ast.Call)
            else item.context_expr
        ))
        if is_async:
            self._async_locks += lockish
        else:
            self._sync_locks += lockish
        self.generic_visit(node)
        if is_async:
            self._async_locks -= lockish
        else:
            self._sync_locks -= lockish

    def visit_With(self, node: ast.With):
        self._visit_with(node, is_async=False)

    def visit_AsyncWith(self, node: ast.AsyncWith):
        self._visit_with(node, is_async=True)

    # -- context predicates --------------------------------------------------

    def _in_finalizer(self) -> bool:
        return any(
            fn == "__del__" or fn in self.pre.finalizer_funcs
            for fn in self._fn_stack
        )

    def _in_hot_context(self) -> bool:
        if not self._fn_stack:
            return False
        fn = self._fn_stack[-1]
        if self._in_report_path():
            return False
        # lexically inside a loop of a scheduler/decode-named function, or
        # anywhere inside a function the hot closure proved is called per
        # iteration of one.
        if self._loop_depth and _is_hot_named(fn):
            return True
        return fn in self.pre.hot_funcs

    def _in_report_path(self) -> bool:
        return bool(self._fn_stack) and any(
            fn in self.pre.report_paths for fn in self._fn_stack
        )

    # -- RL901 ---------------------------------------------------------------

    def _metric_receiver(self, recv: ast.expr) -> bool:
        """Is `recv` provably a Counter/Gauge/Histogram (or a series pulled
        out of a metrics dict/factory)?"""
        if isinstance(recv, ast.Name):
            return recv.id in self.pre.metric_names
        if isinstance(recv, ast.Attribute):
            if _root_name(recv) in ("self", "cls"):
                return recv.attr in self.pre.metric_attrs
            return recv.attr in self.pre.metric_names
        if isinstance(recv, ast.Subscript):
            ident = _base_ident(recv)
            if ident and (ident in self.pre.metric_attrs
                          or ident in self.pre.metric_names
                          or ident in self.pre.metric_factories):
                return True
            if isinstance(recv.value, ast.Call):
                leaf = _leaf_name(recv.value.func)
                return leaf in self.pre.metric_factories
            return False
        if isinstance(recv, ast.Call):
            return _leaf_name(recv.func) in self.pre.metric_factories
        return False

    def _check_rl901(self, node: ast.Call):
        func = node.func
        if not isinstance(func, ast.Attribute):
            return
        if func.attr not in _METRIC_MUTATORS:
            return
        if not self._metric_receiver(func.value):
            return
        if self._in_report_path():
            return
        where = self._fn_stack[-1] if self._fn_stack else "<module>"
        self._emit(
            node, "RL901",
            f"metric .{func.attr}() outside a report path (in {where!r}): "
            "every mutation may flush, and a flush is a blocking GCS RPC — "
            "accumulate plain counters on the data path and mutate/flush "
            "only from stats()/report()-roster functions",
        )

    # -- RL902 / RL905 -------------------------------------------------------

    def _check_call_contexts(self, node: ast.Call, awaited: bool):
        rpc = _is_gcs_rpc(node)
        if rpc is not None:
            if self._in_finalizer():
                self._emit(
                    node, "RL902",
                    f"blocking control-plane RPC ({rpc}) in a __del__/"
                    "finalizer: GC timing decides when (and on which thread) "
                    "the control plane is dialed — release explicitly and "
                    "make the finalizer a last-resort local cleanup",
                )
                return
            if self._sync_locks and not awaited:
                self._emit(
                    node, "RL902",
                    f"blocking control-plane RPC ({rpc}) under a held lock: "
                    "every thread contending for the lock stalls on the GCS "
                    "round-trip — copy state out, release, then call",
                )
                return
            if self._in_hot_context():
                self._emit(
                    node, "RL902",
                    f"blocking control-plane RPC ({rpc}) in a scheduler/"
                    "decode hot context: a per-iteration GCS round-trip "
                    "gates the hot loop on the control plane — batch it or "
                    "move it off the loop",
                )
                return
        # RL905(a): awaited cross-process call while an async lock is held.
        if awaited and self._async_locks and (
            rpc is not None or _is_remote_call(node)
            or (_leaf_name(node.func) in self.pre.crossproc_funcs
                and self._is_infile_callee(node))
        ):
            what = rpc or (
                ".remote()" if _is_remote_call(node)
                else f"{_leaf_name(node.func)}() [performs a cross-process "
                     "call]"
            )
            self._emit(
                node, "RL905",
                f"await of a cross-process call ({what}) while holding an "
                "async lock: the lock is held across a network round-trip, "
                "stalling every task contending for it — snapshot under the "
                "lock, release, then await",
            )
            return
        # RL905(b): the interprocedural shape RL902 can't see — a plain call
        # under a held sync lock to an in-file helper that transitively
        # blocks on the control plane. Bare calls to `async def` helpers are
        # exempt: they only BUILD a coroutine (io.spawn(self._resolve(...))
        # under a lock runs the body later, on the loop, lock released).
        if (
            not awaited
            and self._sync_locks
            and rpc is None
            and self._is_infile_callee(node)
            and _leaf_name(node.func) in self.pre.rpc_funcs
            and _leaf_name(node.func) not in self.pre.async_funcs
        ):
            self._emit(
                node, "RL905",
                f"{_leaf_name(node.func)}() performs a blocking control-"
                "plane RPC and is called under a held lock: the GCS round-"
                "trip happens with the lock held — hoist the call out of "
                "the critical section",
            )

    def _is_infile_callee(self, node: ast.Call) -> bool:
        """Only `name(...)` / `self.name(...)` calls resolve against the
        in-file call graph (arbitrary `obj.method()` would alias any
        same-named function anywhere)."""
        if isinstance(node.func, ast.Name):
            return node.func.id in self.pre.defined_funcs
        if isinstance(node.func, ast.Attribute) and _root_name(node.func) in (
            "self", "cls"
        ):
            return node.func.attr in self.pre.defined_funcs
        return False

    # -- RL903 ---------------------------------------------------------------

    def _check_rl903_class(self, node: ast.ClassDef):
        # A base-less class is not raisable: a plain `FooError` value wrapper
        # pickles by __dict__, so the args-based hazard does not apply.
        looks_exc = bool(node.bases) and (
            node.name.endswith(("Error", "Exception")) or any(
                isinstance(b, (ast.Name, ast.Attribute))
                and (_leaf_name(b) or "").endswith(("Error", "Exception"))
                for b in node.bases
            )
        )
        if not looks_exc:
            return
        init = None
        has_reduce = False
        for stmt in node.body:
            if isinstance(stmt, ast.FunctionDef):
                if stmt.name == "__init__":
                    init = stmt
                elif stmt.name in ("__reduce__", "__reduce_ex__",
                                   "__getnewargs__", "__getnewargs_ex__"):
                    has_reduce = True
        if init is None or has_reduce:
            return
        params = [a.arg for a in init.args.args[1:]]  # drop self
        if not params and not init.args.vararg:
            return
        # Find the super().__init__(...) call; verbatim positional forwarding
        # of the own parameter list round-trips under default pickling.
        for sub in ast.walk(init):
            if (
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr == "__init__"
                and isinstance(sub.func.value, ast.Call)
                and _leaf_name(sub.func.value.func) == "super"
            ):
                forwarded = [
                    a.id for a in sub.args if isinstance(a, ast.Name)
                ] if all(isinstance(a, ast.Name) for a in sub.args) else None
                if forwarded == params:
                    return  # verbatim forwarding: default pickling is stable
                break
        self._emit(
            node, "RL903",
            f"exception class {node.name} does not survive a .remote()/RPC "
            "hop: its __init__ formats/transforms its args, so default "
            "pickling re-calls the class with the FORMATTED message shifted "
            "into the first parameter — define __reduce__ returning "
            "(type(self), (<original ctor args>,)) like exceptions.py does",
        )

    # -- dispatch ------------------------------------------------------------

    def visit_Await(self, node: ast.Await):
        if isinstance(node.value, ast.Call):
            self._check_call_contexts(node.value, awaited=True)
            self._check_rl901(node.value)
            self._check_rl904(node.value)
            # visit arguments but not the call head again
            for arg in ast.iter_child_nodes(node.value):
                self.visit(arg)
            return
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        self._check_call_contexts(node, awaited=False)
        self._check_rl901(node)
        self._check_rl904(node)
        self.generic_visit(node)

    # -- RL904 ---------------------------------------------------------------

    def _callback_reads_trace(self, cb: ast.expr) -> bool:
        if isinstance(cb, ast.Lambda):
            return any(
                isinstance(sub, ast.Call) and _is_tracing_read(sub)
                for sub in ast.walk(cb.body)
            )
        leaf = None
        if isinstance(cb, ast.Name):
            leaf = cb.id
        elif isinstance(cb, ast.Attribute):
            leaf = cb.attr
        elif isinstance(cb, ast.Call):
            # functools.partial(fn, ...) — inspect the wrapped fn
            if _leaf_name(cb.func) == "partial" and cb.args:
                return self._callback_reads_trace(cb.args[0])
            return False
        return leaf is not None and leaf in self.pre.trace_read_funcs

    def _check_rl904(self, node: ast.Call):
        leaf = _leaf_name(node.func)
        cb = None
        if leaf == "run_in_executor" and len(node.args) >= 2:
            cb = node.args[1]
        elif leaf == "submit" and node.args and isinstance(
            node.func, ast.Attribute
        ):
            recv = _base_ident(node.func.value)
            if recv and _ident_parts(recv) & _SUBMIT_RECEIVER_PARTS:
                cb = node.args[0]
        elif leaf == "Thread":
            for kw in node.keywords:
                if kw.arg == "target":
                    cb = kw.value
                    break
        if cb is None or not self._callback_reads_trace(cb):
            return
        self._emit(
            node, "RL904",
            "trace context read inside a callback handed across an executor/"
            "thread boundary: contextvars do not cross threads, so "
            "tracing.current()/propagation_context() there reads an EMPTY "
            "context — capture trace_ctx before the hop and pass it "
            "explicitly (tracing.activate(trace_ctx) inside the callback)",
        )


def check_dist_file(ctx: FileContext) -> list[Finding]:
    pre = _Prepass(ctx.tree)
    checker = _DistChecker(ctx, pre)
    checker.visit(ctx.tree)
    return checker.findings
