"""The raylint checkers: one AST pass per file + a cross-file lock graph.

Identity conventions shared by all checkers:

- A "lock-ish" expression is a Name/Attribute/Subscript whose final
  identifier contains a lock word (lock, mutex, semaphore, cond, ...) when
  split on snake/camel boundaries: `self._state_lock`, `_global_lock`,
  `self.cond`, `self._stream_locks[j]`.
- Lock identity is class-qualified (`Worker._state_lock`) for `self`
  attributes and module-qualified (`worker._global_lock`) for globals, so the
  acquisition-order graph composes across files.
"""

from __future__ import annotations

import ast
import re
from typing import Optional

from ray_tpu.devtools.raylint.core import FileContext, Finding

_LOCK_WORDS = {
    "lock", "locks", "rlock", "mutex", "sem", "semaphore", "semaphores",
    "cond", "condition",
}

_MUTATOR_METHODS = {
    "append", "extend", "insert", "remove", "clear", "add", "discard",
    "update", "setdefault", "popitem", "sort", "reverse",
}

_COPY_CALLS = {"copy", "deepcopy", "replace", "dict", "list", "set", "tuple",
               "frozenset", "asdict", "astuple"}

_DISCARDED_CALL_ATTRS = {"remote", "execute", "execute_async"}

_BROAD_EXC = {"Exception", "BaseException"}


def _ident_parts(name: str) -> set[str]:
    name = re.sub(r"([a-z0-9])([A-Z])", r"\1_\2", name)
    return {p for p in name.lower().split("_") if p}


def _base_ident(expr: ast.expr) -> Optional[str]:
    """The identifier a call/attribute hangs off: `self._q.get` -> "_q";
    `time.sleep` -> "time"; `locks[i].acquire` -> "locks"."""
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Subscript):
        return _base_ident(expr.value)
    return None


def _is_lockish(expr: ast.expr) -> bool:
    ident = _base_ident(expr)
    return bool(ident and _ident_parts(ident) & _LOCK_WORDS)


def _root_name(expr: ast.expr) -> Optional[str]:
    """Walk `a.b[c].d` down to the root Name ("a")."""
    while isinstance(expr, (ast.Attribute, ast.Subscript)):
        expr = expr.value
    return expr.id if isinstance(expr, ast.Name) else None


def _call_is_nonblocking(node: ast.Call) -> bool:
    """acquire(False) / get(block=False) / acquire(blocking=False) /
    timeout=0 forms that poll instead of blocking."""
    for arg in node.args[:1]:
        if isinstance(arg, ast.Constant) and arg.value is False:
            return True
    for kw in node.keywords:
        if kw.arg in ("block", "blocking") and isinstance(
            kw.value, ast.Constant
        ) and kw.value.value is False:
            return True
        if kw.arg == "timeout" and isinstance(
            kw.value, ast.Constant
        ) and kw.value.value == 0:
            return True
    return False


class LockEdge:
    """One statically observed 'outer held while inner acquired' fact."""

    __slots__ = ("src", "dst", "relpath", "line", "symbol", "suppressed")

    def __init__(self, src: str, dst: str, relpath: str, line: int,
                 symbol: str, suppressed: bool):
        self.src = src
        self.dst = dst
        self.relpath = relpath
        self.line = line
        self.symbol = symbol
        self.suppressed = suppressed


class _Checker(ast.NodeVisitor):
    def __init__(self, ctx: FileContext):
        self.ctx = ctx
        self.findings: list[Finding] = []
        self.lock_edges: list[LockEdge] = []
        self._scope: list[str] = []          # class/function names, outermost first
        self._func_kinds: list[str] = []     # "sync" | "async"
        self._class_stack: list[str] = []
        self._held_locks: list[tuple[str, bool]] = []  # (lock id, is_async_with)
        # Module-level mutable bindings (dict/list/set/ctor) by name.
        self._module_mutables: set[str] = set()
        # Per-function: local name -> root param it aliases into.
        self._derived: dict[str, str] = {}
        self._locals: set[str] = set()
        self._params: set[str] = set()
        self._awaited_calls: set[int] = set()
        self._module_name = (ctx.relpath.rsplit("/", 1)[-1]).removesuffix(".py")

    # -- bookkeeping ---------------------------------------------------------

    def _symbol(self) -> str:
        return ".".join(self._scope) if self._scope else "<module>"

    def _emit(self, node: ast.AST, code: str, message: str):
        self.findings.append(Finding(
            self.ctx.relpath, getattr(node, "lineno", 0), code, message,
            self._symbol(),
        ))

    def _lock_id(self, expr: ast.expr) -> str:
        ident = _base_ident(expr) or "?"
        suffix = "[]" if isinstance(expr, ast.Subscript) or (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Subscript)
        ) else ""
        root = _root_name(expr)
        if root in ("self", "cls") and self._class_stack:
            return f"{self._class_stack[-1]}.{ident}{suffix}"
        return f"{self._module_name}.{ident}{suffix}"

    # -- module / class structure -------------------------------------------

    def check_module(self):
        for stmt in self.ctx.tree.body:
            if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                value = stmt.value
                targets = (
                    stmt.targets if isinstance(stmt, ast.Assign)
                    else [stmt.target]
                )
                if isinstance(value, ast.Call) and _base_ident(
                    value.func
                ) in ("local", "ContextVar", "Lock", "RLock", "Event",
                      "Semaphore", "BoundedSemaphore", "Condition", "count"):
                    # Per-thread / per-context / synchronization objects are
                    # designed to be mutated without external locking.
                    continue
                if isinstance(value, (ast.Dict, ast.List, ast.Set, ast.Call)):
                    for t in targets:
                        if isinstance(t, ast.Name):
                            self._module_mutables.add(t.id)
        self.visit(self.ctx.tree)
        return self

    def visit_ClassDef(self, node: ast.ClassDef):
        self._scope.append(node.name)
        self._class_stack.append(node.name)
        self._check_mutable_defaults(node)
        self.generic_visit(node)
        self._class_stack.pop()
        self._scope.pop()

    def _check_mutable_defaults(self, node: ast.ClassDef):
        for stmt in node.body:
            if not (isinstance(stmt, ast.AnnAssign) and stmt.value is not None):
                continue
            call = stmt.value
            if not (isinstance(call, ast.Call)
                    and _base_ident(call.func) == "field"):
                continue
            for kw in call.keywords:
                if kw.arg == "default" and isinstance(
                    kw.value, (ast.Dict, ast.List, ast.Set, ast.Call)
                ):
                    self._scope.append(getattr(stmt.target, "id", "?"))
                    self._emit(
                        stmt, "RL302",
                        "dataclass field(default=...) with a mutable value "
                        "is one object shared by every instance; use "
                        "default_factory",
                    )
                    self._scope.pop()

    def _visit_function(self, node, kind: str):
        self._scope.append(node.name)
        self._func_kinds.append(kind)
        saved_held = self._held_locks
        saved_derived, saved_locals = self._derived, self._locals
        saved_params = getattr(self, "_params", set())
        self._held_locks = []
        self._derived = {}
        args = node.args
        params = [
            a.arg
            for a in (args.posonlyargs + args.args + args.kwonlyargs)
        ] + [a.arg for a in (args.vararg, args.kwarg) if a is not None]
        self._locals = {p for p in params}
        self._params = {p for p in params if p not in ("self", "cls")}
        self.generic_visit(node)
        self._held_locks = saved_held
        self._derived, self._locals = saved_derived, saved_locals
        self._params = saved_params
        self._func_kinds.pop()
        self._scope.pop()

    def visit_FunctionDef(self, node):
        self._visit_function(node, "sync")

    def visit_AsyncFunctionDef(self, node):
        self._visit_function(node, "async")

    def visit_Lambda(self, node):
        # A lambda body is a deferred callback: neither its blocking calls nor
        # its lock use belong to the enclosing (possibly async) frame.
        self._func_kinds.append("sync")
        self.generic_visit(node)
        self._func_kinds.pop()

    def _in_async(self) -> bool:
        return bool(self._func_kinds) and self._func_kinds[-1] == "async"

    # -- RL101 / RL201: with-statement lock tracking -------------------------

    def _visit_with(self, node, is_async: bool):
        acquired = []
        for item in node.items:
            expr = item.context_expr
            if _is_lockish(expr):
                lock = self._lock_id(expr)
                suppressed = "RL201" in self.ctx.line_disables.get(
                    node.lineno, set()
                )
                for held, _a in self._held_locks:
                    self.lock_edges.append(LockEdge(
                        held, lock, self.ctx.relpath, node.lineno,
                        self._symbol(), suppressed,
                    ))
                self._held_locks.append((lock, is_async))
                acquired.append(lock)
        self.generic_visit(node)
        for _ in acquired:
            self._held_locks.pop()

    def visit_With(self, node):
        self._visit_with(node, is_async=False)

    def visit_AsyncWith(self, node):
        self._visit_with(node, is_async=True)

    def _held_sync_locks(self) -> list[str]:
        return [lock for lock, is_async in self._held_locks if not is_async]

    def visit_Await(self, node):
        held = self._held_sync_locks()
        if self._in_async() and held:
            self._emit(
                node, "RL101",
                f"await while holding sync lock {held[-1]!r}: every thread "
                "and task contending for the lock stalls until this "
                "coroutine resumes",
            )
        # The awaited call produced an awaitable — by definition not a
        # blocking call (asyncio.Event.wait, asyncio.Queue.get, ...).
        if isinstance(node.value, ast.Call):
            self._awaited_calls.add(id(node.value))
        self.generic_visit(node)

    # -- RL102: blocking calls in async frames -------------------------------

    def _blocking_reason(self, node: ast.Call) -> Optional[str]:
        func = node.func
        if isinstance(func, ast.Name):
            if func.id == "sleep":
                return "time.sleep"
            return None
        if not isinstance(func, ast.Attribute):
            return None
        attr = func.attr
        base = _base_ident(func.value)
        base_parts = _ident_parts(base) if base else set()
        if attr == "sleep" and base == "time":
            return "time.sleep"
        if base == "ray_tpu" and attr in ("get", "wait"):
            return f"blocking ray_tpu.{attr}"
        if attr == "acquire" and _is_lockish(func.value):
            if not _call_is_nonblocking(node):
                return "blocking lock.acquire"
            return None
        if attr in ("get", "put") and base_parts & {"queue", "q"}:
            if not _call_is_nonblocking(node):
                return f"blocking queue.{attr}"
            return None
        if base == "subprocess" and attr in (
            "run", "call", "check_call", "check_output"
        ):
            return f"subprocess.{attr}"
        if base == "os" and attr in ("system", "waitpid"):
            return f"os.{attr}"
        if attr == "result" and (
            isinstance(func.value, ast.Call) or base_parts & {"fut", "future"}
        ):
            return "Future.result"
        if attr == "join" and base_parts & {"thread", "threads", "proc",
                                            "process"}:
            return "thread/process join"
        if attr == "wait" and (
            _is_lockish(func.value)
            or base_parts & {"event", "ev", "evt", "done", "started", "cond"}
        ):
            return "blocking wait"
        if attr in ("recv", "recvfrom", "accept"):
            return f"blocking socket.{attr}"
        return None

    _ASYNC_HELPERS = {
        "wait_for", "gather", "shield", "create_task", "ensure_future",
        "run_coroutine_threadsafe", "as_completed", "wait",
    }

    def visit_Call(self, node: ast.Call):
        # Calls handed to asyncio combinators are coroutine factories, not
        # blocking calls: asyncio.wait_for(ev.wait(), t), gather(q.get(), ...).
        func = node.func
        if (
            isinstance(func, ast.Attribute)
            and func.attr in self._ASYNC_HELPERS
            and _base_ident(func.value) in ("asyncio", "aio")
        ):
            for arg in node.args:
                if isinstance(arg, ast.Call):
                    self._awaited_calls.add(id(arg))
        if self._in_async() and id(node) not in self._awaited_calls:
            reason = self._blocking_reason(node)
            if reason is not None:
                self._emit(
                    node, "RL102",
                    f"{reason} inside an async frame blocks the whole event "
                    "loop; await an async equivalent or push it through "
                    "run_in_executor",
                )
        self._check_mutator_call(node)
        self.generic_visit(node)

    # -- RL301: aliased mutation ---------------------------------------------

    def _container_root(self, expr: ast.expr) -> Optional[str]:
        """The parameter a container expression is rooted at, if any: for
        `acc`, `acc[k]`, `spec["config"]` (spec already derived) -> the
        original parameter name."""
        root = _root_name(expr)
        if root is None or root in ("self", "cls"):
            return None
        if root in getattr(self, "_params", set()):
            return root
        return self._derived.get(root)

    def _derivation_root(self, expr: ast.expr) -> Optional[str]:
        """If `expr` reaches INTO a parameter-owned object (subscript /
        .get() / attribute off a param or an existing alias), the root
        parameter name. A bare `x = param` alias is NOT a derivation — direct
        parameter mutation is the function's business."""
        if isinstance(expr, ast.Call):
            if isinstance(expr.func, ast.Attribute) and expr.func.attr == "get":
                return self._container_root(expr.func.value)
            return None
        if isinstance(expr, ast.Subscript):
            return self._container_root(expr)
        # NOTE: a pure attribute path (`param.attr`) does NOT taint — mutating
        # a parameter's own sub-structure is the function's business; the bug
        # class is objects pulled OUT of caller-owned containers.
        if isinstance(expr, ast.Name):
            return self._derived.get(expr.id)
        return None

    def visit_Assign(self, node: ast.Assign):
        # Track aliases first, then look for stores through existing aliases.
        for target in node.targets:
            if isinstance(target, ast.Name):
                root = self._derivation_root(node.value)
                if root is not None:
                    self._derived[target.id] = root
                else:
                    self._derived.pop(target.id, None)
                self._locals.add(target.id)
            elif isinstance(target, (ast.Attribute, ast.Subscript)):
                self._check_store(target, node)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign):
        if isinstance(node.target, (ast.Attribute, ast.Subscript)):
            self._check_store(node.target, node)
        self.generic_visit(node)

    def _check_store(self, target, node):
        """`x.attr = v` / `x[k] = v` where x aliases caller-owned state."""
        base = target.value if isinstance(
            target, (ast.Attribute, ast.Subscript)
        ) else None
        if base is None:
            return
        if isinstance(base, ast.Name):
            name = base.id
            if name in self._derived:
                self._emit(
                    node, "RL301",
                    f"in-place mutation of {name!r}, an alias into "
                    f"caller-owned state (via parameter "
                    f"{self._derived[name]!r}); copy before overriding "
                    "(dataclasses.replace / copy.deepcopy)",
                )
            elif (
                name in self._module_mutables
                and name not in self._locals
                and self._func_kinds
                and not self._held_locks
            ):
                self._emit(
                    node, "RL301",
                    f"in-place mutation of module-level {name!r} outside any "
                    "lock: shared across threads and callers",
                )
            return
        # x[k].attr = v / param[k].attr = v  — mutation through a deep path
        # rooted at a parameter.
        root = self._derivation_root(target.value)
        if root is not None:
            self._emit(
                node, "RL301",
                f"in-place mutation through caller-owned state (parameter "
                f"{root!r}); copy the object before overriding",
            )

    def _check_mutator_call(self, node: ast.Call):
        func = node.func
        if not (isinstance(func, ast.Attribute)
                and func.attr in _MUTATOR_METHODS):
            return
        base = func.value
        if isinstance(base, ast.Name):
            name = base.id
            if name in self._derived:
                self._emit(
                    node, "RL301",
                    f".{func.attr}() mutates {name!r}, an alias into "
                    f"caller-owned state (via parameter "
                    f"{self._derived[name]!r}); copy before mutating",
                )
            elif (
                name in self._module_mutables
                and name not in self._locals
                and self._func_kinds
                and not self._held_locks
            ):
                self._emit(
                    node, "RL301",
                    f".{func.attr}() mutates module-level {name!r} outside "
                    "any lock: shared across threads and callers",
                )

    # -- RL401: swallowed exceptions -----------------------------------------
    # Scope (framework-aware): RPC handlers (`rpc_*` methods) and async
    # control-plane frames — the places where a silently dropped error turns
    # into a hung call or a stuck reconcile loop. Best-effort teardown
    # (`try: x.close() except Exception: pass`) is exempt: failing to close a
    # dying resource is not an error worth surfacing.

    _TEARDOWN_CALLS = {
        "close", "cancel", "shutdown", "kill", "terminate", "unlink",
        "release", "join", "stop", "disconnect", "destroy", "flush",
        "print_exc", "remove", "rmtree",
    }

    def visit_Try(self, node: ast.Try):
        teardown = self._is_teardown_try(node)
        for handler in node.handlers:
            if (
                not teardown
                and self._in_handler_scope()
                and self._is_broad(handler.type)
                and self._swallows(handler)
            ):
                self._scope_emit_handler(handler)
        self.generic_visit(node)

    def _scope_emit_handler(self, handler: ast.ExceptHandler):
        self._emit(
            handler, "RL401",
            "broad except in an RPC/control-plane handler silently swallows "
            "the error: re-raise, fail the call, log, or leave a comment "
            "saying why dropping it is safe",
        )

    def _in_handler_scope(self) -> bool:
        if not self._func_kinds:
            return False
        if self._func_kinds[-1] == "async":
            return True
        func_names = [s for s in self._scope if s not in self._class_stack]
        return bool(func_names) and func_names[-1].startswith("rpc_")

    def _is_teardown_try(self, node: ast.Try) -> bool:
        for stmt in node.body:
            if isinstance(stmt, ast.Pass):
                continue
            if not (isinstance(stmt, ast.Expr)
                    and isinstance(stmt.value, ast.Call)):
                return False
            func = stmt.value.func
            name = func.attr if isinstance(func, ast.Attribute) else (
                func.id if isinstance(func, ast.Name) else None
            )
            if name not in self._TEARDOWN_CALLS:
                return False
        return True

    @staticmethod
    def _is_broad(type_node) -> bool:
        if type_node is None:
            return True
        if isinstance(type_node, ast.Name):
            return type_node.id in _BROAD_EXC
        if isinstance(type_node, ast.Tuple):
            return any(
                isinstance(e, ast.Name) and e.id in _BROAD_EXC
                for e in type_node.elts
            )
        return False

    def _swallows(self, node: ast.ExceptHandler) -> bool:
        for stmt in node.body:
            if isinstance(stmt, (ast.Pass, ast.Continue, ast.Break)):
                continue
            if isinstance(stmt, ast.Return) and (
                stmt.value is None
                or (isinstance(stmt.value, ast.Constant)
                    and stmt.value.value is None)
            ):
                continue
            return False  # any real statement counts as handling
        # An explanatory comment anywhere in the handler is documentation.
        end = node.body[-1].end_lineno or node.body[-1].lineno
        for line in range(node.lineno, end + 1):
            if line in self.ctx.comment_lines:
                return False
        return True

    # -- RL501: discarded remote/execute results -----------------------------

    def visit_Expr(self, node: ast.Expr):
        call = node.value
        if (
            isinstance(call, ast.Call)
            and isinstance(call.func, ast.Attribute)
            and call.func.attr in _DISCARDED_CALL_ATTRS
        ):
            self._emit(
                node, "RL501",
                f".{call.func.attr}() result discarded: unread refs leak "
                "capacity (compiled DAGs wedge at max_inflight) and hide "
                "failures; get/await it, keep it for later, or release() it",
            )
        self.generic_visit(node)


def check_file(ctx: FileContext) -> tuple[list[Finding], list[LockEdge]]:
    checker = _Checker(ctx).check_module()
    findings = checker.findings
    # jaxlint (RL6xx/RL7xx) only has something to say about files that
    # touch jax; the import gate keeps control-plane float()/np.asarray
    # idioms out of its sight.
    from ray_tpu.devtools.raylint import distlint, jaxlint, leaklint

    findings = findings + jaxlint.check_jax_file(ctx)
    # leaklint (RL8xx) keys off the declarative resource table, so it runs
    # over every file — the table's receiver hints are its precision gate.
    findings = findings + leaklint.check_leak_file(ctx)
    # distlint (RL9xx) enforces the distributed-plane contracts (report-path
    # metrics, finalizer/lock/hot-context RPC, remote-safe exceptions,
    # explicit trace_ctx); its receiver/roster proofs are the precision gate.
    findings = findings + distlint.check_dist_file(ctx)
    return findings, checker.lock_edges


def lock_cycle_findings(edges: list[LockEdge]) -> list[Finding]:
    """RL201 over the union of every file's acquisition-order edges.

    Suppressing an edge's `with` line (`# raylint: disable=RL201`) removes
    the edge from the graph — the suppression is a claim that this nesting
    cannot run concurrently with the reverse order."""
    graph: dict[str, set[str]] = {}
    witness: dict[tuple[str, str], LockEdge] = {}
    for e in edges:
        if e.suppressed:
            continue
        graph.setdefault(e.src, set()).add(e.dst)
        witness.setdefault((e.src, e.dst), e)

    findings: list[Finding] = []
    seen_cycles: set[tuple[str, ...]] = set()

    def dfs(start: str):
        stack = [(start, [start])]
        while stack:
            node, path = stack.pop()
            for nxt in sorted(graph.get(node, ())):
                if nxt == start:
                    cycle = tuple(sorted(path))
                    if cycle in seen_cycles:
                        continue
                    seen_cycles.add(cycle)
                    e = witness[(path[-1], start)]
                    order = " -> ".join(path + [start])
                    findings.append(Finding(
                        e.relpath, e.line, "RL201",
                        f"lock acquisition-order cycle: {order} — two "
                        "threads taking these locks in opposite orders "
                        "deadlock",
                        "|".join(cycle),
                    ))
                elif nxt not in path:
                    stack.append((nxt, path + [nxt]))

    for start in sorted(graph):
        dfs(start)
    return findings
