import sys

from ray_tpu.devtools.raylint.cli import main

sys.exit(main())
