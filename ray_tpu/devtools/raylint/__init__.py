"""raylint: framework-aware static analysis for the ray_tpu control plane.

The runtime is a mix of asyncio control loops (raylet, GCS, serve) and
threaded data paths (worker, channels) — the bug classes that slip through
review here are concurrency and aliasing bugs that generic linters don't
model. raylint is an AST pass purpose-built for this codebase's invariants
(reference role: `python/ray/util/check_serialize.py` and the reference's CI
lint gates). Run it as::

    python -m ray_tpu.devtools.raylint ray_tpu/

Checker families (see docs/raylint.md for the full contract):

- RL101 await-under-lock      `await` inside a `with <lock>:` body
- RL102 blocking-in-async     blocking call inside an `async def` body
- RL201 lock-order-cycle      cycle in the static lock acquisition-order graph
- RL301 aliased-mutation      in-place mutation of an object reached through a
                              caller-owned container (shared-config aliasing)
- RL302 mutable-default       dataclass `field(default=<mutable>)` shared
                              across instances
- RL401 swallowed-exception   broad `except` that silently discards the error
- RL501 unreleased-ref        `.remote()`/`execute()` result discarded unread

jaxlint family (compute plane; files that import jax only):

- RL601 jit-in-hot-path       `jax.jit` constructed in a loop / per-call frame
- RL602 unbounded-program-cache  jitted programs cached with no cap/eviction
- RL603 host-sync-in-loop     device->host readback in a step loop/async frame
- RL604 retrace-hazard        list / raw-len()-shaped array into a jitted call
- RL605 donation-misuse       donated argument read after the call
- RL701 side-effect-under-jit traced fn mutates self/globals/closures

leaklint family (resource-lifetime plane; see also devtools/leaksan.py,
the runtime live-handle sanitizer these checkers pair with):

- RL801 unreleased-acquire    lease/pin/conn not released on every path
- RL802 release-via-gc-only   cross-process release reachable only from __del__
- RL803 use/double-release    handle used or released again after release
- RL804 fragile-release       swallowed release failure / lock-mismatched release

distlint family (distributed-contract plane; see also devtools/distsan.py,
the runtime contract sanitizer these checkers pair with):

- RL901 metric-outside-report metric mutation off the report path
- RL902 blocking-control-rpc  control-plane RPC on a latency-critical path
- RL903 unpicklable-exception exception class that dies crossing a hop
- RL904 trace-context-hop     trace context read on the wrong thread
- RL905 rpc-under-lock        cross-process call awaited under a held lock

apilint family (cross-process call contracts; the static half of the
API-surface gate in devtools/apisurface.py):

- RL1001 unknown-remote-method  `.remote()` to a method no target defines
- RL1002 remote-arity-mismatch  call shape that can't bind the target sig
- RL1003 protocol-drift         deployed class with a partial duck-typed
                                roster (PROTOCOL_TABLE) or drifted shape
- RL1004 unknown-or-dead-flag   CONFIG read absent from _DEFS / flag no
                                code reads
- RL1005 unpicklable-boundary   lambda, local def, or OS handle shipped
                                through a `.remote()` boundary
- RL1006 gcs-verb-drift         unknown `gcs_call` verb / orphan rpc_*
                                handler no string anywhere names

Suppress a finding with a trailing (or immediately preceding) comment::

    ref = actor.ping.remote()  # raylint: disable=RL501

or grandfather it in the checked-in baseline (`baseline.json`) with a one-line
justification; `tests/test_raylint.py` gates tier-1 on zero non-baselined
findings.
"""

from ray_tpu.devtools.raylint.core import (  # noqa: F401
    CODES,
    Finding,
    lint_file,
    lint_paths,
    load_baseline,
    partition_baselined,
)
