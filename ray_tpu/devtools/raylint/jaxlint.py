"""jaxlint: the RL6xx/RL7xx checker family — TPU/JAX compute-plane hazards.

JAX's trace-then-compile model makes the compute plane's performance bugs
statically recognizable in a way eager frameworks never were: a retrace, a
host sync, or a donated-buffer read each leave a syntactic fingerprint.
These checkers only run over files that import jax (see `uses_jax`).

Shared analysis infrastructure, built in a prepass over the whole file:

- **Jit registry**: names/attributes bound to `jax.jit(...)` results —
  module globals (`_step = jax.jit(f)`), instance attributes
  (`self._jit_decode = jax.jit(...)`), program-cache dict attributes
  (`self._jit_prefill[key] = jax.jit(...)`), and functions whose return
  value is a jit result (factories like `build_train_step`). A call through
  any of these is a "jitted call".
- **Device taint**: expressions that hold device arrays — results of jitted
  calls, `jnp.*` constructors, `jax.device_put`, instance attributes
  assigned device values anywhere in the class, and anything reached from a
  tainted value through subscripts/attributes/tuple unpacking. Host
  conversions (`np.asarray`, `float`, `int`) both *clear* taint and are the
  sync sites RL603 reports.
- **Hot-context call graph**: a function is hot when it contains a sync
  site inside a lexical loop, or when it is called (transitively, within
  this file) from a loop body — the decode/train step loops reach their
  helpers through exactly this shape.
"""

from __future__ import annotations

import ast
import re
from typing import Optional

from ray_tpu.devtools.raylint.core import FileContext, Finding

_JIT_NAMES = {"jit", "pjit"}
_JNP_ROOTS = {"jnp"}
_ARRAY_CTORS = {"zeros", "ones", "empty", "full", "arange", "asarray", "array"}
_SYNC_BUILTINS = {"float", "int", "bool"}
_EVICT_METHODS = {"pop", "popitem", "clear"}
# wrapper name -> positions of the function argument(s) it traces.
_TRACING_WRAPPERS = {
    "jit": (0,), "pjit": (0,), "scan": (0,), "shard_map": (0,),
    "vmap": (0,), "pmap": (0,), "grad": (0,), "value_and_grad": (0,),
    "checkpoint": (0,), "remat": (0,), "while_loop": (0, 1),
    "cond": (1, 2), "fori_loop": (2,), "custom_vjp": (0,),
    "custom_jvp": (0,),
}

_USES_JAX_RE = re.compile(r"^\s*(import jax\b|from jax\b|import jax\.)",
                          re.MULTILINE)


def uses_jax(source: str) -> bool:
    return bool(_USES_JAX_RE.search(source))


def _base_ident(expr: ast.expr) -> Optional[str]:
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Subscript):
        return _base_ident(expr.value)
    return None


def _root_name(expr: ast.expr) -> Optional[str]:
    while isinstance(expr, (ast.Attribute, ast.Subscript)):
        expr = expr.value
    return expr.id if isinstance(expr, ast.Name) else None


def _dotted(expr: ast.expr) -> Optional[str]:
    """`jax.lax.scan` -> "jax.lax.scan"; bare names -> the name."""
    parts = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if isinstance(expr, ast.Name):
        parts.append(expr.id)
        return ".".join(reversed(parts))
    return None


def _is_jit_ctor(node: ast.expr) -> bool:
    """`jax.jit(...)` / `pjit(...)` / `jax.experimental.pjit.pjit(...)`."""
    if not isinstance(node, ast.Call):
        return False
    dotted = _dotted(node.func)
    if dotted is None:
        return False
    last = dotted.rsplit(".", 1)[-1]
    return last in _JIT_NAMES


def _donated_argnums(node: ast.Call) -> tuple:
    """Positional donate indices of a jit ctor call (donate_argnums only —
    donate_argnames needs kw callsites, matched separately)."""
    for kw in node.keywords:
        if kw.arg == "donate_argnums":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return (v.value,)
            if isinstance(v, (ast.Tuple, ast.List)):
                return tuple(
                    e.value for e in v.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, int)
                )
    return ()


def _is_jnp_call(node: ast.expr) -> bool:
    if not isinstance(node, ast.Call):
        return False
    dotted = _dotted(node.func)
    if not dotted:
        return False
    root = dotted.split(".", 1)[0]
    if root in _JNP_ROOTS:
        return True
    return dotted in ("jax.device_put", "jax.numpy") or dotted.startswith(
        "jax.numpy."
    ) or dotted.startswith("jax.random.")


def _contains_len_call(expr: ast.expr) -> bool:
    for node in ast.walk(expr):
        if (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
                and node.func.id == "len"):
            return True
    return False


def _is_unbucketed_array_ctor(expr: ast.expr) -> bool:
    """np/jnp array ctor whose shape argument embeds a raw `len(...)`."""
    if not isinstance(expr, ast.Call):
        return False
    dotted = _dotted(expr.func) or ""
    last = dotted.rsplit(".", 1)[-1]
    root = dotted.split(".", 1)[0]
    if root not in ("np", "numpy", "jnp") or last not in _ARRAY_CTORS:
        return False
    return any(_contains_len_call(a) for a in expr.args[:1])


class _Prepass(ast.NodeVisitor):
    """File-wide facts every per-function check needs."""

    def __init__(self, tree: ast.AST):
        self.module_jit: set[str] = set()          # global names bound to jit
        self.jit_attrs: set[str] = set()           # self attrs bound to jit
        self.jit_dict_attrs: set[str] = set()      # self attrs: dict of programs
        self.device_attrs: set[str] = set()        # self attrs holding arrays
        self.jit_factories: set[str] = set()       # fns returning a jit result
        self.device_factories: set[str] = set()    # fns returning device arrays
        # traced-function references, scope-qualified so `jax.jit(update)`
        # inside Learner._build_update marks the NESTED `update`, never a
        # same-named public method: ("scope:<qualified ref scope>", name) for
        # bare names, ("class:<Class>", attr) for self.<method> references.
        self.jit_target_refs: set[tuple[str, str]] = set()
        self.donate: dict[str, tuple] = {}         # jit name/attr -> argnums
        # call graph: qualified fn -> (callees from loop bodies, all callees)
        self._calls_in_loops: dict[str, set[str]] = {}
        self._calls_all: dict[str, set[str]] = {}
        self._scope: list[str] = []
        self._class_stack: list[str] = []
        self._loop_depth = 0
        self._walk(tree)
        self.hot_functions = self._compute_hot()

    def _walk(self, tree):
        self.visit(tree)

    def _fn_key(self) -> str:
        return ".".join(self._scope) if self._scope else "<module>"

    def visit_ClassDef(self, node):
        self._scope.append(node.name)
        self._class_stack.append(node.name)
        self.generic_visit(node)
        self._class_stack.pop()
        self._scope.pop()

    def _visit_fn(self, node):
        self._scope.append(node.name)
        saved = self._loop_depth
        self._loop_depth = 0
        self._calls_in_loops.setdefault(self._fn_key(), set())
        self._calls_all.setdefault(self._fn_key(), set())
        self.generic_visit(node)
        self._loop_depth = saved
        self._scope.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    def _visit_loop(self, node):
        self._loop_depth += 1
        self.generic_visit(node)
        self._loop_depth -= 1

    visit_For = _visit_loop
    visit_AsyncFor = _visit_loop
    visit_While = _visit_loop
    visit_ListComp = _visit_loop
    visit_SetComp = _visit_loop
    visit_DictComp = _visit_loop
    visit_GeneratorExp = _visit_loop

    def visit_Return(self, node):
        if node.value is not None and self._scope:
            if _is_jit_ctor(node.value):
                self.jit_factories.add(self._scope[-1])
            elif isinstance(node.value, ast.Call):
                # `return self._jit_step(...)` — a plain method fronting a
                # jitted program returns device arrays (requires the jit
                # binding to appear earlier in the file, the common shape).
                f = node.value.func
                if (isinstance(f, ast.Attribute)
                        and isinstance(f.value, ast.Name)
                        and f.value.id in ("self", "cls")
                        and f.attr in self.jit_attrs) or (
                    isinstance(f, ast.Name) and f.id in self.module_jit
                ) or (
                    isinstance(f, ast.Subscript)
                    and _base_ident(f) in self.jit_dict_attrs
                ):
                    self.device_factories.add(self._scope[-1])
        self.generic_visit(node)

    def visit_Assign(self, node):
        value = node.value
        if _is_jit_ctor(value):
            donated = _donated_argnums(value)
            for t in node.targets:
                if isinstance(t, ast.Name):
                    if not self._scope:
                        self.module_jit.add(t.id)
                    if donated:
                        self.donate[t.id] = donated
                elif isinstance(t, ast.Attribute) and _root_name(t) in (
                    "self", "cls"
                ):
                    self.jit_attrs.add(t.attr)
                    if donated:
                        self.donate[t.attr] = donated
                elif isinstance(t, ast.Subscript):
                    ident = _base_ident(t)
                    if ident:
                        self.jit_dict_attrs.add(ident)
        elif self._value_is_devicey(value):
            for t in node.targets:
                if isinstance(t, ast.Attribute) and _root_name(t) in (
                    "self", "cls"
                ):
                    self.device_attrs.add(t.attr)
        # An empty dict attr later filled with programs registers at the
        # fill site (the Subscript branch above), not here.
        self.generic_visit(node)

    def _value_is_devicey(self, value: ast.expr) -> bool:
        """Does the assigned expression (or anything inside a container
        display / comprehension it builds) produce device arrays?"""
        for node in ast.walk(value):
            if _is_jnp_call(node):
                return True
        return False

    def visit_Call(self, node):
        # Tracing wrappers: jax.jit(f) / lax.scan(step, ...) /
        # shard_map(body, ...) mark f as a traced (jit-target) function.
        dotted = _dotted(node.func)
        last = dotted.rsplit(".", 1)[-1] if dotted else None
        if last in _TRACING_WRAPPERS:
            for pos in _TRACING_WRAPPERS[last]:
                if pos >= len(node.args):
                    continue
                arg = node.args[pos]
                if isinstance(arg, ast.Name):
                    self.jit_target_refs.add(
                        ("scope:" + ".".join(self._scope), arg.id)
                    )
                elif isinstance(arg, ast.Attribute) and isinstance(
                    arg.value, ast.Name
                ) and arg.value.id in ("self", "cls") and self._class_stack:
                    self.jit_target_refs.add(
                        ("class:" + self._class_stack[-1], arg.attr)
                    )
        # call graph edges
        if self._scope:
            callee = None
            if isinstance(node.func, ast.Name):
                callee = node.func.id
            elif isinstance(node.func, ast.Attribute) and _root_name(
                node.func
            ) in ("self", "cls"):
                callee = node.func.attr
            if callee:
                key = self._fn_key()
                self._calls_all.setdefault(key, set()).add(callee)
                if self._loop_depth:
                    self._calls_in_loops.setdefault(key, set()).add(callee)
        self.generic_visit(node)

    def _compute_hot(self) -> set[str]:
        """Functions reachable from a loop body: seeded by direct
        called-from-loop edges, closed over same-file calls. Matching is by
        trailing name segment (self.foo() can't see which class defines foo)."""
        hot: set[str] = set()
        for callees in self._calls_in_loops.values():
            hot |= callees
        changed = True
        while changed:
            changed = False
            for key, callees in self._calls_all.items():
                leaf = key.rsplit(".", 1)[-1]
                if leaf in hot:
                    new = callees - hot
                    if new:
                        hot |= new
                        changed = True
        return hot


class _JaxChecker(ast.NodeVisitor):
    def __init__(self, ctx: FileContext, pre: _Prepass):
        self.ctx = ctx
        self.pre = pre
        self.findings: list[Finding] = []
        self._scope: list[str] = []
        self._class_stack: list[str] = []
        self._func_stack: list[ast.AST] = []
        self._async_stack: list[bool] = []
        self._loop_depth = 0
        # per-function state
        self._tainted: list[set[str]] = []
        self._local_jit: list[dict[str, tuple]] = []   # name -> donate argnums
        self._list_locals: list[set[str]] = []
        self._unbucketed_locals: list[set[str]] = []
        # donation reads: (call line, donated root names) per function
        self._donation_calls: list[list[tuple[int, list[str]]]] = []

    # -- bookkeeping --------------------------------------------------------

    def _symbol(self) -> str:
        return ".".join(self._scope) if self._scope else "<module>"

    def _emit(self, node: ast.AST, code: str, message: str):
        self.findings.append(Finding(
            self.ctx.relpath, getattr(node, "lineno", 0), code, message,
            self._symbol(),
        ))

    def check_module(self):
        self.visit(self.ctx.tree)
        return self

    def visit_ClassDef(self, node):
        self._scope.append(node.name)
        self._class_stack.append(node.name)
        self.generic_visit(node)
        self._class_stack.pop()
        self._scope.pop()

    def _visit_fn(self, node, is_async: bool):
        self._scope.append(node.name)
        self._func_stack.append(node)
        self._async_stack.append(is_async)
        self._tainted.append(set())
        self._local_jit.append({})
        self._list_locals.append(set())
        self._unbucketed_locals.append(set())
        self._donation_calls.append([])
        saved_depth = self._loop_depth
        self._loop_depth = 0
        if self._is_jit_target(node) or self._is_jit_decorated(node):
            self._check_side_effects(node)
        self.generic_visit(node)
        self._check_donation_reads(node)
        self._loop_depth = saved_depth
        self._donation_calls.pop()
        self._unbucketed_locals.pop()
        self._list_locals.pop()
        self._local_jit.pop()
        self._tainted.pop()
        self._async_stack.pop()
        self._func_stack.pop()
        self._scope.pop()

    def visit_FunctionDef(self, node):
        self._visit_fn(node, is_async=False)

    def visit_AsyncFunctionDef(self, node):
        self._visit_fn(node, is_async=True)

    def _is_jit_target(self, node) -> bool:
        """Was THIS def (not a name-collision elsewhere) handed to a tracing
        wrapper? Methods match a `self.<name>` reference from their own class;
        nested/module defs match a bare-name reference from a scope the def is
        visible in (its defining scope or anything nested inside it)."""
        parent = self._scope[:-1]
        if parent and parent[-1] == (
            self._class_stack[-1] if self._class_stack else None
        ):
            return ("class:" + parent[-1], node.name) in self.pre.jit_target_refs
        prefix = ".".join(parent)
        for kind, name in self.pre.jit_target_refs:
            if name != node.name or not kind.startswith("scope:"):
                continue
            ref_scope = kind[len("scope:"):]
            if not prefix or ref_scope == prefix or ref_scope.startswith(
                prefix + "."
            ):
                return True
        return False

    @staticmethod
    def _is_jit_decorated(node) -> bool:
        for dec in node.decorator_list:
            if _is_jit_ctor(dec):
                return True
            dotted = _dotted(dec) or (
                _dotted(dec.func) if isinstance(dec, ast.Call) else None
            )
            if dotted and dotted.rsplit(".", 1)[-1] in _JIT_NAMES:
                return True
            if isinstance(dec, ast.Call):  # partial(jax.jit, ...)
                for a in dec.args:
                    d = _dotted(a)
                    if d and d.rsplit(".", 1)[-1] in _JIT_NAMES:
                        return True
        return False

    def _visit_loop(self, node):
        if isinstance(node, (ast.For, ast.AsyncFor)) and self._is_tainted(
            node.iter
        ):
            # iterating device state binds device values to the loop target
            self._taint_targets([node.target], True)
        self._loop_depth += 1
        self.generic_visit(node)
        self._loop_depth -= 1

    visit_For = _visit_loop
    visit_AsyncFor = _visit_loop
    visit_While = _visit_loop

    def _visit_comp(self, node):
        for gen in node.generators:
            if self._is_tainted(gen.iter):
                self._taint_targets([gen.target], True)
        self._loop_depth += 1
        self.generic_visit(node)
        self._loop_depth -= 1

    visit_ListComp = _visit_comp
    visit_SetComp = _visit_comp
    visit_DictComp = _visit_comp
    visit_GeneratorExp = _visit_comp

    # -- hot-context predicate ---------------------------------------------

    def _in_hot_context(self) -> bool:
        if self._loop_depth:
            return True
        if self._async_stack and self._async_stack[-1]:
            return True
        return bool(self._scope) and self._scope[-1] in self.pre.hot_functions

    # -- taint --------------------------------------------------------------

    def _is_jitted_callable(self, func: ast.expr) -> bool:
        """Is this call-expression's func a known jitted program?"""
        if isinstance(func, ast.Name):
            return (func.id in self.pre.module_jit
                    or (self._local_jit and func.id in self._local_jit[-1]))
        if isinstance(func, ast.Attribute):
            root = _root_name(func)
            if root in ("self", "cls") and func.attr in self.pre.jit_attrs:
                return True
            return False
        if isinstance(func, ast.Subscript):
            ident = _base_ident(func)
            return bool(ident and ident in self.pre.jit_dict_attrs)
        return False

    def _is_jit_factory_call(self, value: ast.expr) -> bool:
        if not isinstance(value, ast.Call):
            return False
        name = None
        if isinstance(value.func, ast.Name):
            name = value.func.id
        elif isinstance(value.func, ast.Attribute):
            name = value.func.attr
        return bool(name and name in self.pre.jit_factories)

    def _is_tainted(self, expr: ast.expr) -> bool:
        if isinstance(expr, ast.Name):
            return bool(self._tainted and expr.id in self._tainted[-1])
        if isinstance(expr, ast.Attribute):
            root = _root_name(expr)
            if root in ("self", "cls"):
                # `self._caches[i][0]` reaches a device attr through its base
                return expr.attr in self.pre.device_attrs
            return self._is_tainted(expr.value)
        if isinstance(expr, ast.Subscript):
            return self._is_tainted(expr.value)
        if isinstance(expr, ast.Call):
            if self._is_jitted_callable(expr.func):
                return True
            if _is_jnp_call(expr):
                return True
            fname = None
            if isinstance(expr.func, ast.Name):
                fname = expr.func.id
            elif isinstance(expr.func, ast.Attribute):
                fname = expr.func.attr
            if fname and fname in self.pre.device_factories:
                return True
            # `.copy()` / `.astype()` / `.at[..].set(..)` on tainted stays device
            if isinstance(expr.func, ast.Attribute):
                return self._is_tainted(expr.func.value)
        return False

    def _taint_targets(self, targets, tainted: bool):
        if not self._tainted:
            return
        for t in targets:
            if isinstance(t, ast.Name):
                if tainted:
                    self._tainted[-1].add(t.id)
                else:
                    self._tainted[-1].discard(t.id)
            elif isinstance(t, (ast.Tuple, ast.List)):
                self._taint_targets(t.elts, tainted)

    # -- assignments: taint flow, RL602, RL604 locals, RL605 registry -------

    def visit_Assign(self, node: ast.Assign):
        value = node.value
        # RHS first: `x = [f(x) for x in np.asarray(x)]` must see the OLD
        # taint of x while walking the comprehension, not the post-store one.
        self.visit(value)
        for t in node.targets:
            self.visit(t)
        if _is_jit_ctor(value):
            donated = _donated_argnums(value)
            for t in node.targets:
                if isinstance(t, ast.Name) and self._local_jit:
                    self._local_jit[-1][t.id] = donated
                elif isinstance(t, ast.Subscript):
                    self._check_unbounded_cache(node, t)
            return
        if self._is_jit_factory_call(value) or (
            isinstance(value, ast.Name) and self._local_jit
            and value.id in self._local_jit[-1]
        ):
            # a program (from a factory or an alias) stored into a dict
            for t in node.targets:
                if isinstance(t, ast.Subscript):
                    self._check_unbounded_cache(node, t)
                elif isinstance(t, ast.Name) and self._local_jit:
                    self._local_jit[-1][t.id] = ()
            return
        tainted = self._is_tainted(value)
        self._taint_targets(node.targets, tainted)
        if self._list_locals:
            is_list = isinstance(value, (ast.List, ast.ListComp)) or (
                isinstance(value, ast.Call)
                and isinstance(value.func, ast.Name)
                and value.func.id == "list"
            )
            for t in node.targets:
                if isinstance(t, ast.Name):
                    if is_list:
                        self._list_locals[-1].add(t.id)
                    else:
                        self._list_locals[-1].discard(t.id)
                    if _is_unbucketed_array_ctor(value):
                        self._unbucketed_locals[-1].add(t.id)
                    else:
                        self._unbucketed_locals[-1].discard(t.id)

    def _check_unbounded_cache(self, node, target: ast.Subscript):
        """RL602: a jitted program stored into a cache with no eviction in
        sight. Evidence of bounding, checked across the enclosing function:
        `.pop()/.popitem()/.clear()` on the same cache, `del cache[...]`, or a
        `len(cache)` read (a cap check)."""
        ident = _base_ident(target)
        if not ident or not self._func_stack:
            return
        if self._has_eviction_evidence(self._func_stack[-1], ident):
            return
        self._emit(
            node, "RL602",
            f"jitted program stored into {ident!r} with no eviction or cap in "
            "this function: request-derived keys compile and retain programs "
            "unboundedly (an adversarial input mix exhausts memory); bound it "
            "with an explicit bucket set or LRU cap",
        )

    @staticmethod
    def _has_eviction_evidence(fn: ast.AST, ident: str) -> bool:
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                f = node.func
                if (isinstance(f, ast.Attribute) and f.attr in _EVICT_METHODS
                        and _base_ident(f.value) == ident):
                    return True
                if (isinstance(f, ast.Name) and f.id == "len" and node.args
                        and _base_ident(node.args[0]) == ident):
                    return True
            if isinstance(node, ast.Delete):
                for t in node.targets:
                    if isinstance(t, ast.Subscript) and _base_ident(t) == ident:
                        return True
        return False

    # -- calls: RL601, RL603, RL604, RL605 ----------------------------------

    def visit_Call(self, node: ast.Call):
        if _is_jit_ctor(node):
            if self._loop_depth:
                self._emit(
                    node, "RL601",
                    "jax.jit(...) constructed inside a loop: every iteration "
                    "builds a fresh wrapper whose compiled program cannot be "
                    "reused across calls; hoist the jit to module/__init__ "
                    "scope or a keyed program cache",
                )
        elif isinstance(node.func, ast.Call) and _is_jit_ctor(node.func):
            if self._func_stack:
                self._emit(
                    node, "RL601",
                    "jax.jit(f)(...) constructed and invoked in one "
                    "expression inside a function: the wrapper dies with the "
                    "frame, so every call re-traces; cache the jitted "
                    "callable outside the per-call frame",
                )
        self._check_host_sync(node)
        if self._is_jitted_callable(node.func):
            self._check_retrace_args(node)
            self._record_donation_call(node)
        self.generic_visit(node)

    def _check_host_sync(self, node: ast.Call):
        """RL603: device->host synchronization in a hot context."""
        if not self._in_hot_context():
            return
        func = node.func
        reason = None
        target = None
        dotted = _dotted(func) or ""
        last = dotted.rsplit(".", 1)[-1]
        if isinstance(func, ast.Name) and func.id in _SYNC_BUILTINS:
            if node.args and self._is_tainted(node.args[0]):
                reason = f"{func.id}() on a device value"
                target = node.args[0]
        elif dotted in ("np.asarray", "np.array", "numpy.asarray",
                        "numpy.array"):
            if node.args and self._is_tainted(node.args[0]):
                reason = f"{dotted}() on a device value"
                target = node.args[0]
        elif last == "device_get":
            reason = "jax.device_get()"
            target = node.args[0] if node.args else node
        elif isinstance(func, ast.Attribute) and func.attr in (
            "item", "tolist", "block_until_ready"
        ):
            if func.attr == "block_until_ready" or self._is_tainted(
                func.value
            ):
                reason = f".{func.attr}()"
                target = func.value
        if reason is None:
            return
        name = None
        if target is not None:
            root = _root_name(target)
            name = _base_ident(target) if root in ("self", "cls") else root
        where = f" (value {name!r})" if name else ""
        self._emit(
            node, "RL603",
            f"host sync {reason}{where} inside a decode/train hot path "
            "(loop body, loop-called helper, or async frame) stalls the "
            "dispatch pipeline per step; batch the readback once per chunk, "
            "keep the state host-native, or annotate the sync as intentional",
        )

    def _check_retrace_args(self, node: ast.Call):
        """RL604: arguments whose pytree structure or shape varies with the
        data, passed to a jitted callable without static_argnums/bucketing."""
        for arg in node.args:
            if isinstance(arg, (ast.List, ast.ListComp)) or (
                isinstance(arg, ast.Name) and self._list_locals
                and arg.id in self._list_locals[-1]
            ):
                self._emit(
                    node, "RL604",
                    "Python list passed to a jitted callable: its pytree "
                    "structure (and so the compiled program) changes with the "
                    "list's length — every distinct length re-traces; pass an "
                    "array, or mark the argument static and bucket it",
                )
            elif _is_unbucketed_array_ctor(arg) or (
                isinstance(arg, ast.Name) and self._unbucketed_locals
                and arg.id in self._unbucketed_locals[-1]
            ):
                self._emit(
                    node, "RL604",
                    "array with a raw len()-derived shape passed to a jitted "
                    "callable: every distinct input length compiles a new "
                    "program; round the shape to a bucket table first",
                )

    # -- RL605: donated argument read after the call ------------------------

    def _record_donation_call(self, node: ast.Call):
        func = node.func
        donated: tuple = ()
        if isinstance(func, ast.Name) and self._local_jit and func.id in (
            self._local_jit[-1]
        ):
            donated = self._local_jit[-1][func.id]
        elif isinstance(func, ast.Attribute) and func.attr in self.pre.donate:
            donated = self.pre.donate[func.attr]
        elif isinstance(func, ast.Name) and func.id in self.pre.donate:
            donated = self.pre.donate[func.id]
        if not donated or not self._donation_calls:
            return
        roots = []
        for pos in donated:
            if pos < len(node.args):
                root = _root_name(node.args[pos])
                if root:
                    roots.append(root)
        if roots:
            self._donation_calls[-1].append((node.lineno, roots))

    def _check_donation_reads(self, fn: ast.AST):
        """After `out = jitted(x)` with x donated, a later read of x sees a
        deleted buffer (jax raises) or, worse on some paths, aliased memory."""
        if not self._donation_calls or not self._donation_calls[-1]:
            return
        calls = self._donation_calls[-1]
        assigns: dict[str, list[int]] = {}
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    for leaf in ast.walk(t):
                        if isinstance(leaf, ast.Name):
                            assigns.setdefault(leaf.id, []).append(node.lineno)
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Name)
                    and isinstance(node.ctx, ast.Load)):
                continue
            for call_line, roots in calls:
                if node.id in roots and node.lineno > call_line:
                    # Reassigned at/after the donating call -> fresh value
                    # (`state, _ = step(state, ...)` rebinds on the call line).
                    if any(call_line <= a <= node.lineno
                           for a in assigns.get(node.id, [])):
                        continue
                    self.findings.append(Finding(
                        self.ctx.relpath, node.lineno, "RL605",
                        f"{node.id!r} was donated to a jitted call on line "
                        f"{call_line} (donate_argnums) and is read afterwards:"
                        " the buffer was handed to XLA and no longer holds "
                        "the value; rebind the name from the call's result",
                        self._symbol(),
                    ))

    # -- RL701: side effects inside traced functions -------------------------

    def _check_side_effects(self, fn: ast.AST):
        """A function handed to jit/scan/shard_map runs at TRACE time only:
        writes to self/globals/closures happen once per compilation, not per
        execution — silent state corruption the day the cache stops hitting."""
        local_names: set[str] = set()
        args = fn.args
        for a in (args.posonlyargs + args.args + args.kwonlyargs):
            local_names.add(a.arg)
        for a in (args.vararg, args.kwarg):
            if a is not None:
                local_names.add(a.arg)
        declared_global: set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                # Nested defs trace with the outer function; their params and
                # name are locals of *some* traced frame, which is all the
                # closure check needs.
                a = node.args
                for p in (a.posonlyargs + a.args + a.kwonlyargs):
                    local_names.add(p.arg)
                for p in (a.vararg, a.kwarg):
                    if p is not None:
                        local_names.add(p.arg)
                if not isinstance(node, ast.Lambda):
                    local_names.add(node.name)
            elif isinstance(node, (ast.Global, ast.Nonlocal)):
                declared_global.update(node.names)
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        local_names.add(t.id)
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                if isinstance(node.target, ast.Name):
                    local_names.add(node.target.id)
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                for t in ast.walk(node.target):
                    if isinstance(t, ast.Name):
                        local_names.add(t.id)
            elif isinstance(node, ast.comprehension):
                for t in ast.walk(node.target):
                    if isinstance(t, ast.Name):
                        local_names.add(t.id)
            elif isinstance(node, ast.With):
                for item in node.items:
                    if isinstance(item.optional_vars, ast.Name):
                        local_names.add(item.optional_vars.id)

        def emit(node, what):
            # self._scope already ends with fn's name (appended by _visit_fn).
            self.findings.append(Finding(
                self.ctx.relpath, node.lineno, "RL701",
                f"{what} inside a function handed to jax.jit/lax.scan/"
                "shard_map: the side effect runs at trace time (once per "
                "compilation), not per call — and a captured tracer here "
                "escapes the trace; return the new value instead",
                ".".join(self._scope),
            ))

        for node in ast.walk(fn):
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    if isinstance(t, (ast.Attribute, ast.Subscript)):
                        root = _root_name(t)
                        if root in ("self", "cls"):
                            emit(node, f"write to {root}.{_base_ident(t)}")
                        elif (root and root not in local_names
                              and isinstance(t, ast.Subscript)):
                            emit(node, f"write into closed-over {root!r}")
                    elif (isinstance(t, ast.Name)
                          and t.id in declared_global):
                        emit(node, f"write to global/nonlocal {t.id!r}")
            elif isinstance(node, ast.Expr) and isinstance(
                node.value, ast.Call
            ):
                # Only bare-statement mutator calls: `x.append(v)` is
                # mutation-for-effect; `new, st = tx.update(...)` is the
                # functional optax idiom whose result carries the state.
                f = node.value.func
                if isinstance(f, ast.Attribute) and f.attr in (
                    "append", "extend", "add", "update", "insert",
                    "setdefault", "pop", "remove", "clear",
                ):
                    root = _root_name(f.value)
                    if root in ("self", "cls"):
                        emit(node, f".{f.attr}() on {root} state")
                    elif root and root not in local_names and not isinstance(
                        f.value, ast.Call
                    ):
                        emit(node, f".{f.attr}() on closed-over {root!r}")


def check_jax_file(ctx: FileContext) -> list[Finding]:
    if not uses_jax(ctx.source):
        return []
    pre = _Prepass(ctx.tree)
    return _JaxChecker(ctx, pre).check_module().findings
