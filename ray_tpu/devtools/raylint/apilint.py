"""apilint: the RL10xx cross-process call-contract family.

Everything that crosses a process boundary in this framework is dynamically
dispatched: `.remote()` method names resolve as strings inside the worker
(`worker.py` `spec["method_name"]`), serve handles and the DP/PD routers
broadcast duck-typed stats/control surfaces across a growing roster of
classes, GCS verbs are bare strings at `gcs_call` sites, and the `_DEFS`
flag table is string-keyed. Every one of those contracts is invisible to the
other checker families — a typo becomes an `AttributeError`/`TypeError`
inside a remote worker, mid-chaos-test. apilint makes them fail at lint time:

- **RL1001** unknown-remote-method: `h.method.remote(...)` where `method`
  does not exist on the resolved target class (handle provenance tracked
  through `h = Cls.remote(...)` / `self._h = Cls.options(...).remote(...)`
  assignments), or — when the handle cannot be resolved — on ANY class or
  function in the scanned tree.
- **RL1002** remote-arity-mismatch: positional count / keyword names at a
  cross-process call site that no candidate target `def` accepts
  (defaults/`*args`/`**kwargs`-aware). Covers actor constructors
  (`Cls.remote(...)` vs `__init__`), handle method calls, `@remote`
  functions, and `gcs_call` verb arity vs the `rpc_<verb>` handler.
- **RL1003** protocol-drift: the cross-process surface protocols this
  codebase broadcasts (`PROTOCOL_TABLE`, the leaklint `RESOURCE_TABLE`
  shape) — a deployed class implementing any anchor of a roster must
  implement every member with a signature the broadcast call shape accepts.
- **RL1004** unknown-or-dead-flag: `CONFIG.<name>` reads of flags absent
  from `_DEFS` (pre-PR-21 these silently read nothing; now they raise, but
  only at runtime), and `_DEFS` entries no scanned file ever reads.
- **RL1005** unpicklable-at-boundary: lambdas, locally-defined functions,
  and OS handles (open files, locks, threads) passed as `.remote()`
  arguments. Closures DO cloudpickle, but they ship their captured enclosing
  state by value — a copy executes in the worker, silently diverging from
  the driver's state; OS handles don't survive the hop at all.
- **RL1006** unknown-gcs-verb: `gcs_call("verb", ...)` strings with no
  `rpc_<verb>` handler on the GCS service classes, and orphan handlers no
  string in the tree ever names.

Unlike the per-file families, apilint needs a tree-wide prepass:
`build_registry()` runs over every parsed file first (classes + method
signatures, actor/deployment detection, `_DEFS`, `rpc_*` verb tables,
`CONFIG` reads), then `check_api_file()` lints each file against it and
`tree_findings()` emits the aggregate checks (dead flags, orphan verbs).
Fixture files lint standalone because a single file is its own registry.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu.devtools.raylint.core import FileContext, Finding

#: Receiver names that denote the central flag singleton. `CONFIG` is the
#: canonical import; `_CFG` is ray_tpu/__init__.py's local alias.
_CONFIG_NAMES = frozenset({"CONFIG", "_CFG"})

#: Real methods on _Config — attribute access to these is not a flag read.
_CONFIG_METHODS = frozenset({"get"})

#: Ctor leaf names whose results are OS-backed and must not cross a pickle
#: boundary (RL1005).
_OS_HANDLE_CTORS = {
    "open": "open file handle",
    "Lock": "lock",
    "RLock": "lock",
    "Condition": "condition variable",
    "Semaphore": "semaphore",
    "BoundedSemaphore": "semaphore",
    "Thread": "thread object",
    "socket": "socket",
}


# -- signatures ---------------------------------------------------------------

@dataclass(frozen=True)
class Sig:
    """A callable's parameter shape, `self`/`cls` dropped for methods."""

    params: Tuple[str, ...]          # positional-or-keyword (incl pos-only)
    defaults: int                    # trailing params with defaults
    default_srcs: Tuple[str, ...]    # unparsed default exprs, same order
    kwonly: Tuple[str, ...]
    kwonly_required: Tuple[str, ...]
    kwonly_default_srcs: Tuple[str, ...]  # "" for required kw-only params
    vararg: bool
    kwarg: bool
    lineno: int

    def accepts(self, npos: int, kwnames: Tuple[str, ...]) -> Optional[str]:
        """None if a call with `npos` positional args and `kwnames` keyword
        args binds; otherwise a TypeError-style description."""
        if npos > len(self.params) and not self.vararg:
            return (f"takes at most {len(self.params)} positional "
                    f"argument(s), got {npos}")
        consumed = set(self.params[:min(npos, len(self.params))])
        for kw in kwnames:
            if kw in consumed:
                return f"got multiple values for argument {kw!r}"
            if (kw not in self.params and kw not in self.kwonly
                    and not self.kwarg):
                return f"got an unexpected keyword argument {kw!r}"
        required = self.params[:len(self.params) - self.defaults]
        missing = [p for p in required[npos:] if p not in kwnames]
        missing += [k for k in self.kwonly_required if k not in kwnames]
        if missing:
            return "missing required argument(s): " + ", ".join(
                repr(m) for m in missing
            )
        return None

    def render(self) -> str:
        """Deterministic human/text form for API_SURFACE.json."""
        parts: List[str] = []
        plain = len(self.params) - self.defaults
        for i, p in enumerate(self.params):
            if i < plain:
                parts.append(p)
            else:
                parts.append(f"{p}={self.default_srcs[i - plain]}")
        if self.vararg:
            parts.append("*args")
        elif self.kwonly:
            parts.append("*")
        for k, d in zip(self.kwonly, self.kwonly_default_srcs):
            parts.append(k if not d else f"{k}={d}")
        if self.kwarg:
            parts.append("**kwargs")
        return "(" + ", ".join(parts) + ")"


def _unparse(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:
        return "..."


def sig_of(fn: ast.AST, drop_first: bool) -> Sig:
    a = fn.args
    params = [p.arg for p in a.posonlyargs] + [p.arg for p in a.args]
    if drop_first and params:
        params = params[1:]
    defaults = list(a.defaults)
    kwonly = tuple(p.arg for p in a.kwonlyargs)
    kw_required, kw_srcs = [], []
    for p, d in zip(a.kwonlyargs, a.kw_defaults):
        if d is None:
            kw_required.append(p.arg)
            kw_srcs.append("")
        else:
            kw_srcs.append(_unparse(d))
    return Sig(
        params=tuple(params),
        defaults=len(defaults),
        default_srcs=tuple(_unparse(d) for d in defaults),
        kwonly=kwonly,
        kwonly_required=tuple(kw_required),
        kwonly_default_srcs=tuple(kw_srcs),
        vararg=a.vararg is not None,
        kwarg=a.kwarg is not None,
        lineno=getattr(fn, "lineno", 0),
    )


# -- the protocol table (RL1003) ----------------------------------------------

@dataclass(frozen=True)
class ProtocolSpec:
    """One duck-typed cross-process surface: defining any ANCHOR member
    makes a deployed class part of the protocol, which then requires EVERY
    member, each callable with its declared broadcast shape."""

    protocol: str
    #: member -> (npos, kwnames) the broadcast/collection site calls it with.
    members: Tuple[Tuple[str, Tuple[int, Tuple[str, ...]]], ...]
    anchors: Tuple[str, ...]


PROTOCOL_TABLE: Tuple[ProtocolSpec, ...] = (
    # serve_stats()/`ray_tpu status` collect these per replica; the DP/PD
    # routers broadcast them across their pools. A replica class exposing one
    # without the rest turns the operator snapshot into AttributeError.
    ProtocolSpec(
        "llm-stats-surface",
        members=(
            ("cache_stats", (0, ())),
            ("scheduler_stats", (0, ())),
            ("recorder_stats", (0, ())),
            ("capture_profile", (0, ("duration_s",))),
        ),
        anchors=("cache_stats", "scheduler_stats", "recorder_stats"),
    ),
    # The SLO autopilot's sticky managed set: a deployment is managed once
    # ANY replica answers autopilot_signals(), and managed deployments
    # receive set_tenant_weight broadcasts — implementing the signal without
    # the actuator detonates the weight law's broadcast.
    ProtocolSpec(
        "autopilot-surface",
        members=(
            ("autopilot_signals", (0, ())),
            ("set_tenant_weight", (2, ())),
        ),
        anchors=("autopilot_signals", "set_tenant_weight"),
    ),
    # Replica.prepare_shutdown() calls the wrapped instance's shutdown() with
    # zero args before the controller hard-kills; a shutdown that grew a
    # required parameter silently stops being graceful.
    ProtocolSpec(
        "graceful-shutdown",
        members=(("shutdown", (0, ())),),
        anchors=("shutdown",),
    ),
    # Round 21 (docs/generation.md): the streaming front-door pair. The
    # OpenAI router dispatches body["stream"] to generate_stream and
    # everything else to generate on the SAME handle — a deployed class
    # exposing the streaming half without the blocking twin (or accepting
    # different request knobs on each) breaks that dispatch, and the
    # SSE-vs-blocking token-identity tests stop meaning anything.
    ProtocolSpec(
        "llm-stream-surface",
        members=(
            ("generate", (1, ("max_tokens", "temperature", "top_k",
                              "lora", "guided"))),
            ("generate_stream", (1, ("max_tokens", "temperature", "top_k",
                                     "lora", "guided"))),
        ),
        anchors=("generate_stream",),
    ),
)


# -- registry -----------------------------------------------------------------

@dataclass
class ClassInfo:
    name: str
    relpath: str
    lineno: int
    bases: Tuple[str, ...]
    methods: Dict[str, Sig]
    self_attrs: Set[str]
    actor_via: Optional[str] = None    # how it crosses a process boundary


@dataclass
class FlagDef:
    name: str
    relpath: str
    lineno: int
    type_name: str
    default_src: str
    doc: str


@dataclass
class VerbDef:
    verb: str
    relpath: str
    lineno: int
    class_name: str
    sig: Sig                            # `self` and `conn` dropped


@dataclass
class ApiRegistry:
    classes: Dict[str, List[ClassInfo]] = field(default_factory=dict)
    #: every def anywhere, by leaf name (the RL1001 fallback universe)
    function_names: Set[str] = field(default_factory=set)
    method_universe: Set[str] = field(default_factory=set)
    remote_functions: Dict[str, List[Sig]] = field(default_factory=dict)
    flags: Dict[str, FlagDef] = field(default_factory=dict)
    flag_reads: Dict[str, List[Tuple[str, int]]] = field(default_factory=dict)
    gcs_verbs: Dict[str, VerbDef] = field(default_factory=dict)
    str_constants: Set[str] = field(default_factory=set)
    _resolve_cache: Dict[int, Tuple[Dict[str, List[Sig]], bool]] = field(
        default_factory=dict
    )

    # -- method resolution with in-tree inheritance --------------------------

    def resolved_methods(
        self, info: ClassInfo
    ) -> Tuple[Dict[str, List[Sig]], bool]:
        """-> ({method -> candidate sigs}, all_bases_resolved). Merges base
        classes resolvable by leaf name in the registry; a base the registry
        does not know (imported from outside the scanned tree) makes the
        method set open-ended, which demotes precise RL1001 to the weak
        universe check."""
        cached = self._resolve_cache.get(id(info))
        if cached is not None:
            return cached
        self._resolve_cache[id(info)] = ({}, False)  # cycle guard
        merged: Dict[str, List[Sig]] = {}
        closed = True
        for base in info.bases:
            if base == "object":
                continue
            candidates = self.classes.get(base)
            if not candidates:
                closed = False
                continue
            for c in candidates:
                bm, bclosed = self.resolved_methods(c)
                closed = closed and bclosed
                for name, sigs in bm.items():
                    merged.setdefault(name, []).extend(sigs)
        for name, sig in info.methods.items():
            merged[name] = [sig]   # own def overrides inherited candidates
        self._resolve_cache[id(info)] = (merged, closed)
        return merged, closed

    def actor_classes(self) -> List[ClassInfo]:
        out = []
        for infos in self.classes.values():
            out.extend(i for i in infos if i.actor_via)
        return out

    def method_candidates(self, name: str) -> List[Sig]:
        """Candidate sigs for an unresolved handle call: methods named `name`
        on actor classes first (the plausible targets), any class otherwise,
        plus same-named remote functions."""
        actor, anywhere = [], []
        for infos in self.classes.values():
            for info in infos:
                sig = info.methods.get(name)
                if sig is None:
                    continue
                (actor if info.actor_via else anywhere).append(sig)
        out = actor or anywhere
        out = out + self.remote_functions.get(name, [])
        return out


def _leaf(node: ast.expr) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def _root(node: ast.expr) -> Optional[str]:
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _is_remote_decorator(dec: ast.expr) -> bool:
    """@remote / @ray_tpu.remote / @remote(...) / @ray_tpu.remote(...)."""
    if isinstance(dec, ast.Call):
        dec = dec.func
    if isinstance(dec, ast.Name):
        return dec.id == "remote"
    if isinstance(dec, ast.Attribute):
        return dec.attr == "remote" and _root(dec) in ("ray_tpu", "ray")
    return False


def _is_deployment_decorator(dec: ast.expr) -> bool:
    if isinstance(dec, ast.Call):
        dec = dec.func
    return _leaf(dec) == "deployment"


def _unwrap_options(base: ast.expr) -> ast.expr:
    """`X.options(...).remote(...)` -> X (same for handle-method options)."""
    if (isinstance(base, ast.Call) and isinstance(base.func, ast.Attribute)
            and base.func.attr == "options"):
        return base.func.value
    return base


def _gcs_call_verb(node: ast.Call) -> Optional[str]:
    if _leaf(node.func) != "gcs_call":
        return None
    if node.args and isinstance(node.args[0], ast.Constant) and isinstance(
        node.args[0].value, str
    ):
        return node.args[0].value
    return None


def _is_gcsish_class(name: str, relpath: str) -> bool:
    import os as _os
    import re as _re

    parts = {p for p in _re.sub(
        r"([a-z0-9])([A-Z])", r"\1_\2", name
    ).lower().split("_") if p}
    return "gcs" in parts or _os.path.basename(relpath).startswith("gcs")


class _FileScan(ast.NodeVisitor):
    """Registry facts from one file: classes + signatures, actor-class
    markers, `@remote` functions, `_DEFS`, `rpc_*` verb handlers, CONFIG
    reads, and the string-constant pool."""

    def __init__(self, ctx: FileContext, reg: ApiRegistry):
        self.ctx = ctx
        self.reg = reg
        self._class_stack: List[ClassInfo] = []
        # names seen in `X.remote(...)` / wrap positions; resolved to classes
        # or functions once the whole tree is scanned.
        self.remote_instantiated: Set[str] = set()
        self.deployment_wrapped: Set[str] = set()

    def visit_ClassDef(self, node: ast.ClassDef):
        info = ClassInfo(
            name=node.name,
            relpath=self.ctx.relpath,
            lineno=node.lineno,
            bases=tuple(
                b for b in (_leaf(x) for x in node.bases) if b
            ),
            methods={},
            self_attrs=set(),
        )
        for dec in node.decorator_list:
            if _is_remote_decorator(dec):
                info.actor_via = "@remote"
            elif _is_deployment_decorator(dec):
                info.actor_via = "serve-deployment"
        for stmt in node.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                is_static = any(
                    _leaf(d) == "staticmethod" for d in stmt.decorator_list
                )
                info.methods[stmt.name] = sig_of(stmt, drop_first=not is_static)
                self.reg.method_universe.add(stmt.name)
                if stmt.name.startswith("rpc_") and _is_gcsish_class(
                    node.name, self.ctx.relpath
                ):
                    verb = stmt.name[len("rpc_"):]
                    self.reg.gcs_verbs.setdefault(verb, VerbDef(
                        verb=verb,
                        relpath=self.ctx.relpath,
                        lineno=stmt.lineno,
                        class_name=node.name,
                        # drop `conn` (the transport hands it in, callers
                        # never pass it)
                        sig=_drop_leading(sig_of(stmt, drop_first=True), 1),
                    ))
        for sub in ast.walk(node):
            if (isinstance(sub, ast.Attribute)
                    and isinstance(sub.ctx, ast.Store)
                    and isinstance(sub.value, ast.Name)
                    and sub.value.id == "self"):
                info.self_attrs.add(sub.attr)
        self.reg.classes.setdefault(node.name, []).append(info)
        self._class_stack.append(info)
        self.generic_visit(node)
        self._class_stack.pop()

    def _visit_fn(self, node):
        self.reg.function_names.add(node.name)
        if not self._class_stack or not isinstance(
            getattr(node, "parent", None), ast.ClassDef
        ):
            # any def (module-level or nested) counts for the fallback
            # universe; @remote functions additionally get an arity contract
            for dec in node.decorator_list:
                if _is_remote_decorator(dec):
                    self.reg.remote_functions.setdefault(node.name, []).append(
                        sig_of(node, drop_first=False)
                    )
        self.generic_visit(node)

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    def visit_Assign(self, node: ast.Assign):
        # _DEFS: dict[str, tuple[type, Any, str]] = {...} (plain Assign or
        # the annotated form handled in visit_AnnAssign)
        for t in node.targets:
            if isinstance(t, ast.Name) and t.id == "_DEFS":
                self._scan_defs(node.value)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign):
        if isinstance(node.target, ast.Name) and node.target.id == "_DEFS" \
                and node.value is not None:
            self._scan_defs(node.value)
        self.generic_visit(node)

    def _scan_defs(self, value: ast.expr):
        if not isinstance(value, ast.Dict):
            return
        for k, v in zip(value.keys, value.values):
            if not (isinstance(k, ast.Constant) and isinstance(k.value, str)):
                continue
            type_name, default_src, doc = "?", "?", ""
            if isinstance(v, ast.Tuple) and len(v.elts) >= 2:
                type_name = _leaf(v.elts[0]) or "?"
                default_src = _unparse(v.elts[1])
                if len(v.elts) >= 3 and isinstance(
                    v.elts[2], ast.Constant
                ) and isinstance(v.elts[2].value, str):
                    doc = v.elts[2].value
            self.reg.flags[k.value] = FlagDef(
                name=k.value, relpath=self.ctx.relpath, lineno=k.lineno,
                type_name=type_name, default_src=default_src, doc=doc,
            )

    def visit_Attribute(self, node: ast.Attribute):
        if (isinstance(node.ctx, ast.Load)
                and isinstance(node.value, ast.Name)
                and node.value.id in _CONFIG_NAMES
                and not node.attr.startswith("_")
                and node.attr not in _CONFIG_METHODS):
            self.reg.flag_reads.setdefault(node.attr, []).append(
                (self.ctx.relpath, node.lineno)
            )
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        func = node.func
        # X.remote(...) / X.options(...).remote(...): X is remote-instantiated
        if isinstance(func, ast.Attribute) and func.attr == "remote":
            base = _unwrap_options(func.value)
            if isinstance(base, ast.Name):
                self.remote_instantiated.add(base.id)
        # serve.deployment(X) / serve.deployment(...)(X) / remote(...)(X) /
        # ray_tpu.remote(X)
        target = None
        head = func
        if isinstance(head, ast.Call):
            head = head.func
        leaf = _leaf(head)
        if leaf == "deployment":
            target = self.deployment_wrapped
        elif leaf == "remote" and (
            isinstance(head, ast.Name)
            or (isinstance(head, ast.Attribute)
                and _root(head) in ("ray_tpu", "ray"))
        ):
            target = self.remote_instantiated
        if target is not None:
            for a in node.args:
                if isinstance(a, ast.Name):
                    target.add(a.id)
        # getattr(CONFIG, "name") and CONFIG.get("name") count as flag reads
        if (_leaf(func) == "getattr" and len(node.args) >= 2
                and isinstance(node.args[0], ast.Name)
                and node.args[0].id in _CONFIG_NAMES
                and isinstance(node.args[1], ast.Constant)
                and isinstance(node.args[1].value, str)):
            self.reg.flag_reads.setdefault(node.args[1].value, []).append(
                (self.ctx.relpath, node.lineno)
            )
        if (isinstance(func, ast.Attribute) and func.attr == "get"
                and isinstance(func.value, ast.Name)
                and func.value.id in _CONFIG_NAMES
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            self.reg.flag_reads.setdefault(node.args[0].value, []).append(
                (self.ctx.relpath, node.lineno)
            )
        self.generic_visit(node)

    def visit_Constant(self, node: ast.Constant):
        if isinstance(node.value, str) and len(node.value) <= 80:
            self.reg.str_constants.add(node.value)


def _drop_leading(sig: Sig, n: int) -> Sig:
    params = sig.params[n:]
    dropped_defaults = max(0, sig.defaults - len(params))
    return Sig(
        params=params,
        defaults=sig.defaults - dropped_defaults,
        default_srcs=sig.default_srcs[dropped_defaults:],
        kwonly=sig.kwonly,
        kwonly_required=sig.kwonly_required,
        kwonly_default_srcs=sig.kwonly_default_srcs,
        vararg=sig.vararg,
        kwarg=sig.kwarg,
        lineno=sig.lineno,
    )


def build_registry(ctxs: List[FileContext]) -> ApiRegistry:
    reg = ApiRegistry()
    pending_remote: Set[str] = set()
    pending_deploy: Set[str] = set()
    for ctx in ctxs:
        scan = _FileScan(ctx, reg)
        scan.visit(ctx.tree)
        pending_remote |= scan.remote_instantiated
        pending_deploy |= scan.deployment_wrapped
    for name in pending_deploy:
        for info in reg.classes.get(name, ()):
            info.actor_via = info.actor_via or "serve-deployment"
    for name in pending_remote:
        infos = reg.classes.get(name)
        if infos:
            for info in infos:
                info.actor_via = info.actor_via or "remote-instantiation"
    return reg


# -- per-file checks ----------------------------------------------------------

class _ApiChecker(ast.NodeVisitor):
    def __init__(self, ctx: FileContext, reg: ApiRegistry):
        self.ctx = ctx
        self.reg = reg
        self.findings: List[Finding] = []
        self._scope: List[str] = []
        self._class_info_stack: List[Optional[ClassInfo]] = []
        # per-function-scope maps: var -> actor class name ("handle"),
        # var -> class name ("class object"), var -> RL1005 hazard kind
        self._handle_scopes: List[Dict[str, str]] = [{}]
        self._clsobj_scopes: List[Dict[str, str]] = [{}]
        self._hazard_scopes: List[Dict[str, str]] = [{}]
        # per-enclosing-class attr maps (self._h = Cls.remote(...))
        self._attr_handles: List[Dict[str, str]] = []
        self._attr_clsobjs: List[Dict[str, str]] = []

    # -- bookkeeping ---------------------------------------------------------

    def _symbol(self) -> str:
        return ".".join(self._scope) if self._scope else "<module>"

    def _emit(self, node: ast.AST, code: str, message: str,
              symbol: Optional[str] = None):
        self.findings.append(Finding(
            self.ctx.relpath, getattr(node, "lineno", 0), code, message,
            symbol if symbol is not None else self._symbol(),
        ))

    def _my_class_info(self) -> Optional[ClassInfo]:
        for info in reversed(self._class_info_stack):
            if info is not None:
                return info
        return None

    def visit_ClassDef(self, node: ast.ClassDef):
        info = None
        for c in self.reg.classes.get(node.name, ()):
            if c.relpath == self.ctx.relpath and c.lineno == node.lineno:
                info = c
                break
        if info is not None:
            self._check_rl1003(node, info)
        self._scope.append(node.name)
        self._class_info_stack.append(info)
        # pre-collect handle/class-object attributes assigned anywhere in the
        # class, so method order doesn't matter
        attr_handles: Dict[str, str] = {}
        attr_clsobjs: Dict[str, str] = {}
        for sub in ast.walk(node):
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                t = sub.targets[0]
                if (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    cls = self._instantiated_class(sub.value)
                    if cls:
                        attr_handles[t.attr] = cls
                    elif (isinstance(sub.value, ast.Name)
                          and sub.value.id in self.reg.classes):
                        attr_clsobjs[t.attr] = sub.value.id
        self._attr_handles.append(attr_handles)
        self._attr_clsobjs.append(attr_clsobjs)
        self.generic_visit(node)
        self._attr_handles.pop()
        self._attr_clsobjs.pop()
        self._class_info_stack.pop()
        self._scope.pop()

    def _visit_fn(self, node):
        # a def nested inside another function is a locally-defined function:
        # shipping it through .remote() ships its closure by value
        if self._handle_scopes[-1] is not self._handle_scopes[0] or \
                len(self._handle_scopes) > 1:
            self._hazard_scopes[-1].setdefault(
                node.name, "locally-defined function"
            )
        self._scope.append(node.name)
        self._class_info_stack.append(None)
        self._handle_scopes.append(dict(self._handle_scopes[-1]))
        self._clsobj_scopes.append(dict(self._clsobj_scopes[-1]))
        self._hazard_scopes.append({})
        self.generic_visit(node)
        self._hazard_scopes.pop()
        self._clsobj_scopes.pop()
        self._handle_scopes.pop()
        self._class_info_stack.pop()
        self._scope.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    # -- assignment tracking -------------------------------------------------

    def _instantiated_class(self, value: ast.expr) -> Optional[str]:
        """`Cls.remote(...)` / `Cls.options(...).remote(...)` -> "Cls"."""
        if not (isinstance(value, ast.Call)
                and isinstance(value.func, ast.Attribute)
                and value.func.attr == "remote"):
            return None
        base = _unwrap_options(value.func.value)
        if isinstance(base, ast.Name) and base.id in self.reg.classes:
            return base.id
        return None

    def visit_Assign(self, node: ast.Assign):
        if len(node.targets) == 1 and isinstance(node.targets[0], ast.Name):
            name = node.targets[0].id
            cls = self._instantiated_class(node.value)
            if cls:
                self._handle_scopes[-1][name] = cls
                self._hazard_scopes[-1].pop(name, None)
            elif isinstance(node.value, ast.Lambda):
                self._hazard_scopes[-1][name] = "lambda"
            elif isinstance(node.value, ast.Call):
                leaf = _leaf(node.value.func)
                if leaf in _OS_HANDLE_CTORS:
                    self._hazard_scopes[-1][name] = _OS_HANDLE_CTORS[leaf]
                else:
                    self._hazard_scopes[-1].pop(name, None)
                    self._handle_scopes[-1].pop(name, None)
            elif (isinstance(node.value, ast.Name)
                    and node.value.id in self.reg.classes):
                self._clsobj_scopes[-1][name] = node.value.id
            else:
                self._hazard_scopes[-1].pop(name, None)
                self._handle_scopes[-1].pop(name, None)
                self._clsobj_scopes[-1].pop(name, None)
        self.generic_visit(node)

    # -- RL1003 --------------------------------------------------------------

    def _check_rl1003(self, node: ast.ClassDef, info: ClassInfo):
        if not info.actor_via:
            return
        methods, closed = self.reg.resolved_methods(info)
        if "__getattr__" in methods:
            return  # dynamic attribute surface: membership is unknowable
        for spec in PROTOCOL_TABLE:
            if not any(a in methods for a in spec.anchors):
                continue
            missing, drifted = [], []
            for member, (npos, kwnames) in spec.members:
                sigs = methods.get(member)
                if sigs is None:
                    if closed:
                        missing.append(member)
                    continue
                problems = [s.accepts(npos, tuple(kwnames)) for s in sigs]
                if all(p is not None for p in problems):
                    drifted.append(f"{member}{sigs[0].render()}: {problems[0]}")
            if missing:
                self._emit(
                    node, "RL1003",
                    f"class {info.name} implements part of the "
                    f"{spec.protocol!r} cross-process protocol but is "
                    f"missing {', '.join(sorted(missing))} — duck-typed "
                    "broadcasts/collections across this surface fail on "
                    "exactly this class; implement the full roster or "
                    "rename the partial member off the protocol",
                    symbol=info.name,
                )
            for d in drifted:
                self._emit(
                    node, "RL1003",
                    f"class {info.name}: {spec.protocol!r} protocol member "
                    f"{d} — the broadcast call shape no longer binds",
                    symbol=info.name,
                )

    # -- RL1004 --------------------------------------------------------------

    def visit_Attribute(self, node: ast.Attribute):
        if (self.reg.flags
                and isinstance(node.ctx, ast.Load)
                and isinstance(node.value, ast.Name)
                and node.value.id in _CONFIG_NAMES
                and not node.attr.startswith("_")
                and node.attr not in _CONFIG_METHODS
                and node.attr not in self.reg.flags):
            import difflib

            close = difflib.get_close_matches(
                node.attr, list(self.reg.flags), n=1
            )
            hint = f" — did you mean {close[0]!r}?" if close else ""
            self._emit(
                node, "RL1004",
                f"config read of unknown flag {node.attr!r}: not in _DEFS, "
                f"so this raises KeyError at runtime{hint}",
            )
        self.generic_visit(node)

    # -- calls: RL1001 / RL1002 / RL1005 / RL1006 ----------------------------

    def _resolve_receiver(self, recv: ast.expr) -> Optional[str]:
        if isinstance(recv, ast.Name):
            return self._handle_scopes[-1].get(recv.id)
        if (isinstance(recv, ast.Attribute)
                and isinstance(recv.value, ast.Name)
                and recv.value.id == "self"
                and self._attr_handles):
            return self._attr_handles[-1].get(recv.attr)
        return None

    def _call_shape(self, node: ast.Call):
        """-> (npos, kwnames) or None when *args/**kwargs make it dynamic."""
        if any(isinstance(a, ast.Starred) for a in node.args):
            return None
        if any(kw.arg is None for kw in node.keywords):
            return None
        return len(node.args), tuple(kw.arg for kw in node.keywords)

    def _check_remote_call(self, node: ast.Call):
        base = _unwrap_options(node.func.value)
        shape = self._call_shape(node)
        self._check_rl1005(node)
        if isinstance(base, ast.Name):
            name = base.id
            cls = self._clsobj_scopes[-1].get(name) or (
                name if name in self.reg.classes else None
            )
            if cls:
                self._check_ctor(node, cls, shape)
            elif name in self.reg.remote_functions:
                self._check_against(
                    node, self.reg.remote_functions[name], shape,
                    f"remote function {name}",
                )
            return
        if not isinstance(base, ast.Attribute):
            return
        method = base.attr
        recv = base.value
        # `self.X.remote(...)`: X is a value attribute of this class — a
        # stored class object (ctor) or a stored remote-function handle.
        if isinstance(recv, ast.Name) and recv.id in ("self", "cls"):
            if self._attr_clsobjs and method in self._attr_clsobjs[-1]:
                self._check_ctor(node, self._attr_clsobjs[-1][method], shape)
            return
        cls = self._resolve_receiver(recv)
        if cls is not None:
            self._check_handle_method(node, cls, method, shape)
            return
        # Unresolved handle: weak checks against the whole-tree universe.
        # Only meaningful when the scanned set declares methods at all —
        # a classless scratch file would otherwise flag every method name.
        if method.startswith("_") or not self.reg.method_universe:
            return
        if (method not in self.reg.method_universe
                and method not in self.reg.function_names
                and method not in self.reg.remote_functions):
            self._emit(
                node, "RL1001",
                f".remote() call to {method!r}: no class or function in the "
                "scanned tree defines this name — the method string resolves "
                "at the worker and raises AttributeError inside the remote "
                "process",
            )
            return
        candidates = self.reg.method_candidates(method)
        if candidates and shape is not None:
            self._check_against(
                node, candidates, shape, f"remote method {method}",
                any_ok=True,
            )

    def _check_ctor(self, node: ast.Call, cls_name: str, shape):
        infos = self.reg.classes.get(cls_name, [])
        if not infos or shape is None:
            return
        sigs, closed = [], True
        for info in infos:
            methods, c = self.reg.resolved_methods(info)
            closed = closed and c
            init = methods.get("__init__")
            if init:
                sigs.extend(init)
        if not sigs:
            if not closed:
                return  # __init__ may live on an unscanned base
            sigs = [Sig((), 0, (), (), (), (), False, False, 0)]
        self._check_against(
            node, sigs, shape, f"{cls_name}.__init__", any_ok=True,
        )

    def _check_handle_method(self, node: ast.Call, cls_name: str,
                             method: str, shape):
        infos = self.reg.classes.get(cls_name, [])
        sigs = []
        closed = True
        dynamic = False
        for info in infos:
            methods, c = self.reg.resolved_methods(info)
            closed = closed and c
            dynamic = dynamic or "__getattr__" in methods
            found = methods.get(method)
            if found:
                sigs.extend(found)
        if not sigs:
            if closed and not dynamic:
                self._emit(
                    node, "RL1001",
                    f".remote() call to {cls_name}.{method}: class "
                    f"{cls_name} defines no such method — resolves as a "
                    "string at the worker and raises AttributeError inside "
                    "the remote process",
                )
            return
        if shape is not None:
            self._check_against(
                node, sigs, shape, f"{cls_name}.{method}", any_ok=True,
            )

    def _check_against(self, node: ast.Call, sigs: List[Sig], shape,
                       what: str, any_ok: bool = False):
        if shape is None or not sigs:
            return
        npos, kwnames = shape
        problems = [s.accepts(npos, kwnames) for s in sigs]
        if any(p is None for p in problems):
            return
        self._emit(
            node, "RL1002",
            f"cross-process call does not bind to {what}"
            f"{sigs[0].render()}: {problems[0]} — the TypeError fires "
            "inside the remote worker, not here",
        )

    def _check_rl1005(self, node: ast.Call):
        values = list(node.args) + [kw.value for kw in node.keywords]
        for v in values:
            if isinstance(v, ast.Starred):
                v = v.value
            kind = None
            if isinstance(v, ast.Lambda):
                kind = "lambda"
            elif isinstance(v, ast.Name):
                kind = self._hazard_scopes[-1].get(v.id)
            elif isinstance(v, ast.Call):
                leaf = _leaf(v.func)
                kind = _OS_HANDLE_CTORS.get(leaf)
            if kind is None:
                continue
            if kind in ("lambda", "locally-defined function"):
                msg = (
                    f"{kind} passed across a .remote() submission boundary: "
                    "closures cloudpickle BY VALUE with their captured "
                    "enclosing state — the worker executes a copy that "
                    "silently diverges from the driver; pass a module-level "
                    "function and explicit arguments instead"
                )
            else:
                msg = (
                    f"{kind} passed across a .remote() submission boundary: "
                    "OS-backed handles do not survive the pickle hop — open/"
                    "construct it inside the remote task instead"
                )
            self._emit(node, "RL1005", msg)

    def _check_gcs_call(self, node: ast.Call):
        verb = _gcs_call_verb(node)
        if verb is None or not self.reg.gcs_verbs:
            return
        vdef = self.reg.gcs_verbs.get(verb)
        if vdef is None:
            import difflib

            close = difflib.get_close_matches(
                verb, list(self.reg.gcs_verbs), n=1
            )
            hint = f" — did you mean {close[0]!r}?" if close else ""
            self._emit(
                node, "RL1006",
                f"gcs_call verb {verb!r} has no rpc_{verb} handler on the "
                f"GCS service{hint} — the call fails with an unknown-method "
                "error at the server",
            )
            return
        # arity: gcs_call(verb, *args) forwards positionally only (its own
        # keywords — timeout/deadline_s — stay client-side)
        if any(isinstance(a, ast.Starred) for a in node.args):
            return
        npos = len(node.args) - 1
        problem = vdef.sig.accepts(npos, ())
        if problem is not None:
            self._emit(
                node, "RL1002",
                f"gcs_call({verb!r}, ...) does not bind to "
                f"rpc_{verb}{vdef.sig.render()}: {problem} — the TypeError "
                "fires inside the GCS server",
            )

    def _check_config_get(self, node: ast.Call):
        """CONFIG.get("name") with a constant key and no fallback default is
        the same typo surface as attribute access."""
        func = node.func
        if not (isinstance(func, ast.Attribute) and func.attr == "get"
                and isinstance(func.value, ast.Name)
                and func.value.id in _CONFIG_NAMES):
            return
        if len(node.args) != 1 or node.keywords:
            return  # an explicit default makes the unknown key intentional
        key = node.args[0]
        if not (isinstance(key, ast.Constant) and isinstance(key.value, str)):
            return
        if self.reg.flags and key.value not in self.reg.flags:
            import difflib

            close = difflib.get_close_matches(
                key.value, list(self.reg.flags), n=1
            )
            hint = f" — did you mean {close[0]!r}?" if close else ""
            self._emit(
                node, "RL1004",
                f"config read of unknown flag {key.value!r}: not in _DEFS, "
                f"so this raises KeyError at runtime{hint}",
            )

    def visit_Call(self, node: ast.Call):
        if isinstance(node.func, ast.Attribute) and node.func.attr == "remote":
            self._check_remote_call(node)
        self._check_gcs_call(node)
        self._check_config_get(node)
        self.generic_visit(node)


def check_api_file(ctx: FileContext, reg: ApiRegistry) -> List[Finding]:
    checker = _ApiChecker(ctx, reg)
    checker.visit(ctx.tree)
    return checker.findings


# -- tree-wide findings -------------------------------------------------------

def tree_findings(reg: ApiRegistry) -> List[Finding]:
    """Aggregate checks that only make sense over the whole run: dead flags
    (RL1004) and orphan GCS verbs (RL1006)."""
    out: List[Finding] = []
    # Dead flags: only when the run plausibly contains the consumers — i.e.
    # at least one flag read was seen at all. A run over config.py alone (or
    # a --changed run touching only it) skips the analysis instead of
    # declaring the entire table dead.
    if reg.flags and reg.flag_reads:
        for name in sorted(reg.flags):
            if name in reg.flag_reads:
                continue
            f = reg.flags[name]
            out.append(Finding(
                f.relpath, f.lineno, "RL1004",
                f"flag {name!r} is declared in _DEFS but never read "
                "anywhere in the scanned tree — a dead flag documents "
                "behavior the code does not have; delete it or wire it up",
                "_DEFS",
            ))
    # Orphan verbs: a handler nothing in the tree ever names as a string is
    # unreachable API surface (server-internal dispatch and peer replication
    # verbs reference their names as strings too, so they stay covered).
    for verb in sorted(reg.gcs_verbs):
        if verb in reg.str_constants:
            continue
        v = reg.gcs_verbs[verb]
        out.append(Finding(
            v.relpath, v.lineno, "RL1006",
            f"orphan GCS handler rpc_{verb} on {v.class_name}: no string in "
            "the scanned tree names this verb, so no client can reach it — "
            "delete it or add the missing call site",
            f"{v.class_name}.rpc_{verb}",
        ))
    return out
