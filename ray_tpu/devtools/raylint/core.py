"""raylint engine: file walking, suppression comments, baseline matching.

The checkers themselves live in `checkers.py`; this module owns everything
that makes their findings actionable as a CI gate: stable finding identity,
`# raylint: disable=` comments, and the checked-in baseline of grandfathered
findings.
"""

from __future__ import annotations

import ast
import io
import json
import os
import tokenize
from dataclasses import dataclass, field
from typing import Iterable

#: code -> one-line contract (the CLI prints this for --codes).
CODES: dict[str, str] = {
    "RL101": "await-under-lock: `await` inside a sync `with <lock>:` body "
             "stalls every other task contending for the lock",
    "RL102": "blocking-in-async: blocking call (time.sleep, queue.get, "
             "lock.acquire, ray_tpu.get, subprocess, fut.result) inside an "
             "`async def` body stalls the whole event loop",
    "RL201": "lock-order-cycle: cycle in the static lock acquisition-order "
             "graph (nested `with` acquisitions) — a deadlock waiting for "
             "the right interleaving",
    "RL301": "aliased-mutation: in-place mutation of an object reached "
             "through a caller-owned container/parameter without copying it "
             "first — overrides leak into the caller's shared state",
    "RL302": "mutable-default: dataclass field(default=<mutable>) is one "
             "object shared by every instance",
    "RL401": "swallowed-exception: broad `except` whose body neither "
             "re-raises, logs, returns a value, nor explains itself",
    "RL501": "unreleased-ref: `.remote()`/`execute()` result discarded "
             "without get/await/release — leaks capacity or hides failures",
    # -- jaxlint family (compute plane; only runs in files importing jax) ----
    "RL601": "jit-in-hot-path: `jax.jit(...)` constructed inside a loop or "
             "invoked in the same expression inside a function — the wrapper "
             "(and its compiled program) dies with the frame, re-tracing "
             "every call",
    "RL602": "unbounded-program-cache: jitted program stored into a dict "
             "with no cap/eviction — request-derived keys compile programs "
             "unboundedly under an adversarial input mix",
    "RL603": "host-sync-in-loop: device->host readback (np.asarray, "
             "float/int, .item, .tolist, block_until_ready, device_get) on "
             "a device value inside a decode/train loop, loop-called helper, "
             "or async frame — stalls the dispatch pipeline per step",
    "RL604": "retrace-hazard: Python list or raw len()-shaped array passed "
             "to a jitted callable — every distinct length compiles a new "
             "program; bucket shapes or mark arguments static",
    "RL605": "donation-misuse: an argument donated to a jitted call "
             "(donate_argnums) is read after the call — the buffer was "
             "handed to XLA and no longer holds the value",
    "RL701": "side-effect-under-jit: a function handed to jax.jit/lax.scan/"
             "shard_map mutates self/globals/closures — the effect runs at "
             "trace time only and captured tracers escape the trace",
    # -- leaklint family (resource-lifetime plane) ---------------------------
    "RL801": "unreleased-acquire: an acquired resource (slot-view lease, KV "
             "prefix lease, arena pin, stream channel, rpc conn, rank token) "
             "is not released on every path — no finally/with, and the "
             "handle neither returned, stored, nor passed on",
    "RL802": "release-via-gc-only: a cross-process resource release "
             "reachable only from __del__ — GC timing (or an uncollected "
             "cycle) then decides when the peer's pin/slot/rank frees",
    "RL803": "use-after-release / double-release of a resource handle along "
             "a straight-line path (no re-acquire in between)",
    "RL804": "fragile-release: a failing release silently swallowed by an "
             "undocumented broad except, or a release performed under a "
             "different lock than its acquire",
    # -- distlint family (distributed-contract plane) ------------------------
    "RL901": "metric-outside-report-path: Counter.inc/Gauge.set/Histogram."
             "observe reachable from outside the stats()/scheduler_stats()/"
             "recorder_stats()/report()/control_plane_stats() roster — every "
             "mutation may flush, and a flush is a blocking GCS RPC",
    "RL902": "rpc-in-forbidden-context: blocking control-plane RPC "
             "(gcs_call, KV verbs, by-name get_actor, rpc connect) in a "
             "__del__/weakref finalizer, under a held lock, or in a "
             "scheduler/decode hot context",
    "RL903": "remote-unsafe-exception: exception class whose custom "
             "__init__ does not forward its args verbatim and that defines "
             "no __reduce__ — it re-raises mangled (or not at all) after a "
             ".remote()/RPC pickle round-trip",
    "RL904": "trace-ctx-across-executor: tracing.current()/"
             "propagation_context() read inside a callback handed to "
             "run_in_executor/submit/Thread — contextvars do not cross "
             "threads; capture trace_ctx before the hop and pass it "
             "explicitly",
    "RL905": "await-rpc-under-lock: await of a cross-process call "
             "(.remote(), gcs verbs, or a helper that performs one) while "
             "holding an async lock — or a sync-lock-held call into a "
             "helper that blocks on the control plane (the interprocedural "
             "RL101/RL902 extension)",
    # -- apilint family (cross-process call-contract plane) -------------------
    "RL1001": "unknown-remote-method: `.remote()`/handle call names a method "
              "that does not exist on the resolved target actor class (or "
              "anywhere in the tree) — it resolves as a string at the worker "
              "and detonates as AttributeError inside the remote process",
    "RL1002": "remote-arity-mismatch: positional count or keyword names at a "
              "cross-process call site don't fit the target `def` "
              "(defaults/*args/**kwargs-aware) — the TypeError fires inside "
              "the worker, not at the call site",
    "RL1003": "protocol-drift: a class implementing part of a declared "
              "cross-process surface protocol (stats roster, autopilot "
              "signal/actuator pair, graceful-shutdown) is missing the rest "
              "or disagrees on a member's signature — duck-typed broadcasts "
              "then fail on exactly this class",
    "RL1004": "unknown-or-dead-flag: a config read names a flag absent from "
              "`_DEFS` (typo = KeyError at runtime, silence before PR 21), "
              "or a declared flag is never read anywhere in the tree",
    "RL1005": "unpicklable-at-boundary: a lambda, locally-defined function, "
              "or open OS handle (file, lock, thread) passed as a `.remote()`"
              " argument — closures ship their captured enclosing state by "
              "value and OS handles don't survive the pickle hop at all",
    "RL1006": "unknown-gcs-verb: a `gcs_call(...)` verb string with no "
              "rpc_<verb> handler on the GCS service (or an orphan handler "
              "no call site ever names)",
}

#: Checker families, for the CLI's `--family` filter and the per-family
#: tier-1 gates: each lint plane can run and be gated independently.
#: RL10xx codes are six chars long, so the single-digit plane index only
#: applies to the five-char classic codes.
FAMILIES: dict[str, frozenset] = {
    "concurrency": frozenset(
        c for c in CODES if len(c) == 5 and c[2] in "12345"
    ),
    "jax": frozenset(c for c in CODES if len(c) == 5 and c[2] in "67"),
    "leak": frozenset(c for c in CODES if len(c) == 5 and c[2] == "8"),
    "dist": frozenset(c for c in CODES if len(c) == 5 and c[2] == "9"),
    "api": frozenset(c for c in CODES if c.startswith("RL10")),
}

_DISABLE_MARK = "raylint:"


@dataclass(frozen=True)
class Finding:
    path: str          # normalized, package-relative posix path
    line: int
    code: str
    message: str
    symbol: str        # enclosing "Class.func" / "func" / "<module>"

    def render(self) -> str:
        return f"{self.path}:{self.line} {self.code} {self.message}"


@dataclass
class FileContext:
    """Everything a checker needs about one source file."""

    abspath: str
    relpath: str                       # package-relative posix path
    source: str
    tree: ast.AST
    # line -> set of disabled codes ("*" disables all) for that line.
    line_disables: dict[int, set[str]] = field(default_factory=dict)
    file_disables: set[str] = field(default_factory=set)
    # lines (1-based) that contain any comment text — RL401 treats an
    # explanatory comment inside a handler as documentation.
    comment_lines: set[int] = field(default_factory=set)


def normalize_path(abspath: str) -> str:
    """Path relative to the directory holding the top-level package, so
    baseline entries survive checkouts at different roots. Files outside any
    package (no __init__.py chain) normalize to their basename."""
    abspath = os.path.abspath(abspath)
    d = os.path.dirname(abspath)
    root = None
    while os.path.isfile(os.path.join(d, "__init__.py")):
        root = d
        d = os.path.dirname(d)
        if d == root:  # filesystem root guard
            break
    if root is None:
        return os.path.basename(abspath)
    return os.path.relpath(abspath, os.path.dirname(root)).replace(os.sep, "/")


def _parse_suppressions(ctx: FileContext) -> None:
    """Collect `# raylint: disable=RLxxx[,RLyyy]` comments.

    A trailing comment suppresses its own line; a standalone comment line
    suppresses the next non-comment line. `# raylint: disable-file=RLxxx`
    anywhere suppresses the code for the whole file."""
    try:
        tokens = tokenize.generate_tokens(io.StringIO(ctx.source).readline)
        comments = []
        code_lines = set()
        for tok in tokens:
            if tok.type == tokenize.COMMENT:
                comments.append((tok.start[0], tok.line, tok.string))
                ctx.comment_lines.add(tok.start[0])
            elif tok.type not in (
                tokenize.NL, tokenize.NEWLINE, tokenize.INDENT,
                tokenize.DEDENT, tokenize.ENCODING, tokenize.ENDMARKER,
            ):
                code_lines.add(tok.start[0])
    except tokenize.TokenError:
        return
    for lineno, line, text in comments:
        body = text.lstrip("#").strip()
        if not body.startswith(_DISABLE_MARK):
            continue
        directive = body[len(_DISABLE_MARK):].strip()
        # Anything after the code list is a justification, e.g.
        # `# raylint: disable=RL501 (idempotent fire-and-forget)`.
        for kind, target in (("disable-file=", ctx.file_disables), ):
            if directive.startswith(kind):
                codes = directive[len(kind):].split(None, 1)[0]
                target.update(c.strip() for c in codes.split(",") if c.strip())
                break
        else:
            if directive.startswith("disable="):
                raw_codes = directive[len("disable="):].split(None, 1)[0]
                codes = {
                    c.strip() for c in raw_codes.split(",") if c.strip()
                }
                # Standalone comment -> applies to the next code line; trailing
                # comment -> applies to its own line.
                target_line = lineno
                if lineno not in code_lines:
                    nxt = [ln for ln in code_lines if ln > lineno]
                    target_line = min(nxt) if nxt else lineno
                ctx.line_disables.setdefault(target_line, set()).update(codes)


def _is_suppressed(ctx: FileContext, f: Finding) -> bool:
    if f.code in ctx.file_disables or "*" in ctx.file_disables:
        return True
    disabled = ctx.line_disables.get(f.line, set())
    return f.code in disabled or "*" in disabled


def _load_context(abspath: str):
    """-> (FileContext, None) or (None, syntax-error Finding)."""
    with open(abspath, encoding="utf-8") as fh:
        source = fh.read()
    try:
        tree = ast.parse(source, filename=abspath)
    except SyntaxError as e:
        return None, Finding(normalize_path(abspath), e.lineno or 0, "RL000",
                             f"syntax error: {e.msg}", "<module>")
    ctx = FileContext(abspath=abspath, relpath=normalize_path(abspath),
                      source=source, tree=tree)
    _parse_suppressions(ctx)
    return ctx, None


def lint_file(abspath: str, codes: set[str] | None = None) -> list[Finding]:
    """Lint one file (including its own lock graph and api registry)."""
    return lint_paths([abspath], codes=codes)


def iter_python_files(paths: Iterable[str]) -> list[str]:
    out = []
    for p in paths:
        if os.path.isfile(p) and p.endswith(".py"):
            out.append(os.path.abspath(p))
        elif os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(
                    d for d in dirnames if d != "__pycache__"
                )
                for name in sorted(filenames):
                    if name.endswith(".py"):
                        out.append(os.path.abspath(os.path.join(dirpath, name)))
    return out


def lint_paths(paths: Iterable[str],
               codes: set[str] | None = None) -> list[Finding]:
    """Two-pass run: every file parses into a FileContext first, the apilint
    registry (actor classes, flags, GCS verbs) is built over ALL of them, then
    the per-file checkers run with that tree-wide context. RL201 lock edges
    and RL1004/RL1006 tree findings aggregate across the whole run."""
    from ray_tpu.devtools.raylint import apilint, checkers

    findings: list[Finding] = []
    ctxs: list[FileContext] = []
    for abspath in iter_python_files(paths):
        ctx, err = _load_context(abspath)
        if err is not None:
            findings.append(err)
        else:
            ctxs.append(ctx)

    registry = apilint.build_registry(ctxs)
    all_edges = []
    for ctx in ctxs:
        file_findings, edges = checkers.check_file(ctx)
        file_findings = file_findings + apilint.check_api_file(ctx, registry)
        findings.extend(
            f for f in file_findings if not _is_suppressed(ctx, f)
        )
        all_edges.extend(edges)
    findings.extend(checkers.lock_cycle_findings(all_edges))
    # Tree-wide findings (dead flags, orphan GCS verbs) anchor to their
    # declaration line; suppression comments there still apply.
    ctx_by_path = {c.relpath: c for c in ctxs}
    for f in apilint.tree_findings(registry):
        ctx = ctx_by_path.get(f.path)
        if ctx is None or not _is_suppressed(ctx, f):
            findings.append(f)
    if codes:
        findings = [f for f in findings if f.code in codes]
    findings.sort(key=lambda f: (f.path, f.line, f.code))
    return findings


# -- baseline -----------------------------------------------------------------

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.json")


def load_baseline(path: str | None = None) -> list[dict]:
    """Baseline entries: {"file", "code", "symbol", "reason"}. `symbol` may be
    "*" to cover a whole file+code pair. One entry grandfathers every finding
    it matches — line numbers are deliberately not part of the identity so
    unrelated edits don't churn the baseline."""
    path = path or DEFAULT_BASELINE
    if not os.path.isfile(path):
        return []
    with open(path, encoding="utf-8") as fh:
        data = json.load(fh)
    return list(data.get("entries", []))


def _matches(entry: dict, f: Finding) -> bool:
    return (
        entry.get("code") == f.code
        and entry.get("file") == f.path
        and entry.get("symbol") in ("*", f.symbol)
    )


def partition_baselined(
    findings: list[Finding], entries: list[dict]
) -> tuple[list[Finding], list[Finding], list[dict]]:
    """-> (violations, grandfathered, stale_entries)."""
    violations, grandfathered = [], []
    used = [False] * len(entries)
    for f in findings:
        hit = False
        for i, entry in enumerate(entries):
            if _matches(entry, f):
                used[i] = True
                hit = True
                break
        (grandfathered if hit else violations).append(f)
    stale = [e for i, e in enumerate(entries) if not used[i]]
    return violations, grandfathered, stale


def emit_baseline(findings: list[Finding]) -> dict:
    """Scaffold a baseline document from current findings (reasons must be
    filled in by hand — an unjustified entry defeats the point)."""
    seen = set()
    entries = []
    for f in findings:
        key = (f.path, f.code, f.symbol)
        if key in seen:
            continue
        seen.add(key)
        entries.append({
            "file": f.path, "code": f.code, "symbol": f.symbol,
            "reason": "TODO: justify",
        })
    return {"entries": entries}
