"""apisurface: the committed snapshot of the cross-process contract surface.

Everything apilint (docs/raylint.md, RL10xx) checks call sites *against* —
actor classes and their remote-callable method signatures, `@remote`
functions, the duck-typed protocol rosters and who implements them, the GCS
verb table, and the `_DEFS` flag registry — is ALSO the project's de-facto
public API: it is what a peer process two releases older, an operator
script, or a dashboard actually talks to. This module snapshots that
surface deterministically to `API_SURFACE.json` at the repo root (plus the
generated `docs/flags.md`), and a tier-1 test diffs the live tree against
the committed copy:

- unintentional drift (a renamed remote method, a signature change, a flag
  deleted under an operator) fails CI with a readable diff;
- intentional drift is one command — `python -m ray_tpu.devtools.apisurface
  --write` — and the regenerated snapshot is reviewed in the PR like a
  lockfile.

The snapshot is built from the same AST registry apilint uses (no imports
of the scanned modules, no runtime state, keys sorted), so regeneration is
byte-deterministic for a given tree.

CLI:
    python -m ray_tpu.devtools.apisurface --check      # diff live vs committed
    python -m ray_tpu.devtools.apisurface --write      # regenerate both files
    python -m ray_tpu.devtools.apisurface --flags-md   # regenerate docs/flags.md
"""

from __future__ import annotations

import json
import os
import re
import sys
from typing import Dict, List, Optional

from ray_tpu.devtools.raylint import apilint
from ray_tpu.devtools.raylint.core import _load_context, iter_python_files

SURFACE_FILE = "API_SURFACE.json"
FLAGS_MD = os.path.join("docs", "flags.md")

_SECTION_RE = re.compile(r"#\s*---\s*(.+?)\s*---")


def repo_root() -> str:
    import ray_tpu

    return os.path.dirname(os.path.dirname(os.path.abspath(ray_tpu.__file__)))


def _build_registry(pkg_dir: str) -> apilint.ApiRegistry:
    ctxs = []
    for abspath in iter_python_files([pkg_dir]):
        ctx, err = _load_context(abspath)
        if err is None:
            ctxs.append(ctx)
    return apilint.build_registry(ctxs)


def _flag_sections(reg: apilint.ApiRegistry) -> Dict[str, str]:
    """flag name -> the `# --- section ---` comment above it in the defining
    file ("" when none)."""
    out: Dict[str, str] = {}
    by_file: Dict[str, List[apilint.FlagDef]] = {}
    for f in reg.flags.values():
        by_file.setdefault(f.relpath, []).append(f)
    for relpath, flags in by_file.items():
        path = os.path.join(repo_root(), relpath)
        try:
            with open(path, encoding="utf-8") as fh:
                lines = fh.readlines()
        except OSError:
            continue
        section_at: Dict[int, str] = {}
        current = ""
        for i, line in enumerate(lines, start=1):
            m = _SECTION_RE.search(line)
            if m:
                current = m.group(1)
            section_at[i] = current
        for f in flags:
            out[f.name] = section_at.get(f.lineno, "")
    return out


def build_surface(pkg_dir: Optional[str] = None) -> dict:
    """The deterministic cross-process contract snapshot."""
    if pkg_dir is None:
        import ray_tpu

        pkg_dir = os.path.dirname(os.path.abspath(ray_tpu.__file__))
    reg = _build_registry(pkg_dir)

    actor_classes: Dict[str, dict] = {}
    for info in reg.actor_classes():
        methods, closed = reg.resolved_methods(info)
        public = {
            name: sigs[0].render()
            for name, sigs in methods.items()
            if not name.startswith("_") or name == "__call__"
        }
        key = info.name
        if key in actor_classes:  # same class name in two files: qualify
            key = f"{info.name}@{info.relpath}"
        actor_classes[key] = {
            "file": info.relpath,
            "via": info.actor_via,
            "bases_resolved": closed,
            "methods": dict(sorted(public.items())),
        }

    protocols: Dict[str, dict] = {}
    for spec in apilint.PROTOCOL_TABLE:
        implementors = []
        for info in reg.actor_classes():
            methods, _ = reg.resolved_methods(info)
            if any(a in methods for a in spec.anchors):
                implementors.append(info.name)
        protocols[spec.protocol] = {
            "members": {
                m: {"npos": npos, "kwnames": list(kw)}
                for m, (npos, kw) in spec.members
            },
            "anchors": list(spec.anchors),
            "implementors": sorted(set(implementors)),
        }

    gcs_verbs = {
        verb: {
            "handler": f"{v.class_name}.rpc_{verb}",
            "file": v.relpath,
            "sig": v.sig.render(),
        }
        for verb, v in reg.gcs_verbs.items()
    }

    sections = _flag_sections(reg)
    flags = {
        name: {
            "type": f.type_name,
            "default": f.default_src,
            "doc": f.doc,
            "section": sections.get(name, ""),
        }
        for name, f in reg.flags.items()
    }

    remote_functions = {
        name: sorted(s.render() for s in sigs)
        for name, sigs in reg.remote_functions.items()
    }

    return {
        "actor_classes": dict(sorted(actor_classes.items())),
        "remote_functions": dict(sorted(remote_functions.items())),
        "protocols": dict(sorted(protocols.items())),
        "gcs_verbs": dict(sorted(gcs_verbs.items())),
        "flags": dict(sorted(flags.items())),
    }


def render_surface(surface: dict) -> str:
    return json.dumps(surface, indent=2, sort_keys=True) + "\n"


def render_flags_md(surface: dict) -> str:
    """docs/flags.md, grouped by the `# --- section ---` comments in
    `_private/config.py`. Generated — edit _DEFS, then run
    `python -m ray_tpu.devtools.apisurface --flags-md`."""
    lines = [
        "# Configuration flags",
        "",
        "<!-- GENERATED FILE — do not edit by hand. Regenerate with:",
        "     python -m ray_tpu.devtools.apisurface --flags-md",
        "     (drift-gated by tests/test_apisurface.py) -->",
        "",
        "Every flag lives in `ray_tpu/_private/config.py` `_DEFS` and is",
        "overridable with the environment variable `RAY_TPU_<NAME>`",
        "(upper-cased). Reads of names not in this table raise `KeyError`",
        "with a did-you-mean suggestion; `raylint --family api` (RL1004)",
        "catches typo'd and dead flags statically (docs/raylint.md).",
        "",
    ]
    by_section: Dict[str, List[str]] = {}
    for name, f in surface["flags"].items():
        by_section.setdefault(f["section"] or "other", []).append(name)
    for section in sorted(by_section):
        lines.append(f"## {section}")
        lines.append("")
        lines.append("| flag | type | default | purpose |")
        lines.append("|---|---|---|---|")
        for name in sorted(by_section[section]):
            f = surface["flags"][name]
            doc = f["doc"].replace("|", "\\|")
            default = f"`{f['default']}`".replace("|", "\\|")
            lines.append(f"| `{name}` | {f['type']} | {default} | {doc} |")
        lines.append("")
    return "\n".join(lines)


def diff_surface(committed: dict, live: dict) -> List[str]:
    """Readable per-entry diff (empty when identical)."""
    out: List[str] = []

    def walk(path: str, a, b):
        if isinstance(a, dict) and isinstance(b, dict):
            for k in sorted(set(a) | set(b)):
                kp = f"{path}.{k}" if path else str(k)
                if k not in b:
                    out.append(f"- {kp}: removed from live tree "
                               f"(committed: {json.dumps(a[k], sort_keys=True)[:120]})")
                elif k not in a:
                    out.append(f"+ {kp}: new in live tree "
                               f"({json.dumps(b[k], sort_keys=True)[:120]})")
                else:
                    walk(kp, a[k], b[k])
        elif a != b:
            out.append(
                f"~ {path}: {json.dumps(a, sort_keys=True)[:120]} -> "
                f"{json.dumps(b, sort_keys=True)[:120]}"
            )

    walk("", committed, live)
    return out


def check(root: Optional[str] = None) -> List[str]:
    """-> list of drift lines (surface + flags.md); empty when in sync."""
    root = root or repo_root()
    live = build_surface()
    problems: List[str] = []
    surface_path = os.path.join(root, SURFACE_FILE)
    try:
        with open(surface_path, encoding="utf-8") as fh:
            committed = json.load(fh)
    except (OSError, ValueError):
        problems.append(f"! {SURFACE_FILE} missing or unreadable at {root}")
        committed = {}
    problems.extend(diff_surface(committed, live))
    md_path = os.path.join(root, FLAGS_MD)
    want_md = render_flags_md(live)
    try:
        with open(md_path, encoding="utf-8") as fh:
            have_md = fh.read()
    except OSError:
        have_md = ""
    if have_md != want_md:
        problems.append(f"! {FLAGS_MD} is stale — regenerate with "
                        "`python -m ray_tpu.devtools.apisurface --flags-md`")
    return problems


def write(root: Optional[str] = None, flags_only: bool = False) -> List[str]:
    root = root or repo_root()
    live = build_surface()
    written = []
    if not flags_only:
        path = os.path.join(root, SURFACE_FILE)
        with open(path, "w", encoding="utf-8") as fh:
            fh.write(render_surface(live))
        written.append(path)
    path = os.path.join(root, FLAGS_MD)
    with open(path, "w", encoding="utf-8") as fh:
        fh.write(render_flags_md(live))
    written.append(path)
    return written


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv == ["--check"] or not argv:
        problems = check()
        if problems:
            print("API surface drift (regenerate with "
                  "`python -m ray_tpu.devtools.apisurface --write` if "
                  "intentional):")
            for p in problems:
                print(" ", p)
            return 1
        print("API surface in sync")
        return 0
    if argv == ["--write"]:
        for p in write():
            print("wrote", p)
        return 0
    if argv == ["--flags-md"]:
        for p in write(flags_only=True):
            print("wrote", p)
        return 0
    print("usage: python -m ray_tpu.devtools.apisurface "
          "[--check|--write|--flags-md]", file=sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main())
