"""distsan: runtime distributed-contract sanitizer.

The runtime counterpart of `raylint`'s RL9xx static family (distlint), the
way leaksan backs the RL8xx checkers: distlint PROVES at parse time that no
metric mutation or control-plane RPC sits on a hot/finalizer path it can
see; distsan CATCHES the ones it can't — mutations reached through
callbacks, dynamic dispatch, or third-party code — at the moment they
execute.

The model is a thread-local stack of context tags:

- ``hot_path(label)``   — a scheduler/decode/dispatch loop: a blocking GCS
  round-trip here gates every iteration on the control plane.
- ``finalizer(label)``  — a ``__del__``/weakref finalizer: GC timing decides
  when (and on which thread) the control plane would be dialed.
- ``report_path(label)`` — a stats()/report() export: control-plane traffic
  here is the contract. The INNERMOST tag decides, so a report-path flush
  invoked from inside a tagged hot loop is still fine.

Instrumented sites (``util.metrics`` mutators, ``worker.gcs_call``) call
``note_metric_mutation`` / ``note_gcs_call``; when the innermost tag is a
hot path or finalizer, a violation record is appended — never raised, so
production behavior is unchanged even when enabled. The pytest guard
(tests/conftest.py ``distsan_guard``) fails any test in a wired suite that
recorded violations.

Zero overhead unless enabled: every note/tag entry starts with one
``enabled()`` check (an env read / cached bool); nothing is allocated and
no lock is taken when the sanitizer is off. Enable with
``RAY_TPU_DISTSAN=1`` in the environment, or programmatically with
``enable()`` (what the pytest fixture does).
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional

_lock = threading.Lock()
_enabled_override: Optional[bool] = None
_violations: List[Dict[str, str]] = []
_tls = threading.local()


def enabled() -> bool:
    if _enabled_override is not None:
        return _enabled_override
    return os.environ.get("RAY_TPU_DISTSAN", "") == "1"


def enable() -> None:
    global _enabled_override
    _enabled_override = True


def disable() -> None:
    global _enabled_override
    _enabled_override = False


def reset() -> None:
    """Drop recorded violations and this thread's tag stack (test isolation)."""
    with _lock:
        _violations.clear()
    _tls.stack = []


def _stack() -> list:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


class _Tag:
    """Context-manager tag. Pushes only when the sanitizer is enabled at
    entry (and balances its own push even if disable() races the body)."""

    __slots__ = ("kind", "label", "_pushed")

    def __init__(self, kind: str, label: str):
        self.kind = kind
        self.label = label
        self._pushed = False

    def __enter__(self):
        self._pushed = enabled()
        if self._pushed:
            _stack().append((self.kind, self.label))
        return self

    def __exit__(self, *exc):
        if self._pushed:
            stack = _stack()
            if stack:
                stack.pop()
        return False


def hot_path(label: str = "") -> _Tag:
    """Tag the dynamic extent of a scheduler/decode/dispatch loop."""
    return _Tag("hot", label)


def report_path(label: str = "") -> _Tag:
    """Tag a stats()/report() export — control-plane traffic is the contract."""
    return _Tag("report", label)


def finalizer(label: str = "") -> _Tag:
    """Tag a __del__ / weakref-finalize body."""
    return _Tag("finalizer", label)


def _innermost() -> Optional[tuple]:
    stack = getattr(_tls, "stack", None)
    return stack[-1] if stack else None


def _record(kind: str, detail: str, tag: tuple) -> None:
    entry = {
        "kind": kind,
        "detail": detail,
        "context": tag[0],
        "label": tag[1],
        "thread": threading.current_thread().name,
    }
    with _lock:
        _violations.append(entry)


def note_gcs_call(verb: str) -> None:
    """Called by worker.gcs_call at dispatch time. A control-plane round-trip
    inside a tagged hot loop or finalizer is a violation; inside a report
    path (innermost) it is the contract."""
    if not enabled():
        return
    tag = _innermost()
    if tag is not None and tag[0] in ("hot", "finalizer"):
        _record("gcs_call", verb, tag)


def note_metric_mutation(name: str) -> None:
    """Called by Counter.inc / Gauge.set / Histogram.observe. Every mutation
    may flush, and a flush is a blocking GCS RPC — so a mutation inside a
    tagged hot loop or finalizer is a violation even when THIS one happens
    not to flush."""
    if not enabled():
        return
    tag = _innermost()
    if tag is not None and tag[0] in ("hot", "finalizer"):
        _record("metric_mutation", name, tag)


def violations() -> List[Dict[str, str]]:
    """Snapshot of the recorded violations (copies; safe to mutate)."""
    with _lock:
        return [dict(v) for v in _violations]
