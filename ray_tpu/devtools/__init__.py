"""Developer tooling that ships with the framework (linters, analyzers)."""
