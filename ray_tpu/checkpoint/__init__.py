"""ray_tpu.checkpoint: sharded, asynchronous, atomically-committed checkpoints
for JAX pytrees.

The three pieces (docs/checkpoint.md):

- **Sharded format** (`_format`): each process writes only the array slices it
  owns (per-leaf files keyed by global mesh-axis offsets) plus a per-process
  spec; `MANIFEST.json` is written last, atomically — a directory without a
  manifest is garbage by definition.
- **AsyncCheckpointWriter** (`_writer`): the step loop pays one batched
  device->host snapshot; persistence + commit run on a bounded background
  queue (flags ``train_ckpt_async`` / ``train_ckpt_inflight``).
- **Resharding restore** (`_restore`): the global tree is reassembled from
  manifest offsets and redistributed onto the *current* mesh, so an elastic
  restart at a different world size resumes from the last committed save.

Quick use::

    from ray_tpu import checkpoint as ckpt

    ckpt.save(path, {"params": params, "step": step})       # sync, committed
    tree = ckpt.restore(path)                               # host numpy tree
    tree = ckpt.restore(path, shardings=my_shardings)       # onto current mesh

    # inside a JaxTrainer loop: async sharded save via report()
    train.report(metrics, checkpoint=ckpt.ShardedState(state))
"""

from __future__ import annotations

from typing import Optional

from ray_tpu.checkpoint._format import (
    CommitTimeout,
    MANIFEST_NAME,
    SENTINEL_NAME,
    commit,
    is_committed,
    is_partial,
    is_sharded,
    load_manifest,
    write_process_shards,
)
from ray_tpu.checkpoint._restore import restore, restore_leaf
from ray_tpu.checkpoint._writer import AsyncCheckpointWriter


class ShardedState:
    """Marks a pytree for the sharded-save path through ``train.report``.

    ``train.report(metrics, checkpoint=ShardedState(tree))`` makes every rank
    persist only its owned shards of ``tree`` (asynchronously when
    ``train_ckpt_async`` is on) into the report's checkpoint directory; rank 0
    commits the manifest once all ranks' shards are durable.
    """

    __slots__ = ("tree",)

    def __init__(self, tree):
        self.tree = tree

    def __repr__(self):
        return "ShardedState(<pytree>)"


def save(path: str, tree, *, process_index: Optional[int] = None,
         process_count: Optional[int] = None,
         commit_timeout_s: Optional[float] = None) -> str:
    """Synchronous sharded save. Single-process callers get a committed
    checkpoint in one call; simulated/multi-process callers write their shards
    and the LAST committer (process 0) runs `commit` after all specs exist.
    Returns ``path``."""
    write_process_shards(
        path, tree, process_index=process_index, process_count=process_count
    )
    if process_index in (None, 0):
        commit(
            path,
            process_count=1 if process_count is None else process_count,
            timeout_s=commit_timeout_s,
        )
    return path


__all__ = [
    "AsyncCheckpointWriter",
    "CommitTimeout",
    "MANIFEST_NAME",
    "SENTINEL_NAME",
    "ShardedState",
    "commit",
    "is_committed",
    "is_partial",
    "is_sharded",
    "load_manifest",
    "restore",
    "restore_leaf",
    "save",
    "write_process_shards",
]
