"""Resharding restore: assemble global arrays from slice files, redistribute
onto the *current* mesh.

The manifest records global offsets per slice file, so restore never depends on
the save-time process count or mesh shape (Gemini's shard-level
placement-aware recovery): an elastic restart at M hosts reads an N-host
checkpoint by fetching, per device, exactly the file regions that overlap the
device's slice of the NEW sharding. Slice files are memory-mapped, so a
partial overlap reads only the pages it touches.
"""

from __future__ import annotations

import os
import time
from typing import Any, Optional

import numpy as np

from ray_tpu.checkpoint._format import _decode_tree, load_manifest


def _region_slices(index, shape):
    """Manifest/device index -> concrete per-dim (start, stop)."""
    out = []
    for dim in range(len(shape)):
        if index is not None and dim < len(index):
            sl = index[dim]
            if isinstance(sl, slice):
                start = 0 if sl.start is None else int(sl.start)
                stop = shape[dim] if sl.stop is None else int(sl.stop)
            else:
                start, stop = int(sl[0]), int(sl[1])
        else:
            start, stop = 0, shape[dim]
        out.append((start, stop))
    return out


class _LeafReader:
    """Reads arbitrary regions of one leaf from its slice files (mmap-backed,
    opened lazily, shared across all device callbacks of the restore)."""

    def __init__(self, path: str, key: str, spec: dict):
        self._path = path
        self._key = key
        self.shape = tuple(int(d) for d in spec["shape"])
        self.dtype = np.dtype(spec["dtype"])
        self._shards = spec["shards"]
        self._open: dict[str, np.ndarray] = {}

    def _file(self, name: str) -> np.ndarray:
        arr = self._open.get(name)
        if arr is None:
            arr = np.load(os.path.join(self._path, name), mmap_mode="r",
                          allow_pickle=False)
            if arr.dtype != self.dtype and arr.dtype.kind == "V" \
                    and arr.dtype.itemsize == self.dtype.itemsize:
                # Extension dtypes (bfloat16, fp8) hit the .npy format as raw
                # void bytes; reinterpret against the manifest's dtype.
                arr = arr.view(self.dtype)
            self._open[name] = arr
        return arr

    def read(self, index) -> np.ndarray:
        """Assemble the region ``index`` (tuple of slices, or None for the
        whole array) from every overlapping slice file."""
        region = _region_slices(index, self.shape)
        out_shape = tuple(b - a for a, b in region)
        if not self.shape:  # 0-d leaf: exactly one scalar shard
            return np.array(self._file(self._shards[0]["file"]))
        out = np.empty(out_shape, self.dtype)
        covered = 0
        for shard in self._shards:
            s_region = _region_slices(shard["index"], self.shape)
            src_sel, dst_sel, size = [], [], 1
            for (ra, rb), (sa, sb) in zip(region, s_region):
                lo, hi = max(ra, sa), min(rb, sb)
                if lo >= hi:
                    size = 0
                    break
                src_sel.append(slice(lo - sa, hi - sa))
                dst_sel.append(slice(lo - ra, hi - ra))
                size *= hi - lo
            if not size:
                continue
            out[tuple(dst_sel)] = self._file(shard["file"])[tuple(src_sel)]
            covered += size
        want = int(np.prod(out_shape)) if out_shape else 1
        if covered != want:
            raise ValueError(
                f"checkpoint leaf {self._key!r}: region {region} only "
                f"covered {covered}/{want} elements — slice files missing "
                f"or manifest corrupt"
            )
        return out


def _sharding_for(key: str, shardings) -> Optional[Any]:
    """Resolve the target sharding for a leaf: a single Sharding applies to
    every leaf; a dict keys by manifest leaf key ("params/dense/kernel")."""
    if shardings is None:
        return None
    if isinstance(shardings, dict):
        return shardings.get(key)
    return shardings


def restore(path: str, *, shardings=None, mesh=None):
    """Load a committed sharded checkpoint.

    - ``restore(path)`` -> host pytree (numpy leaves) with the saved structure.
    - ``restore(path, shardings=...)`` -> jax arrays distributed per the given
      shardings (one ``jax.sharding.Sharding`` for all leaves, or a dict of
      manifest leaf key -> Sharding). Placement-aware: each device's slice of
      the NEW sharding is read directly from the overlapping regions of the
      OLD shard files via ``jax.make_array_from_callback`` — no full-array
      materialization for sharded targets.
    - ``restore(path, mesh=...)`` -> jax arrays replicated over ``mesh``.

    Raises FileNotFoundError when the directory was never committed.
    """
    t0 = time.perf_counter()
    manifest = load_manifest(path)
    if shardings is None and mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec

        shardings = NamedSharding(mesh, PartitionSpec())
    readers = {
        key: _LeafReader(path, key, spec)
        for key, spec in manifest["leaves"].items()
    }

    if shardings is None:
        def leaf_fn(key: str):
            return readers[key].read(None)
    else:
        import jax

        def leaf_fn(key: str):
            reader = readers[key]
            sharding = _sharding_for(key, shardings)
            if sharding is None:
                return reader.read(None)
            return jax.make_array_from_callback(
                reader.shape, sharding, reader.read
            )

    if manifest.get("tree") is None:
        # Flat fallback: a save of a bare leaf list keyed by position.
        out = {key: leaf_fn(key) for key in sorted(readers)}
    else:
        out = _decode_tree(manifest["tree"], leaf_fn)
    # Compute-plane registry: a restore builds fresh arrays/programs by
    # design, so it records as a SPAN (invocation + wall time), never a
    # compile — it must not read as a retrace storm.
    from ray_tpu.util import xprof

    xprof.registry().note_span(
        "checkpoint", ("restore",), time.perf_counter() - t0
    )
    return out


def restore_leaf(path: str, key: str, *, sharding=None):
    """Load a single leaf by manifest key (serve warm-start helper)."""
    manifest = load_manifest(path)
    spec = manifest["leaves"].get(key)
    if spec is None:
        raise KeyError(
            f"{key!r} not in checkpoint {path} "
            f"(leaves: {sorted(manifest['leaves'])[:8]}...)"
        )
    reader = _LeafReader(path, key, spec)
    if sharding is None:
        return reader.read(None)
    import jax

    return jax.make_array_from_callback(reader.shape, sharding, reader.read)
