"""AsyncCheckpointWriter: decouple the device->host snapshot from persistence.

CheckFreq's split (Mohan et al., FAST'21): the step loop pays only for a
snapshot — ONE batched ``jax.device_get`` of this process's owned shards at the
step boundary — while serialization, fsync, and the manifest commit run on a
background thread. A bounded in-flight queue (``train_ckpt_inflight``)
backpressures the step loop instead of letting host memory grow with
unpersisted snapshots.

Commit coordination is filesystem-based and non-blocking: every process's
background writer persists shards + its ``process_<i>.json`` spec; the
committing process (rank 0) then waits — on its WRITER thread, not the step
loop — for all specs before writing ``MANIFEST.json``. A rank that dies
mid-save simply never produces its spec, the commit times out, and the
directory stays manifest-less (garbage by definition).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Optional

from ray_tpu.checkpoint import _format
from ray_tpu.util import tracing

_metrics = None
_metrics_lock = threading.Lock()


def _get_metrics():
    """Lazy singletons: Counter/Gauge/Histogram no-op their flush outside a
    cluster, so the writer works in plain scripts and benches too."""
    global _metrics
    if _metrics is None:
        with _metrics_lock:
            if _metrics is None:
                from ray_tpu.util.metrics import Counter, Gauge, Histogram

                _metrics = {
                    "snapshot_s": Histogram(
                        "ckpt_snapshot_seconds",
                        "step-loop blocked time per save (device->host "
                        "snapshot + enqueue)",
                        boundaries=[0.001, 0.01, 0.1, 1, 10],
                    ),
                    "write_s": Histogram(
                        "ckpt_write_seconds",
                        "background shard write + spec persist time",
                        boundaries=[0.01, 0.1, 1, 10, 100],
                    ),
                    "bytes": Counter(
                        "ckpt_saved_bytes", "shard bytes persisted"
                    ),
                    "commits": Counter(
                        "ckpt_commits", "manifests committed"
                    ),
                    "failures": Counter(
                        "ckpt_save_failures", "background save jobs that errored"
                    ),
                    "queue_depth": Gauge(
                        "ckpt_queue_depth", "in-flight async save jobs"
                    ),
                }
    return _metrics


class AsyncCheckpointWriter:
    """Background sharded-checkpoint writer with a bounded in-flight queue.

    ``save()`` blocks only for the snapshot (and, when the queue is full, for
    backpressure); ``wait_until_finished()`` is the barrier before shutdown or
    before trusting the latest directory to be committed.
    """

    def __init__(self, *, inflight: Optional[int] = None,
                 commit_timeout_s: Optional[float] = None):
        from ray_tpu._private.config import CONFIG

        if inflight is None:
            inflight = CONFIG.train_ckpt_inflight
        if commit_timeout_s is None:
            commit_timeout_s = CONFIG.train_ckpt_commit_timeout_s
        self._commit_timeout_s = commit_timeout_s
        self._queue: "queue.Queue[Optional[dict]]" = queue.Queue(
            maxsize=max(1, inflight)
        )
        self._idle = threading.Event()
        self._idle.set()
        self._lock = threading.Lock()
        self._pending = 0
        self._pending_bytes = 0
        self._thread: Optional[threading.Thread] = None
        self.error: Optional[BaseException] = None
        # Memory-ledger owner (docs/observability.md "compute plane"): the
        # host bytes of snapshots taken but not yet persisted — exactly the
        # memory the bounded in-flight queue exists to cap. Weakref'd so an
        # abandoned writer stays collectable.
        import weakref

        from ray_tpu.util import xprof

        self._ledger_name = f"ckpt_writer-{id(self):x}"
        _self_ref = weakref.ref(self)

        def _ledger_row():
            w = _self_ref()
            if w is None:
                return {}
            with w._lock:
                return {"bytes": 0, "host_bytes": w._pending_bytes,
                        "pending_jobs": w._pending}

        xprof.register_memory_owner(self._ledger_name, _ledger_row)

    # ------------------------------------------------------------------ save

    def save(self, path: str, tree, *, process_index: Optional[int] = None,
             process_count: Optional[int] = None, commit: Optional[bool] = None):
        """Snapshot ``tree`` (one batched device_get) and enqueue persistence.

        ``commit=None`` commits iff this process is the committer (rank 0 /
        single-process). Raises any error a PREVIOUS background job hit, so
        failures surface at the next step boundary instead of silently.
        """
        if self.error is not None:
            raise RuntimeError(
                f"previous async checkpoint save failed: {self.error!r}"
            ) from self.error
        t0 = time.perf_counter()
        encoded, plan = _format.snapshot(
            tree, process_index=process_index, process_count=process_count
        )
        job = {
            "path": path,
            "encoded": encoded,
            "plan": plan,
            "process_index": process_index,
            "commit": (process_index in (None, 0)) if commit is None else commit,
            "process_count": 1 if process_count is None else process_count,
        }
        job["bytes"] = sum(
            int(getattr(v, "nbytes", 0) or 0) for v in encoded.values()
        ) if hasattr(encoded, "values") else 0
        with self._lock:
            self._pending += 1
            self._pending_bytes += job["bytes"]
            self._idle.clear()
        from ray_tpu.devtools import leaksan as _leaksan

        _leaksan.track("ckpt_pending", token=f"writer@{id(self):x}")
        self._ensure_thread()
        self._queue.put(job)  # blocks when the in-flight budget is exhausted
        m = _get_metrics()
        m["snapshot_s"].observe(time.perf_counter() - t0)
        m["queue_depth"].set(float(self._pending))

    def save_sync(self, path: str, tree, *, process_index: Optional[int] = None,
                  process_count: Optional[int] = None,
                  commit: Optional[bool] = None):
        """The synchronous path (``train_ckpt_async=0``): snapshot, persist,
        and (when committer) commit inline — the step loop pays for all of it."""
        do_commit = (process_index in (None, 0)) if commit is None else commit
        t0 = time.perf_counter()
        spec = _format.write_process_shards(
            path, tree, process_index=process_index, process_count=process_count
        )
        m = _get_metrics()
        m["bytes"].inc(float(spec.get("bytes", 0)))
        if do_commit:
            _format.commit(
                path,
                process_count=1 if process_count is None else process_count,
                timeout_s=self._commit_timeout_s,
            )
            m["commits"].inc()
        m["write_s"].observe(time.perf_counter() - t0)

    # ------------------------------------------------------------ background

    def _ensure_thread(self):
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="ckpt-writer"
            )
            self._thread.start()

    def _loop(self):
        while True:
            job = self._queue.get()
            if job is None:
                return
            try:
                self._run_job(job)
            except BaseException as e:  # surfaced on the next save()/wait
                self.error = e
                _get_metrics()["failures"].inc()
            finally:
                with self._lock:
                    self._pending -= 1
                    self._pending_bytes -= job.get("bytes", 0)
                    if self._pending == 0:
                        self._pending_bytes = 0  # drift-proof at idle
                        self._idle.set()
                from ray_tpu.devtools import leaksan as _leaksan

                _leaksan.untrack("ckpt_pending", token=f"writer@{id(self):x}")
                _get_metrics()["queue_depth"].set(float(self._pending))

    def _run_job(self, job: dict):
        t0 = time.perf_counter()
        with tracing.trace(f"ckpt.write:{job['path']}"):
            spec = _format.write_snapshot(
                job["path"], job["encoded"], job["plan"],
                process_index=job["process_index"],
            )
            m = _get_metrics()
            m["bytes"].inc(float(spec.get("bytes", 0)))
            if job["commit"]:
                _format.commit(
                    job["path"],
                    process_count=job["process_count"],
                    timeout_s=self._commit_timeout_s,
                )
                m["commits"].inc()
            m["write_s"].observe(time.perf_counter() - t0)

    # --------------------------------------------------------------- barrier

    def wait_until_finished(self, timeout: Optional[float] = None) -> bool:
        """Block until every enqueued save has been persisted (and committed,
        for committer jobs). Returns False on timeout. Raises if any
        background job failed."""
        done = self._idle.wait(timeout)
        if self.error is not None:
            raise RuntimeError(
                f"async checkpoint save failed: {self.error!r}"
            ) from self.error
        return done

    def shutdown(self, wait: bool = True):
        if wait:
            try:
                self.wait_until_finished()
            except RuntimeError:
                pass  # error already recorded on self.error
        if self._thread is not None and self._thread.is_alive():
            self._queue.put(None)
            self._thread.join(timeout=5.0)
        self._thread = None
        from ray_tpu.util import xprof

        xprof.unregister_memory_owner(self._ledger_name)
