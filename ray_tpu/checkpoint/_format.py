"""Sharded checkpoint format: per-process slice files + atomic manifest commit.

Layout of a checkpoint directory::

    checkpoint_000003/
      .ray_tpu_sharded                 # sentinel: a sharded save targets this dir
      params.dense.kernel--0_64.0_32.npy   # one slice file per distinct shard
      process_0.json                   # per-process spec (fsynced before manifest)
      process_1.json
      MANIFEST.json                    # written LAST, atomically — the commit record

Commit protocol (CheckFreq/Gemini shape): every writing process persists only
its owned slices plus a `process_<i>.json` spec; the committer merges all specs,
verifies every leaf is fully covered, and writes `MANIFEST.json` via
tmp-file -> fsync -> rename -> directory fsync. **A directory without a
manifest is garbage by definition**: restore refuses it and the train
controller's orphan cleanup reaps it.

Shard ownership: each distinct array slice (mesh-axis offsets, replicas
deduped) has exactly one owner. On a real multi-host mesh the owner is the
process of the first device holding the slice; a *simulated* process grid
(tests, single-host elasticity drills) passes explicit ``process_index``/
``process_count`` and slices are dealt round-robin. Either way an M-process
restore never depends on the N-process save layout — the manifest records
global offsets, not ranks.
"""

from __future__ import annotations

import json
import os
import tempfile
import time

import numpy as np

FORMAT_NAME = "ray_tpu.sharded_ckpt"
FORMAT_VERSION = 1
MANIFEST_NAME = "MANIFEST.json"
SENTINEL_NAME = ".ray_tpu_sharded"
_PROCESS_SPEC_FMT = "process_{}.json"


# --------------------------------------------------------------------- pytree

def _unwrap(node):
    """Strip flax Partitioned/LogicallyPartitioned boxes: checkpoints hold raw
    arrays; partitioning is re-derived from the RESTORE-side shardings (the
    save-time spec is meaningless after an elastic resize anyway)."""
    if hasattr(node, "unbox") and callable(node.unbox):
        return node.unbox()
    return node


def _encode_tree(tree):
    """Structure-only encoding of a pytree of dicts/lists/tuples; leaves become
    {"leaf": key}. Keys double as slice-file stems, so they use "/" separators
    here and "." in filenames."""

    def rec(node, path):
        node = _unwrap(node)
        if isinstance(node, dict):
            return {"kind": "dict",
                    "items": {str(k): rec(v, path + (str(k),))
                              for k, v in sorted(node.items(), key=lambda kv: str(kv[0]))}}
        if isinstance(node, (list, tuple)):
            return {"kind": "list" if isinstance(node, list) else "tuple",
                    "items": [rec(v, path + (str(i),)) for i, v in enumerate(node)]}
        if node is None:
            return {"kind": "none"}
        return {"kind": "leaf", "key": "/".join(path)}

    return rec(tree, ())


def _decode_tree(enc, leaf_fn):
    if enc["kind"] == "dict":
        return {k: _decode_tree(v, leaf_fn) for k, v in enc["items"].items()}
    if enc["kind"] == "list":
        return [_decode_tree(v, leaf_fn) for v in enc["items"]]
    if enc["kind"] == "tuple":
        return tuple(_decode_tree(v, leaf_fn) for v in enc["items"])
    if enc["kind"] == "none":
        return None
    return leaf_fn(enc["key"])


def _flatten(tree):
    """[(key, leaf)] in the same order _encode_tree assigns keys."""
    out = []

    def rec(node, path):
        node = _unwrap(node)
        if isinstance(node, dict):
            for k, v in sorted(node.items(), key=lambda kv: str(kv[0])):
                rec(v, path + (str(k),))
        elif isinstance(node, (list, tuple)):
            for i, v in enumerate(node):
                rec(v, path + (str(i),))
        elif node is None:
            pass
        else:
            out.append(("/".join(path), node))

    rec(tree, ())
    return out


# --------------------------------------------------------------------- shards

def _is_jax_array(leaf) -> bool:
    return type(leaf).__module__.startswith("jax") and hasattr(leaf, "sharding")


def _norm_index(index, shape) -> list[list[int]]:
    """A device index (tuple of slices) -> [[start, stop], ...] per dim."""
    out = []
    for dim, sl in enumerate(index):
        start = 0 if sl.start is None else int(sl.start)
        stop = shape[dim] if sl.stop is None else int(sl.stop)
        out.append([start, stop])
    # 0-d arrays have an empty index; record nothing.
    return out


def _shard_file_name(key: str, offsets: list[list[int]]) -> str:
    stem = key.replace("/", ".")
    if not offsets:
        return f"{stem}--scalar.npy"
    span = ".".join(f"{a}_{b}" for a, b in offsets)
    return f"{stem}--{span}.npy"


def _distinct_shards(leaf):
    """One (index, device) per distinct slice of a jax array, replicas deduped
    deterministically (lowest device id wins), sorted by offsets."""
    seen: dict[tuple, object] = {}
    for device, index in leaf.sharding.devices_indices_map(leaf.shape).items():
        norm = tuple(tuple(p) for p in _norm_index(index, leaf.shape))
        prev = seen.get(norm)
        if prev is None or device.id < prev.id:
            seen[norm] = device
    return sorted(seen.items())


def _owner_of(position: int, device, process_index, process_count) -> int:
    if process_count is None:
        # Real mesh: the slice belongs to the process hosting its first device.
        return getattr(device, "process_index", 0)
    return position % process_count


def plan_snapshot(tree, *, process_index=None, process_count=None):
    """Split a pytree into (encoded_tree, plan) where plan is a list of
    ``{key, dtype, shape, offsets, file, data}`` entries for every shard THIS
    process owns. ``data`` is still device-resident for jax leaves — callers
    batch all of them through ONE jax.device_get at the step boundary
    (see snapshot()); host leaves are copied immediately."""
    if (process_index is None) != (process_count is None):
        raise ValueError("process_index and process_count go together")
    me = 0 if process_index is None else process_index
    encoded = _encode_tree(tree)
    plan = []
    for key, leaf in _flatten(tree):
        if _is_jax_array(leaf):
            addressable = {
                s.device: s for s in leaf.addressable_shards
            }
            for pos, (offsets, device) in enumerate(_distinct_shards(leaf)):
                if _owner_of(pos, device, process_index, process_count) != me:
                    continue
                shard = addressable.get(device)
                if shard is None:
                    # Owned by this (simulated) process but not addressable
                    # here — only possible on a real mesh with simulated
                    # process args, which plan_snapshot rejects implicitly:
                    # the caller must own only addressable slices.
                    raise ValueError(
                        f"process {me} owns shard {offsets} of {key!r} but "
                        f"its device {device} is not addressable"
                    )
                offs = [list(p) for p in offsets]
                plan.append({
                    "key": key,
                    "dtype": str(np.dtype(leaf.dtype)),
                    "shape": [int(d) for d in leaf.shape],
                    "offsets": offs,
                    "file": _shard_file_name(key, offs),
                    "data": shard.data,  # device array; fetched in one batch
                    "device": True,
                })
        else:
            # Host leaf (numpy array / python scalar): one full shard, owned
            # by process 0 so exactly one writer persists it.
            if me != 0:
                continue
            arr = np.asarray(leaf)
            offs = [[0, int(d)] for d in arr.shape]
            plan.append({
                "key": key,
                "dtype": str(arr.dtype),
                "shape": [int(d) for d in arr.shape],
                "offsets": offs,
                "file": _shard_file_name(key, offs),
                "data": arr.copy(),
                "device": False,
            })
    return encoded, plan


def snapshot(tree, *, process_index=None, process_count=None):
    """Device->host snapshot of this process's owned shards: ONE batched
    jax.device_get for every device-resident slice (the step-boundary cost of
    an async save), host leaves copied. Returns (encoded_tree, plan) with all
    ``data`` as numpy."""
    encoded, plan = plan_snapshot(
        tree, process_index=process_index, process_count=process_count
    )
    device_entries = [e for e in plan if e["device"]]
    if device_entries:
        import jax

        fetched = jax.device_get([e["data"] for e in device_entries])
        for entry, host in zip(device_entries, fetched):
            entry["data"] = np.asarray(host)
    return encoded, plan


# ---------------------------------------------------------------------- write

def _fsync_dir(path: str):
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _write_atomic(path: str, payload: bytes):
    """tmp-file -> fsync -> rename: the file either exists complete or not at all."""
    d = os.path.dirname(path)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".tmp_", suffix=".part")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    _fsync_dir(d)


def write_snapshot(path: str, encoded_tree, plan, *, process_index=None) -> dict:
    """Persist one process's snapshot: slice files first (each durable before
    the spec references it), then the process spec. Returns the spec dict."""
    os.makedirs(path, exist_ok=True)
    sentinel = os.path.join(path, SENTINEL_NAME)
    if not os.path.exists(sentinel):
        _write_atomic(sentinel, b"")
    total_bytes = 0
    leaves: dict[str, dict] = {}
    for entry in plan:
        arr = entry["data"]
        with open(os.path.join(path, entry["file"] + ".part"), "wb") as f:
            np.save(f, arr, allow_pickle=False)
            f.flush()
            os.fsync(f.fileno())
        os.replace(os.path.join(path, entry["file"] + ".part"),
                   os.path.join(path, entry["file"]))
        total_bytes += arr.nbytes
        spec = leaves.setdefault(entry["key"], {
            "dtype": entry["dtype"], "shape": entry["shape"], "shards": [],
        })
        spec["shards"].append({"file": entry["file"], "index": entry["offsets"]})
    _fsync_dir(path)
    spec = {
        "process_index": 0 if process_index is None else process_index,
        "tree": encoded_tree,
        "leaves": leaves,
        "bytes": total_bytes,
        "ts": time.time(),
    }
    me = 0 if process_index is None else process_index
    _write_atomic(
        os.path.join(path, _PROCESS_SPEC_FMT.format(me)),
        json.dumps(spec).encode(),
    )
    return spec


def write_process_shards(path: str, tree, *, process_index=None,
                         process_count=None) -> dict:
    """Sync path: snapshot + persist this process's shards (no manifest)."""
    encoded, plan = snapshot(
        tree, process_index=process_index, process_count=process_count
    )
    return write_snapshot(path, encoded, plan, process_index=process_index)


# --------------------------------------------------------------------- commit

class CommitTimeout(TimeoutError):
    """Not every writing process produced its spec before the deadline — the
    directory stays manifest-less (i.e. garbage) by design."""


def commit(path: str, *, process_count: int = 1, timeout_s: float | None = None,
           poll_s: float = 0.05) -> str:
    """Merge all process specs into MANIFEST.json — the atomic commit point.

    Waits (bounded) for every ``process_<i>.json``; verifies each leaf's shards
    tile its full global shape; then writes the manifest last, atomically. Any
    failure before the final rename leaves the directory uncommitted.
    """
    spec_paths = [os.path.join(path, _PROCESS_SPEC_FMT.format(i))
                  for i in range(process_count)]
    deadline = None if timeout_s is None else time.monotonic() + timeout_s
    while True:
        missing = [p for p in spec_paths if not os.path.exists(p)]
        if not missing:
            break
        if deadline is not None and time.monotonic() > deadline:
            raise CommitTimeout(
                f"checkpoint {path}: {len(missing)}/{process_count} process "
                f"spec(s) missing after {timeout_s}s (first: {missing[0]})"
            )
        time.sleep(poll_s)
    specs = []
    for p in spec_paths:
        with open(p, "r") as f:
            specs.append(json.load(f))
    tree = next((s["tree"] for s in specs if s.get("tree") is not None), None)
    leaves: dict[str, dict] = {}
    for s in specs:
        for key, leaf_spec in s["leaves"].items():
            merged = leaves.setdefault(key, {
                "dtype": leaf_spec["dtype"],
                "shape": leaf_spec["shape"],
                "shards": [],
            })
            if (merged["dtype"] != leaf_spec["dtype"]
                    or merged["shape"] != leaf_spec["shape"]):
                raise ValueError(
                    f"checkpoint {path}: leaf {key!r} dtype/shape disagrees "
                    f"across processes"
                )
            merged["shards"].extend(leaf_spec["shards"])
    _verify_coverage(path, leaves)
    manifest = {
        "format": FORMAT_NAME,
        "version": FORMAT_VERSION,
        "process_count": process_count,
        "tree": tree,
        "leaves": leaves,
        "ts": time.time(),
    }
    manifest_path = os.path.join(path, MANIFEST_NAME)
    _write_atomic(manifest_path, json.dumps(manifest).encode())
    return manifest_path


def _verify_coverage(path: str, leaves: dict):
    """Every leaf's shards must tile its global shape exactly (distinct slices,
    union = whole array) — a missing writer can't silently commit."""
    for key, spec in leaves.items():
        total = int(np.prod(spec["shape"])) if spec["shape"] else 1
        covered = 0
        seen = set()
        for shard in spec["shards"]:
            idx = tuple(tuple(p) for p in shard["index"])
            if idx in seen:
                raise ValueError(
                    f"checkpoint {path}: duplicate shard {idx} for {key!r}"
                )
            seen.add(idx)
            size = 1
            for a, b in shard["index"]:
                size *= max(0, b - a)
            covered += size
        if covered != total:
            raise ValueError(
                f"checkpoint {path}: leaf {key!r} covers {covered} of {total} "
                f"elements — a writer's shards are missing; refusing to commit"
            )


# --------------------------------------------------------------------- status

def is_sharded(path: str) -> bool:
    """A sharded save targeted (or completed in) this directory."""
    return (os.path.exists(os.path.join(path, SENTINEL_NAME))
            or os.path.exists(os.path.join(path, MANIFEST_NAME)))


def is_committed(path: str) -> bool:
    return os.path.exists(os.path.join(path, MANIFEST_NAME))


def is_partial(path: str) -> bool:
    """A sharded save started here but never committed — garbage by definition."""
    return is_sharded(path) and not is_committed(path)


def load_manifest(path: str) -> dict:
    manifest_path = os.path.join(path, MANIFEST_NAME)
    if not os.path.exists(manifest_path):
        raise FileNotFoundError(
            f"{path} has no {MANIFEST_NAME}: the checkpoint was never "
            f"committed (partial saves are garbage by definition)"
        )
    with open(manifest_path, "r") as f:
        manifest = json.load(f)
    if manifest.get("format") != FORMAT_NAME:
        raise ValueError(f"{manifest_path}: not a {FORMAT_NAME} manifest")
    return manifest
