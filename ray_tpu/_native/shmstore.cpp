// Native shared-memory object store: one mmap arena per node, many processes.
//
// Design parity: reference plasma store (src/ray/object_manager/plasma/ — a dlmalloc
// arena over mmap/shm with an object index, create/seal lifecycle and LRU eviction of
// releasable objects: plasma_allocator.h:42, eviction_policy.h:159, object_store.h:76).
// Rebuilt small: boundary-tag first-fit allocator with coalescing, open-addressing
// object index, LRU list threaded through the index entries, and a robust
// process-shared mutex so any client of the node can allocate/lookup directly in
// shared memory — no RPC on the hot get/put path.
//
// Built with: g++ -O2 -shared -fPIC shmstore.cpp -o libshmstore.so -lpthread -lrt
// Exposed to Python via ctypes (see shmstore.py).

#include <cerrno>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x5254505553544f31ULL;  // "RTPUSTO1"
constexpr uint32_t kMaxObjects = 1 << 16;
constexpr uint32_t kNumBuckets = 1 << 17;  // 2x entries, open addressing
constexpr uint64_t kAlign = 64;
// Payloads start kPayloadOff into their block (not 8): with the data area
// page-aligned, every payload lands on a 64-byte boundary, which keeps large
// memcpys into objects on the aligned-SIMD fast path and hands deserialized
// arrays aligned memory. The 8-byte block header sits at the block start; the
// gap is dead space (56B/object).
constexpr uint64_t kPayloadOff = 64;
constexpr uint32_t kEmpty = 0xffffffffu;
constexpr uint32_t kTombstone = 0xfffffffeu;

// Entry states.
enum : uint32_t { KSTATE_FREE = 0, KSTATE_ALLOCATED = 1, KSTATE_SEALED = 2 };
// Entry flags.
enum : uint32_t { KFLAG_FREED = 1 };

struct Entry {
  uint8_t id[16];
  uint64_t offset;   // payload offset within the data area
  uint64_t size;     // user size
  uint32_t state;
  uint32_t flags;
  uint32_t lru_prev;  // entry index or kEmpty
  uint32_t lru_next;
  uint32_t pins;      // client pin count: pinned entries are never evicted
  uint32_t _pad;
  uint64_t created_ms;  // CLOCK_MONOTONIC at alloc (stale-ALLOCATED reaping)
};

struct Header {
  uint64_t magic;
  uint64_t capacity;   // data area bytes
  uint64_t used;       // user bytes in live entries
  uint64_t data_off;   // offset of data area from arena base
  pthread_mutex_t mutex;
  uint64_t free_head;      // data-offset of first free block, or 0 (none)
  uint32_t num_entries;
  uint32_t lru_head;       // least recently used entry index
  uint32_t lru_tail;
  uint32_t next_free_entry;      // freelist of Entry slots via lru_next
  uint32_t entry_freelist_head;  // kEmpty-terminated
  uint64_t num_evictions;
  uint32_t buckets[kNumBuckets];  // entry index, kEmpty, or kTombstone
  Entry entries[kMaxObjects];
};

// Free data blocks: [u64 size|1bit free][u64 next_free][u64 prev_free]...[u64 size]
// Used data blocks: [u64 size|0][payload][u64 size]
// size field counts the WHOLE block (meta included); low bit = free flag.

struct Arena {
  uint8_t* base;
  Header* hdr;
  uint8_t* data;
  uint64_t map_len;
};

inline uint64_t block_size(uint64_t word) { return word & ~1ULL; }
inline bool block_free(uint64_t word) { return word & 1ULL; }

inline uint64_t rd64(uint8_t* p) { uint64_t v; memcpy(&v, p, 8); return v; }
inline void wr64(uint8_t* p, uint64_t v) { memcpy(p, &v, 8); }

// free-block links stored at payload start (data offsets; 0 = none)
inline uint64_t fb_next(uint8_t* data, uint64_t off) { return rd64(data + off + 8); }
inline uint64_t fb_prev(uint8_t* data, uint64_t off) { return rd64(data + off + 16); }
inline void set_fb_next(uint8_t* data, uint64_t off, uint64_t v) { wr64(data + off + 8, v); }
inline void set_fb_prev(uint8_t* data, uint64_t off, uint64_t v) { wr64(data + off + 16, v); }

void freelist_remove(Header* h, uint8_t* data, uint64_t off) {
  uint64_t prev = fb_prev(data, off), next = fb_next(data, off);
  if (prev) set_fb_next(data, prev, next);
  else h->free_head = next;
  if (next) set_fb_prev(data, next, prev);
}

void freelist_push(Header* h, uint8_t* data, uint64_t off) {
  set_fb_prev(data, off, 0);
  set_fb_next(data, off, h->free_head);
  if (h->free_head) set_fb_prev(data, h->free_head, off);
  h->free_head = off;
}

void write_block(uint8_t* data, uint64_t off, uint64_t size, bool is_free) {
  uint64_t word = size | (is_free ? 1ULL : 0ULL);
  wr64(data + off, word);
  wr64(data + off + size - 8, word);
}

uint64_t hash_id(const uint8_t* id) {
  uint64_t h = 1469598103934665603ULL;
  for (int i = 0; i < 16; i++) { h ^= id[i]; h *= 1099511628211ULL; }
  return h;
}

uint32_t find_entry(Header* h, const uint8_t* id) {
  uint64_t b = hash_id(id) & (kNumBuckets - 1);
  for (uint32_t probe = 0; probe < kNumBuckets; probe++) {
    uint32_t v = h->buckets[(b + probe) & (kNumBuckets - 1)];
    if (v == kEmpty) return kEmpty;
    if (v != kTombstone && memcmp(h->entries[v].id, id, 16) == 0) return v;
  }
  return kEmpty;
}

bool insert_bucket(Header* h, const uint8_t* id, uint32_t entry_idx) {
  uint64_t b = hash_id(id) & (kNumBuckets - 1);
  for (uint32_t probe = 0; probe < kNumBuckets; probe++) {
    uint32_t slot = (b + probe) & (kNumBuckets - 1);
    uint32_t v = h->buckets[slot];
    if (v == kEmpty || v == kTombstone) { h->buckets[slot] = entry_idx; return true; }
  }
  return false;
}

void remove_bucket(Header* h, const uint8_t* id) {
  uint64_t b = hash_id(id) & (kNumBuckets - 1);
  for (uint32_t probe = 0; probe < kNumBuckets; probe++) {
    uint32_t slot = (b + probe) & (kNumBuckets - 1);
    uint32_t v = h->buckets[slot];
    if (v == kEmpty) return;
    if (v != kTombstone && memcmp(h->entries[v].id, id, 16) == 0) {
      h->buckets[slot] = kTombstone;
      return;
    }
  }
}

// -- LRU (most recent at tail) ---------------------------------------------
void lru_unlink(Header* h, uint32_t idx) {
  Entry& e = h->entries[idx];
  if (e.lru_prev != kEmpty) h->entries[e.lru_prev].lru_next = e.lru_next;
  else if (h->lru_head == idx) h->lru_head = e.lru_next;
  if (e.lru_next != kEmpty) h->entries[e.lru_next].lru_prev = e.lru_prev;
  else if (h->lru_tail == idx) h->lru_tail = e.lru_prev;
  e.lru_prev = e.lru_next = kEmpty;
}

void lru_push_tail(Header* h, uint32_t idx) {
  Entry& e = h->entries[idx];
  e.lru_prev = h->lru_tail;
  e.lru_next = kEmpty;
  if (h->lru_tail != kEmpty) h->entries[h->lru_tail].lru_next = idx;
  h->lru_tail = idx;
  if (h->lru_head == kEmpty) h->lru_head = idx;
}

uint32_t entry_alloc(Header* h) {
  if (h->entry_freelist_head != kEmpty) {
    uint32_t idx = h->entry_freelist_head;
    h->entry_freelist_head = h->entries[idx].lru_next;
    return idx;
  }
  if (h->next_free_entry < kMaxObjects) return h->next_free_entry++;
  return kEmpty;
}

void entry_release(Header* h, uint32_t idx) {
  h->entries[idx].state = KSTATE_FREE;
  h->entries[idx].lru_next = h->entry_freelist_head;
  h->entry_freelist_head = idx;
}

// -- allocator -------------------------------------------------------------
uint64_t round_block(uint64_t user_size) {
  uint64_t need = user_size + kPayloadOff + 8;  // header gap + payload + footer
  if (need < 32) need = 32;  // room for free links
  return (need + kAlign - 1) & ~(kAlign - 1);
}

uint64_t data_alloc(Header* h, uint8_t* data, uint64_t user_size) {
  uint64_t want = round_block(user_size);
  uint64_t off = h->free_head;
  while (off) {
    uint64_t word = rd64(data + off);
    uint64_t bsize = block_size(word);
    if (bsize >= want) {
      freelist_remove(h, data, off);
      if (bsize - want >= 64) {
        // split: remainder stays free
        uint64_t rem_off = off + want;
        write_block(data, rem_off, bsize - want, true);
        freelist_push(h, data, rem_off);
        write_block(data, off, want, false);
      } else {
        write_block(data, off, bsize, false);
      }
      return off + kPayloadOff;  // payload offset (64-aligned)
    }
    off = fb_next(data, off);
  }
  return UINT64_MAX;
}

void data_free(Header* h, uint8_t* data, uint64_t payload_off) {
  uint64_t off = payload_off - kPayloadOff;
  uint64_t word = rd64(data + off);
  uint64_t bsize = block_size(word);
  // coalesce with next
  uint64_t next_off = off + bsize;
  if (next_off + 8 <= h->capacity) {
    uint64_t nword = rd64(data + next_off);
    if (block_free(nword)) {
      freelist_remove(h, data, next_off);
      bsize += block_size(nword);
    }
  }
  // coalesce with prev
  if (off >= 8) {
    uint64_t pword = rd64(data + off - 8);
    if (block_free(pword)) {
      uint64_t poff = off - block_size(pword);
      freelist_remove(h, data, poff);
      off = poff;
      bsize += block_size(pword);
    }
  }
  write_block(data, off, bsize, true);
  freelist_push(h, data, off);
}

void evict_entry(Header* h, uint8_t* data, uint32_t idx) {
  Entry& e = h->entries[idx];
  remove_bucket(h, e.id);
  lru_unlink(h, idx);
  data_free(h, data, e.offset);
  h->used -= e.size;
  h->num_evictions++;
  entry_release(h, idx);
}

// Try to make room: evict freed+sealed entries from LRU head.
bool evict_until(Header* h, uint8_t* data, uint64_t user_size) {
  uint64_t want = round_block(user_size);
  for (int rounds = 0; rounds < (int)kMaxObjects; rounds++) {
    // quick check: is there a block big enough?
    for (uint64_t off = h->free_head; off; off = fb_next(data, off)) {
      if (block_size(rd64(data + off)) >= want) return true;
    }
    // evict next evictable from LRU head
    uint32_t idx = h->lru_head;
    while (idx != kEmpty) {
      Entry& e = h->entries[idx];
      uint32_t next = e.lru_next;
      if ((e.flags & KFLAG_FREED) && e.pins == 0) {  // freed AND unpinned evict
        evict_entry(h, data, idx);
        break;
      }
      idx = next;
    }
    if (idx == kEmpty) return false;  // nothing evictable
  }
  return false;
}

uint64_t now_ms() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (uint64_t)ts.tv_sec * 1000 + ts.tv_nsec / 1000000;
}

void lock(Header* h) {
  int rc = pthread_mutex_lock(&h->mutex);
  if (rc == EOWNERDEAD) pthread_mutex_consistent(&h->mutex);
}

void unlock(Header* h) { pthread_mutex_unlock(&h->mutex); }

}  // namespace

extern "C" {

// Create a new arena shm segment; returns mapped Arena* or null.
// pretouch_bytes: fault in this much of the data area up front (one write per
// page). tmpfs pages materialize on first touch at ~1.6 GiB/s; pre-touching at
// startup keeps the first puts at warm-page memcpy speed (~8 GiB/s here).
void* shmstore_create(const char* name, uint64_t capacity, uint64_t pretouch_bytes) {
  uint64_t data_off = (sizeof(Header) + 4095) & ~4095ULL;
  uint64_t total = data_off + capacity;
  shm_unlink(name);
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  if (ftruncate(fd, (off_t)total) != 0) { close(fd); shm_unlink(name); return nullptr; }
  void* base = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (base == MAP_FAILED) { shm_unlink(name); return nullptr; }
  Header* h = (Header*)base;
  memset(h, 0, sizeof(Header));
  h->capacity = capacity;
  // Page-align the data area so the in-block payload alignment (kPayloadOff)
  // yields 64-byte-aligned absolute addresses.
  h->data_off = data_off;
  h->lru_head = h->lru_tail = kEmpty;
  h->entry_freelist_head = kEmpty;
  for (uint32_t i = 0; i < kNumBuckets; i++) h->buckets[i] = kEmpty;
  pthread_mutexattr_t attr;
  pthread_mutexattr_init(&attr);
  pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&h->mutex, &attr);
  pthread_mutexattr_destroy(&attr);
  uint8_t* data = (uint8_t*)base + h->data_off;
  // Offset 0 holds a permanent used sentinel block so free_head==0 can mean
  // "no free blocks" and prev-coalescing never walks off the front.
  write_block(data, 0, kAlign, false);
  write_block(data, kAlign, capacity - kAlign, true);
  set_fb_next(data, kAlign, 0);
  set_fb_prev(data, kAlign, 0);
  h->free_head = kAlign;
  h->magic = kMagic;
  // Pre-fault data pages. Safe here: the arena is unpublished and holds exactly
  // two blocks (used sentinel at 0, one big free block at kAlign), so writes
  // into the free block's payload region touch only unused bytes. Skip the
  // sentinel/free-block metadata at the front and the boundary footer at the end.
  if (pretouch_bytes > capacity) pretouch_bytes = capacity;
  if (pretouch_bytes > kAlign + 32 + 16) {
    for (uint64_t off = kAlign + 32; off + 16 < pretouch_bytes; off += 4096)
      data[off] = 0;
  }
  Arena* a = new Arena{(uint8_t*)base, h, data, total};
  return a;
}

// Attach an existing arena.
void* shmstore_open(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) { close(fd); return nullptr; }
  void* base = mmap(nullptr, (size_t)st.st_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (base == MAP_FAILED) return nullptr;
  Header* h = (Header*)base;
  if (h->magic != kMagic) { munmap(base, (size_t)st.st_size); return nullptr; }
  Arena* a = new Arena{(uint8_t*)base, h, (uint8_t*)base + h->data_off,
                       (uint64_t)st.st_size};
  return a;
}

// Allocate an unsealed object; returns payload offset from arena base, or
// UINT64_MAX if it can't fit (after eviction), UINT64_MAX-1 if id exists.
uint64_t shmstore_alloc(void* arena, const uint8_t* id, uint64_t size) {
  Arena* a = (Arena*)arena;
  Header* h = a->hdr;
  lock(h);
  if (find_entry(h, id) != kEmpty) { unlock(h); return UINT64_MAX - 1; }
  uint64_t payload = data_alloc(h, a->data, size);
  if (payload == UINT64_MAX) {
    if (evict_until(h, a->data, size)) payload = data_alloc(h, a->data, size);
  }
  if (payload == UINT64_MAX) { unlock(h); return UINT64_MAX; }
  uint32_t idx = entry_alloc(h);
  if (idx == kEmpty) { data_free(h, a->data, payload); unlock(h); return UINT64_MAX; }
  Entry& e = h->entries[idx];
  memcpy(e.id, id, 16);
  e.offset = payload;
  e.size = size;
  e.state = KSTATE_ALLOCATED;
  e.flags = 0;
  e.pins = 0;
  e.created_ms = now_ms();
  e.lru_prev = e.lru_next = kEmpty;
  insert_bucket(h, id, idx);
  lru_push_tail(h, idx);
  h->used += size;
  unlock(h);
  return h->data_off + payload;
}

int shmstore_seal(void* arena, const uint8_t* id) {
  Arena* a = (Arena*)arena;
  Header* h = a->hdr;
  lock(h);
  uint32_t idx = find_entry(h, id);
  if (idx == kEmpty) { unlock(h); return -1; }
  h->entries[idx].state = KSTATE_SEALED;
  lru_unlink(h, idx);
  lru_push_tail(h, idx);
  unlock(h);
  return 0;
}

// Lookup a sealed object: fills offset (from arena base) and size; touches LRU.
int shmstore_lookup(void* arena, const uint8_t* id, uint64_t* offset, uint64_t* size) {
  Arena* a = (Arena*)arena;
  Header* h = a->hdr;
  lock(h);
  uint32_t idx = find_entry(h, id);
  if (idx == kEmpty || h->entries[idx].state != KSTATE_SEALED) { unlock(h); return -1; }
  Entry& e = h->entries[idx];
  *offset = h->data_off + e.offset;
  *size = e.size;
  lru_unlink(h, idx);
  lru_push_tail(h, idx);
  unlock(h);
  return 0;
}

// Mark freed. eager=1 evicts now (unless pinned); else the entry stays as
// evictable LRU cache.
int shmstore_free_obj(void* arena, const uint8_t* id, int eager) {
  Arena* a = (Arena*)arena;
  Header* h = a->hdr;
  lock(h);
  uint32_t idx = find_entry(h, id);
  if (idx == kEmpty) { unlock(h); return -1; }
  h->entries[idx].flags |= KFLAG_FREED;
  if (eager && h->entries[idx].pins == 0) evict_entry(h, a->data, idx);
  unlock(h);
  return 0;
}

// Pin: the entry's memory will not be recycled until released. Callers pin while
// zero-copy views alias the payload (plasma's client refcount role). A client that
// dies pinned leaks the entry until the arena is recreated.
int shmstore_pin(void* arena, const uint8_t* id) {
  Arena* a = (Arena*)arena;
  Header* h = a->hdr;
  lock(h);
  uint32_t idx = find_entry(h, id);
  if (idx == kEmpty) { unlock(h); return -1; }
  h->entries[idx].pins++;
  unlock(h);
  return 0;
}

int shmstore_release(void* arena, const uint8_t* id) {
  Arena* a = (Arena*)arena;
  Header* h = a->hdr;
  lock(h);
  uint32_t idx = find_entry(h, id);
  if (idx == kEmpty) { unlock(h); return -1; }
  Entry& e = h->entries[idx];
  if (e.pins > 0) e.pins--;
  // A release of a freed, now-unpinned entry evicts it promptly.
  if (e.pins == 0 && (e.flags & KFLAG_FREED)) evict_entry(h, a->data, idx);
  unlock(h);
  return 0;
}

uint64_t shmstore_used(void* arena) { return ((Arena*)arena)->hdr->used; }
uint64_t shmstore_capacity(void* arena) { return ((Arena*)arena)->hdr->capacity; }
uint64_t shmstore_num_evictions(void* arena) { return ((Arena*)arena)->hdr->num_evictions; }

uint64_t shmstore_count(void* arena) {
  Arena* a = (Arena*)arena;
  Header* h = a->hdr;
  lock(h);
  uint64_t n = 0;
  for (uint32_t i = h->lru_head; i != kEmpty; i = h->entries[i].lru_next) n++;
  unlock(h);
  return n;
}

// List up to max_out SEALED, unpinned entry ids in LRU order (spill candidates:
// the store can copy them out and evict to make room). Writes 16-byte ids
// consecutively into out; returns the count.
uint32_t shmstore_list_spillable(void* arena, uint8_t* out, uint32_t max_out) {
  Arena* a = (Arena*)arena;
  Header* h = a->hdr;
  lock(h);
  uint32_t n = 0;
  for (uint32_t idx = h->lru_head; idx != kEmpty && n < max_out;
       idx = h->entries[idx].lru_next) {
    Entry& e = h->entries[idx];
    if (e.state == KSTATE_SEALED && e.pins == 0) {
      memcpy(out + 16 * n, e.id, 16);
      n++;
    }
  }
  unlock(h);
  return n;
}

// Evict ALLOCATED (never sealed) entries older than age_ms: their writer died
// between alloc and seal (the direct-arena put path has no raylet create
// record to clean up), so without this sweep the capacity would leak until
// arena recreation. Returns the number of entries reclaimed.
uint32_t shmstore_reap_stale_allocated(void* arena, uint64_t age_ms) {
  Arena* a = (Arena*)arena;
  Header* h = a->hdr;
  uint64_t cutoff = now_ms();
  if (cutoff < age_ms) return 0;
  cutoff -= age_ms;
  lock(h);
  uint32_t n = 0;
  uint32_t idx = h->lru_head;
  while (idx != kEmpty) {
    Entry& e = h->entries[idx];
    uint32_t next = e.lru_next;
    if (e.state == KSTATE_ALLOCATED && e.pins == 0 && e.created_ms < cutoff) {
      evict_entry(h, a->data, idx);
      n++;
    }
    idx = next;
  }
  unlock(h);
  return n;
}

// Base pointer for ctypes to build zero-copy memoryviews.
void* shmstore_base(void* arena) { return ((Arena*)arena)->base; }
uint64_t shmstore_map_len(void* arena) { return ((Arena*)arena)->map_len; }

void shmstore_close(void* arena) {
  Arena* a = (Arena*)arena;
  munmap(a->base, a->map_len);
  delete a;
}

void shmstore_destroy(void* arena, const char* name) {
  shmstore_close(arena);
  shm_unlink(name);
}

}  // extern "C"
