"""Native (C++) components, built on demand with the system toolchain."""

from __future__ import annotations

import os
import subprocess
import threading

_build_lock = threading.Lock()
_HERE = os.path.dirname(os.path.abspath(__file__))


def lib_path(name: str) -> str:
    return os.path.join(_HERE, f"lib{name}.so")


def ensure_built(name: str) -> str | None:
    """Compile lib<name>.so from <name>.cpp if missing or stale; returns the path
    or None if the toolchain is unavailable/fails (callers fall back to Python)."""
    src = os.path.join(_HERE, f"{name}.cpp")
    out = lib_path(name)
    with _build_lock:
        if os.path.exists(out) and os.path.getmtime(out) >= os.path.getmtime(src):
            return out
        try:
            tmp = out + f".tmp{os.getpid()}"
            subprocess.run(
                ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", src, "-o", tmp,
                 "-lpthread", "-lrt"],
                check=True, capture_output=True, timeout=120,
            )
            os.replace(tmp, out)
            return out
        except Exception:
            return None
