"""ctypes binding for the native shared-memory object store (shmstore.cpp).

Server side (raylet) creates the arena; clients (workers) attach by name and read
payloads zero-copy via a memoryview over the mapping.
"""

from __future__ import annotations

import ctypes
from typing import Optional, Tuple

from ray_tpu._native import ensure_built
from ray_tpu.devtools import leaksan as _leaksan

_lib = None


def load() -> Optional[ctypes.CDLL]:
    global _lib
    if _lib is not None:
        return _lib
    path = ensure_built("shmstore")
    if path is None:
        return None
    lib = ctypes.CDLL(path)
    lib.shmstore_create.restype = ctypes.c_void_p
    lib.shmstore_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint64]
    lib.shmstore_open.restype = ctypes.c_void_p
    lib.shmstore_open.argtypes = [ctypes.c_char_p]
    lib.shmstore_alloc.restype = ctypes.c_uint64
    lib.shmstore_alloc.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64]
    lib.shmstore_seal.restype = ctypes.c_int
    lib.shmstore_seal.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.shmstore_lookup.restype = ctypes.c_int
    lib.shmstore_lookup.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.shmstore_free_obj.restype = ctypes.c_int
    lib.shmstore_free_obj.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
    lib.shmstore_list_spillable.restype = ctypes.c_uint32
    lib.shmstore_list_spillable.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32,
    ]
    lib.shmstore_reap_stale_allocated.restype = ctypes.c_uint32
    lib.shmstore_reap_stale_allocated.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.shmstore_pin.restype = ctypes.c_int
    lib.shmstore_pin.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.shmstore_release.restype = ctypes.c_int
    lib.shmstore_release.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    for fn in ("shmstore_used", "shmstore_capacity", "shmstore_count",
               "shmstore_num_evictions", "shmstore_map_len"):
        getattr(lib, fn).restype = ctypes.c_uint64
        getattr(lib, fn).argtypes = [ctypes.c_void_p]
    lib.shmstore_base.restype = ctypes.c_void_p
    lib.shmstore_base.argtypes = [ctypes.c_void_p]
    lib.shmstore_close.argtypes = [ctypes.c_void_p]
    lib.shmstore_destroy.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    _lib = lib
    return lib


_ALLOC_FULL = (1 << 64) - 1
_ALLOC_EXISTS = (1 << 64) - 2


class _ArenaView:
    """Zero-copy view over the whole arena mapping."""

    def __init__(self, lib, handle):
        base = lib.shmstore_base(handle)
        length = lib.shmstore_map_len(handle)
        self._buf = (ctypes.c_char * length).from_address(base)
        self.view = memoryview(self._buf).cast("B")


class _ArenaHandle:
    """Shared lookup/read/write/pin plumbing for server and client views."""

    def __init__(self, name: str, handle):
        self._lib = load()
        self.name = name
        self._h = handle
        self._view = _ArenaView(self._lib, self._h)

    def _handle(self):
        """The live native handle. Raises instead of letting ctypes pass NULL into
        the library (a closed client's handle is None; C would segfault on it —
        e.g. a racing reader during worker shutdown)."""
        h = self._h
        if h is None:
            raise KeyError(f"arena {self.name!r} is closed")
        return h

    def lookup(self, object_id: bytes) -> Optional[Tuple[int, int]]:
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        if self._lib.shmstore_lookup(self._handle(), object_id, ctypes.byref(off),
                                     ctypes.byref(size)) != 0:
            return None
        return off.value, size.value

    def read(self, offset: int, size: int) -> memoryview:
        return self._view.view[offset : offset + size]

    def write(self, offset: int, data: bytes):
        self._view.view[offset : offset + len(data)] = data

    def pin(self, object_id: bytes) -> bool:
        h = self._h
        if h is None:
            return False
        ok = self._lib.shmstore_pin(h, object_id) == 0
        if ok:
            _leaksan.track("shm_pin", token=(self.name, bytes(object_id)))
        return ok

    def release(self, object_id: bytes) -> bool:
        if self._h is None:
            return False
        ok = self._lib.shmstore_release(self._h, object_id) == 0
        if ok:
            _leaksan.untrack("shm_pin", token=(self.name, bytes(object_id)))
        return ok

    # Allocation/seal/free run directly in shared memory under the arena's
    # process-shared robust mutex, so BOTH the server (raylet) and clients
    # (workers) can drive the full create→write→seal lifecycle without an RPC
    # on the hot put path (plasma parity in spirit; plasma routes creates
    # through the store socket, we don't need to).
    def alloc(self, object_id: bytes, size: int) -> Optional[int]:
        """Returns payload offset from arena base, None if full, or raises
        FileExistsError on duplicate id."""
        off = self._lib.shmstore_alloc(self._handle(), object_id, size)
        if off == _ALLOC_FULL:
            return None
        if off == _ALLOC_EXISTS:
            raise FileExistsError(object_id.hex())
        return off

    def seal(self, object_id: bytes) -> bool:
        return self._lib.shmstore_seal(self._handle(), object_id) == 0

    def free(self, object_id: bytes, eager: bool = False) -> bool:
        return self._lib.shmstore_free_obj(self._handle(), object_id, 1 if eager else 0) == 0

    def read_pinned(self, object_id: bytes, offset: int, size: int) -> memoryview:
        """A view that PINS the object while it is being read. Zero-copy on
        Python >= 3.12: the arena will not recycle the payload while the view
        (or any memoryview/ndarray sliced from it) is alive, releasing when the
        region object is garbage collected. On older Pythons memoryview() does
        not honor a pure-Python __buffer__ (PEP 688 landed in 3.12), so a
        zero-copy view cannot tie the pin to alias lifetime — fall back to
        pin -> copy -> release, which is correct (no use-after-recycle) at the
        cost of one copy. Raises KeyError if the object vanished
        (evicted/spilled) since the caller resolved its location — callers
        re-resolve."""
        import sys

        if not self.pin(object_id):
            raise KeyError(object_id.hex())
        view = self._view.view[offset : offset + size]
        if sys.version_info >= (3, 12):
            region = _PinnedRegion(self, object_id, view)
            return memoryview(region)
        try:
            data = bytes(view)
        finally:
            self.release(object_id)
        return memoryview(data)


class _PinnedRegion:
    """Buffer-protocol wrapper tying an arena pin to Python object lifetime.

    memoryview(region) re-exports the underlying view but keeps `region` as the
    owner (PEP 688 __buffer__), so every slice/ndarray built over it holds the pin
    until the last alias dies — the plasma client-refcount role."""

    def __init__(self, handle: _ArenaHandle, object_id: bytes, view: memoryview):
        self._handle = handle
        self._object_id = object_id
        self._mv = view

    def __buffer__(self, flags):
        return self._mv.__buffer__(flags)

    def __del__(self):
        try:
            # raylint: disable=RL802 (buffer-protocol lifetime IS the release path: every alias built over memoryview(region) holds this object, and the pin must outlive the last alias — PEP 688)
            self._handle.release(self._object_id)
        except Exception:
            pass


class NativeStoreServer(_ArenaHandle):
    """Owns the arena segment (raylet side)."""

    def __init__(self, name: str, capacity: int, pretouch: int = 0):
        lib = load()
        if lib is None:
            raise RuntimeError("native shmstore unavailable")
        h = lib.shmstore_create(name.encode(), capacity, pretouch)
        if not h:
            raise RuntimeError(f"failed to create arena {name!r}")
        super().__init__(name, h)

    def reap_stale_allocated(self, age_ms: int) -> int:
        """Evict never-sealed entries older than age_ms (writer died mid-put)."""
        return int(self._lib.shmstore_reap_stale_allocated(self._handle(), age_ms))

    def list_spillable(self, max_out: int = 256) -> list:
        """Sealed, unpinned object keys in LRU order (spill candidates)."""
        buf = ctypes.create_string_buffer(16 * max_out)
        n = self._lib.shmstore_list_spillable(self._handle(), buf, max_out)
        return [buf.raw[16 * i : 16 * (i + 1)] for i in range(n)]

    @property
    def used(self) -> int:
        return self._lib.shmstore_used(self._handle())

    @property
    def capacity(self) -> int:
        return self._lib.shmstore_capacity(self._handle())

    @property
    def num_objects(self) -> int:
        return self._lib.shmstore_count(self._handle())

    @property
    def num_evictions(self) -> int:
        return self._lib.shmstore_num_evictions(self._handle())

    def destroy(self):
        if self._h:
            del self._view
            self._lib.shmstore_destroy(self._h, self.name.encode())
            self._h = None


class NativeStoreClient(_ArenaHandle):
    """Attaches to an existing arena (worker side)."""

    def __init__(self, name: str):
        lib = load()
        if lib is None:
            raise RuntimeError("native shmstore unavailable")
        h = lib.shmstore_open(name.encode())
        if not h:
            raise RuntimeError(f"failed to open arena {name!r}")
        super().__init__(name, h)

    def close(self):
        # Deliberately does NOT munmap: zero-copy readers (numpy arrays
        # deserialized from the store) may alias the mapping for the rest of the
        # process lifetime — plasma semantics; the kernel reclaims at exit.
        self._h = None
