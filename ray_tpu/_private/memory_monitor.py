"""Node memory monitor + group-by-owner worker-killing policy (OOM defense).

Design parity: reference `src/ray/common/memory_monitor.h:52` — poll node memory
usage (cgroup v2 when present, else /proc/meminfo) against a kill threshold —
and `src/ray/raylet/worker_killing_policy_group_by_owner.h:87` — group running
tasks by their owner, prefer evicting groups whose tasks are retriable, and
within the chosen group kill the worker running the newest task, so older
(further-progressed) work survives and the node never thrashes to death.
"""

from __future__ import annotations

import os


def _read_meminfo(path: str) -> tuple[int, int] | None:
    """(total_bytes, available_bytes) from a /proc/meminfo-format file."""
    total = avail = None
    try:
        with open(path) as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    total = int(line.split()[1]) * 1024
                elif line.startswith("MemAvailable:"):
                    avail = int(line.split()[1]) * 1024
                if total is not None and avail is not None:
                    return total, avail
    except (OSError, ValueError, IndexError):
        pass
    return None


def _own_cgroup_v2_path(proc_cgroup: str = "/proc/self/cgroup") -> str | None:
    """This process's cgroup-v2 directory, from /proc/self/cgroup ("0::/a/b")."""
    try:
        with open(proc_cgroup) as f:
            for line in f:
                # v2 unified hierarchy entries are "0::<path>"; v1 controllers
                # ("N:<name>:<path>") don't map onto /sys/fs/cgroup directly.
                if line.startswith("0::"):
                    rel = line.split("::", 1)[1].strip().lstrip("/")
                    return os.path.join("/sys/fs/cgroup", rel) if rel else "/sys/fs/cgroup"
    except OSError:
        pass
    return None


def _read_cgroup_v2() -> tuple[int, int] | None:
    """(limit_bytes, current_bytes) for the nearest memory-limited ancestor of
    this process's own cgroup, else None.

    Walking up from /proc/self/cgroup (not reading the fixed cgroup root)
    matters when the raylet runs in a systemd slice or container sub-group with
    a memory limit: the root's memory.max is usually "max", so a root-only read
    would miss the limit and fall back to host-wide meminfo — and the kernel
    would OOM-kill the node before the monitor ever triggered."""
    path = _own_cgroup_v2_path() or "/sys/fs/cgroup"
    root = "/sys/fs/cgroup"
    # The binding constraint is the ancestor closest to its limit, not the
    # deepest one with a limit set (a loose leaf limit must not mask a tight
    # parent slice limit) — so inspect every level and keep the worst ratio.
    tightest: tuple[int, int] | None = None
    while True:
        try:
            with open(os.path.join(path, "memory.max")) as f:
                raw = f.read().strip()
            if raw != "max":
                limit = int(raw)
                with open(os.path.join(path, "memory.current")) as f:
                    current = int(f.read().strip())
                if limit > 0 and (
                    tightest is None
                    or current / limit > tightest[1] / tightest[0]
                ):
                    tightest = (limit, current)
        except (OSError, ValueError):
            pass
        if path == root or not path.startswith(root):
            return tightest
        path = os.path.dirname(path)


class MemoryMonitor:
    """Computes the node's memory usage fraction on demand."""

    def __init__(self, meminfo_path: str = "/proc/meminfo"):
        self._meminfo_path = meminfo_path

    def usage_fraction(self) -> float | None:
        # A test-provided meminfo path bypasses cgroup discovery so fakes work
        # deterministically (reference tests monkeypatch MemoryMonitor the same
        # way, python/ray/tests/test_memory_pressure.py).
        if self._meminfo_path == "/proc/meminfo":
            cg = _read_cgroup_v2()
            if cg is not None:
                limit, current = cg
                if limit > 0:
                    return current / limit
        info = _read_meminfo(self._meminfo_path)
        if info is None:
            return None
        total, avail = info
        if total <= 0:
            return None
        return 1.0 - avail / total


def pick_worker_to_kill(handles: list) -> object | None:
    """Group-by-owner, retriable-first, newest-task-first victim selection.

    `handles` are raylet WorkerHandles. Never selects drivers. Returns None when
    there is nothing safe to kill (an empty node cannot relieve pressure by
    killing workers).
    """
    def _owner_key(h) -> str | None:
        if h.busy_task is not None:
            owner = (h.busy_task.get("owner") or {}).get("worker_id")
            return owner.hex() if hasattr(owner, "hex") else str(owner)
        leased = getattr(h, "leased_to", None)
        if leased is not None:  # leased workers run owner-retried pushed tasks
            return leased.hex() if hasattr(leased, "hex") else str(leased)
        return None

    def _retry_rank(h) -> float:
        """0 = known retriable (kill first), 1 = known non-retriable (protect),
        0.5 = leased (the raylet cannot see the pushed task's retry budget —
        rank between the two so neither certainty is inverted)."""
        if h.busy_task is not None:
            return 0.0 if h.busy_task.get("retries_left", 0) > 0 else 1.0
        return 0.5

    def _started(h) -> float:
        return getattr(h, "task_started_at", 0.0) or getattr(h, "started_at", 0.0)

    tasks = [
        h for h in handles
        if h.kind == "worker" and _owner_key(h) is not None
    ]
    if tasks:
        groups: dict[str, list] = {}
        for h in tasks:
            groups.setdefault(_owner_key(h), []).append(h)

        def group_rank(members: list) -> tuple:
            rank = max(_retry_rank(m) for m in members)
            newest = max(_started(m) for m in members)
            # Retriable groups first (their work is recoverable); then the
            # group whose newest task started last (least progress lost).
            return (rank, -newest)

        victims = min(groups.values(), key=group_rank)
        return max(victims, key=_started)
    actors = [h for h in handles if h.actor_id is not None and h.kind != "driver"]
    if actors:
        return max(actors, key=lambda m: getattr(m, "started_at", 0.0))
    return None
