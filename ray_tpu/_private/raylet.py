"""Raylet: the per-node manager process.

Design parity: reference `src/ray/raylet/` — NodeManager (node_manager.h:124) combining the
worker-lease protocol (HandleRequestWorkerLease), worker pool with prestart/reuse
(worker_pool.h:280), local + cluster lease managers with the hybrid scheduling policy
(scheduling/cluster_lease_manager.h, policy/hybrid_scheduling_policy.cc), placement-group
bundle resources (placement_group_resource_manager), and the object manager + plasma store
hosted in the same process (raylet/main.cc:177). Cross-node object transfer follows the
push/pull manager design (object_manager/push_manager.h, pull_manager.h) with chunked reads.

Topology difference from the reference (documented, intentional): workers hold exactly one
connection to their local raylet; all cross-process traffic is routed worker -> raylet
[-> raylet] -> worker rather than direct worker-to-worker gRPC. On TPU pods the data plane
for tensors is ICI via XLA collectives, not the object plane, so the object/control plane
optimizes for simplicity and robustness.
"""

from __future__ import annotations

import asyncio
import os
import signal
import subprocess
import sys
import time
import traceback
from typing import Any

from ray_tpu._private import rpc
from ray_tpu._private.config import CONFIG, bind_host_for, get_node_ip
from ray_tpu._private.ids import ActorID, NodeID, ObjectID, WorkerID
from ray_tpu._private.object_store import SharedObjectStore


class WorkerHandle:
    def __init__(self, worker_id: WorkerID, proc: subprocess.Popen | None, kind: str,
                 env_key: str | None = None, log_path: str | None = None):
        self.worker_id = worker_id
        self.proc = proc
        self.kind = kind  # "worker" | "driver" | "actor"
        self.env_key = env_key  # pip-env hash this worker's interpreter serves
        self.log_path = log_path  # worker stdout/stderr file (death-cause tail)
        self.conn: rpc.Connection | None = None
        self.registered = asyncio.Event()
        self.busy_task: dict | None = None  # currently running normal task spec
        self.inflight_actor_tasks: dict = {}  # task_id -> spec (actor calls in flight)
        self.actor_id: ActorID | None = None
        self.acquired: dict[str, float] = {}
        self.pg_key: tuple | None = None  # bundle the acquisition came from, if any
        self.last_idle = time.monotonic()
        self.started_at = time.monotonic()
        self.task_started_at = 0.0  # dispatch time of busy_task (OOM kill order)
        self.oom_killed: tuple | None = None  # (usage_frac, threshold) when reaped
        self.log_owner: str | None = None  # worker_id hex of current work's owner
        self.direct_addr: tuple[str, int] | None = None  # worker's direct-call server
        self.leased_to: WorkerID | None = None  # owner holding a cached lease

    @property
    def alive(self):
        return self.conn is not None and not self.conn.closed


class PullManager:
    """Prioritized, byte-budgeted admission for remote object pulls.

    Reference: `src/ray/object_manager/pull_manager.h:49` — three priority
    tiers (gets ahead of waits ahead of task args) and an in-flight byte cap
    so a burst of large pulls backpressures instead of blowing the store.
    Admission is FIFO within a tier; one oversized pull is always admitted
    when the manager is idle (progress guarantee)."""

    def __init__(self, budget_bytes: int):
        self.budget = budget_bytes
        self.inflight_bytes = 0
        self.inflight_count = 0
        self._seq = 0
        self._waiters: list[tuple] = []  # sorted (priority, seq, size, event)

    def _admissible(self, size: int) -> bool:
        if self.inflight_count == 0:
            return True  # never deadlock on one object larger than the budget
        return self.inflight_bytes + size <= self.budget

    async def admit(self, object_id, size: int, priority: int):
        if self._waiters or not self._admissible(size):
            ev = asyncio.Event()
            self._seq += 1
            entry = (priority, self._seq, size, ev)
            self._waiters.append(entry)
            self._waiters.sort(key=lambda e: (e[0], e[1]))
            while True:
                await ev.wait()
                ev.clear()
                head = self._waiters[0] if self._waiters else None
                if head is entry and self._admissible(size):
                    self._waiters.pop(0)
                    break
                if head is not None and head is not entry:
                    head[3].set()  # misdirected wakeup: forward to the head
                # else: we're head but capacity is short — wait for a release
        self.inflight_bytes += size
        self.inflight_count += 1
        # Chain-admit: room may remain for the next waiter.
        if self._waiters and self._admissible(self._waiters[0][2]):
            self._waiters[0][3].set()

    def release(self, object_id, size: int):
        self.inflight_bytes -= size
        self.inflight_count -= 1
        if self._waiters:
            self._waiters[0][3].set()


class ResourceManager:
    """Reference: LocalResourceManager + placement_group_resource_manager."""

    def __init__(self, total: dict[str, float]):
        self.total = dict(total)
        self.available = dict(total)
        # (pg_id, bundle_index) -> {"reserved": {...}, "available": {...}}
        self.bundles: dict[tuple, dict] = {}

    def feasible(self, demand: dict[str, float], pg_key=None) -> bool:
        pool = self.bundles[pg_key]["reserved"] if pg_key in self.bundles else self.total
        return all(pool.get(r, 0) >= amt for r, amt in demand.items())

    def can_acquire(self, demand: dict[str, float], pg_key=None) -> bool:
        if pg_key is not None:
            bundle = self.bundles.get(pg_key)
            if bundle is None:
                return False
            return all(bundle["available"].get(r, 0) >= amt for r, amt in demand.items())
        return all(self.available.get(r, 0) >= amt for r, amt in demand.items())

    def acquire(self, demand: dict[str, float], pg_key=None) -> bool:
        if not self.can_acquire(demand, pg_key):
            return False
        pool = self.bundles[pg_key]["available"] if pg_key is not None else self.available
        for r, amt in demand.items():
            pool[r] = pool.get(r, 0) - amt
        return True

    def release(self, demand: dict[str, float], pg_key=None):
        if pg_key is not None:
            bundle = self.bundles.get(pg_key)
            if bundle is None:
                return
            pool = bundle["available"]
            cap = bundle["reserved"]
        else:
            pool = self.available
            cap = self.total
        for r, amt in demand.items():
            pool[r] = min(pool.get(r, 0) + amt, cap.get(r, 0))

    def reserve_bundle(self, pg_key, resources: dict[str, float]) -> bool:
        if not all(self.available.get(r, 0) >= amt for r, amt in resources.items()):
            return False
        for r, amt in resources.items():
            self.available[r] -= amt
        self.bundles[pg_key] = {"reserved": dict(resources), "available": dict(resources)}
        return True

    def cancel_bundle(self, pg_key):
        bundle = self.bundles.pop(pg_key, None)
        if bundle is None:
            return
        for r, amt in bundle["reserved"].items():
            self.available[r] = min(self.available.get(r, 0) + amt, self.total.get(r, 0))


class Raylet:
    def __init__(
        self,
        node_id: NodeID,
        gcs_addr: tuple[str, int],
        resources: dict[str, float],
        labels: dict | None = None,
        is_head: bool = False,
        session_dir: str = "/tmp/ray_tpu",
        object_store_bytes: int | None = None,
        worker_env: dict | None = None,
        node_ip: str | None = None,
    ):
        from ray_tpu._private.gcs_replication import parse_addrs

        self.node_id = node_id
        # All GCS candidate addresses; gcs_addr tracks the CURRENT primary
        # (the one this raylet is registered with).
        self.gcs_addrs = parse_addrs(gcs_addr)
        self.gcs_addr = self.gcs_addrs[0]
        # The address peers dial: never advertise loopback on a multi-host
        # cluster (reference: NodeManager registers node_manager_address, not
        # localhost). Direct worker servers advertise this IP too.
        self.node_ip = node_ip or get_node_ip(self.gcs_addr[0])
        self.is_head = is_head
        self.labels = labels or {}
        self.session_dir = session_dir
        self.worker_env = worker_env or {}
        resources = dict(resources)
        if "memory" not in resources:
            # Every node advertises schedulable memory (bytes) so
            # @remote(memory=N) is feasible however the node was started —
            # init(), `ray_tpu start`, the YAML launcher, or cluster_utils.
            try:
                import psutil

                resources["memory"] = float(int(
                    psutil.virtual_memory().total
                    * (1.0 - CONFIG.object_store_memory_fraction)
                ))
            except Exception:
                pass
        self.resources = ResourceManager(resources)
        if object_store_bytes is None:
            try:
                import psutil

                object_store_bytes = int(
                    psutil.virtual_memory().total * CONFIG.object_store_memory_fraction
                )
            except Exception:
                object_store_bytes = 2 << 30
        self.store = SharedObjectStore(object_store_bytes)

        self.server: rpc.RpcServer | None = None
        self.gcs: rpc.Connection | None = None
        self.port: int | None = None
        self.workers: dict[WorkerID, WorkerHandle] = {}
        self.actors: dict[ActorID, WorkerID] = {}  # actors hosted on this node
        self.actor_addr_cache: dict[ActorID, dict] = {}
        self.task_queue: list[dict] = []  # ready tasks waiting for resources/worker
        self.running: dict[Any, dict] = {}  # task_id -> spec (dispatched)
        self.peer_conns: dict[NodeID, rpc.Connection] = {}
        self.node_view: dict[NodeID, dict] = {}  # cluster view from GCS
        self._sched_wakeup = asyncio.Event()
        self._spawning = 0  # worker spawns awaiting registration
        self._pulls_inflight: dict[ObjectID, asyncio.Future] = {}
        # Tasks this raylet forwarded to a peer and is responsible for until the
        # results reach the owner (reference: the owner-side NormalTaskSubmitter
        # retries when a leased node dies). task_id -> {"spec", "target",
        # "missing_since"}. Re-queued (tasks) or failed (actor calls) when the
        # target node dies, so work cannot vanish with a node between the moment
        # it was handed off and the moment its results reached the owner.
        self.delegated: dict[Any, dict] = {}
        # Sealed objects this node holds: id -> (size, owner). Re-reported to the
        # GCS after a GCS restart so the (non-persisted, owner-based) object
        # directory can be rebuilt from the nodes that actually hold the data.
        self._sealed_objects: dict[ObjectID, tuple[int, Any]] = {}
        # Batched object-directory traffic: per-put GCS round trips dominated
        # put cost on small hosts (reference: object directory updates are
        # similarly async/batched via the ray_syncer). Ops keep their relative
        # order (a free must not be applied before the report that precedes it,
        # nor after a re-report that follows it); a seal+free pair inside one
        # window cancels out only when the GCS never learned the object.
        self._obj_ops: list = []  # ordered ("report", ...) | ("free", oid) | None
        self._obj_pending_report: dict[ObjectID, int] = {}  # oid -> _obj_ops index
        self._obj_known: set[ObjectID] = set()  # flushed to GCS, not yet freed
        self._obj_flush_scheduled = False
        self.pull_manager = PullManager(CONFIG.pull_budget_bytes)
        self._last_authoritative_views = 0.0  # composite-scheduling GCS probes
        # pip runtime-env venvs (reference: runtime-env agent + env-keyed worker
        # pools, worker_pool.h:280): env key -> venv python path once built.
        self._venv_python: dict[str, str] = {}
        self._venv_failed: dict[str, tuple[str, float]] = {}  # key -> (err, at)
        self._venv_building: set[str] = set()
        self._env_specs: dict[str, dict] = {}  # env key -> its runtime_env
        self._gcs_connected_at = time.monotonic()  # refreshed on every (re)connect
        self._full_node_view: dict[NodeID, dict] = {}  # incl. alive=False nodes
        self._shutdown = False
        # cgroup-v2 worker isolation (reference src/ray/common/cgroup2/):
        # active only where the cgroupfs is writable; the raylet itself moves
        # into the reserved "system" group so worker memory pressure can't
        # starve the control plane.
        from ray_tpu._private.cgroup import manager_from_env

        self._cgroup = manager_from_env(node_id.hex()[:12])
        if self._cgroup is not None:
            self._cgroup.place_system_process(os.getpid())

    # ------------------------------------------------------------------ startup

    async def start(self, port: int = 0):
        self.server = rpc.RpcServer(lambda conn: self)
        await self.server.start(host=bind_host_for(self.node_ip), port=port)
        self.port = self.server.port
        await self._connect_gcs()
        loop = asyncio.get_running_loop()
        loop.create_task(self._heartbeat_loop())
        loop.create_task(self._scheduler_loop())
        loop.create_task(self._idle_reaper_loop())
        loop.create_task(self._log_monitor_loop())
        loop.create_task(self._memory_monitor_loop())
        return self

    async def _connect_gcs(self, deadline_s: float = 60.0):
        """Connect (or reconnect) to the GCS PRIMARY, register, and sync
        hosted state.

        Retries while the GCS is down: the control plane can restart (or fail
        over to another candidate) independently of raylets (reference: GCS
        clients buffer+retry during GCS downtime). With a replicated GCS the
        probe walks the candidate list, following NOT_PRIMARY redirects until
        the lease holder answers."""
        deadline = time.monotonic() + deadline_s
        hint = None
        i = 0
        while True:
            addr = tuple(hint) if hint else self.gcs_addrs[i % len(self.gcs_addrs)]
            hint = None
            i += 1
            try:
                conn = await rpc.connect(
                    *addr, handler=self, name="raylet->gcs"
                )
            except OSError:
                if self._shutdown or time.monotonic() > deadline:
                    raise
                await asyncio.sleep(0.5)
                continue
            try:
                st = await conn.call("repl_status", timeout=5.0)
            except rpc.RpcError:
                st = None
            if st is None or st.get("role") != "primary":
                hint = (st or {}).get("primary")
                try:
                    await conn.close()
                except Exception:
                    pass  # probe conn teardown; the retry loop owns recovery
                if self._shutdown or time.monotonic() > deadline:
                    raise rpc.ConnectionLost(
                        f"no GCS primary reachable at {self.gcs_addrs}")
                if not hint:
                    await asyncio.sleep(0.3)
                continue
            try:
                await self._register_with_gcs(conn)
            except rpc.ConnectionLost as e:
                # Role flipped (or the primary died) between the probe and the
                # registration sequence: follow any redirect hint and retry.
                hint = getattr(e, "primary", None)
                try:
                    await conn.close()
                except Exception:
                    pass  # half-registered conn teardown; loop retries anyway
                if self._shutdown or time.monotonic() > deadline:
                    raise
                if not hint:
                    await asyncio.sleep(0.3)
                continue
            self.gcs = conn
            self.gcs_addr = addr
            break
        # Armed only after full registration: a half-registered conn that
        # dies mid-sequence is retried here, not by a racing reconnect task.
        self.gcs.on_close(self._on_gcs_lost)
        # Delegation-recovery grace starts now: peers need time to re-register
        # with a restarted GCS before their absence can be read as death.
        self._gcs_connected_at = time.monotonic()

    async def _register_with_gcs(self, conn):
        await conn.call(
            "register_node",
            self.node_id,
            (self.node_ip, self.port),
            self.resources.total,
            self.labels,
            self.is_head,
        )
        # Actor state changes invalidate the local address cache (restart support).
        await conn.call("subscribe", "actors")
        await conn.call("subscribe", "nodes")
        hosted = {}
        for actor_id, worker_id in self.actors.items():
            h = self.workers.get(worker_id)
            hosted[actor_id] = {
                "worker_id": worker_id,
                "direct_addr": h.direct_addr if h is not None else None,
            }
        await conn.call(
            "sync_node_state",
            self.node_id,
            hosted,
            [(oid, sz, owner) for oid, (sz, owner) in self._sealed_objects.items()],
            list(self.resources.bundles.keys()),
        )

    def _on_gcs_lost(self, conn):
        if self._shutdown:
            return
        asyncio.get_running_loop().create_task(self._reconnect_gcs())

    async def _reconnect_gcs(self):
        # Retry indefinitely: a raylet must rejoin whenever the GCS comes back,
        # however long the outage (a bounded attempt would leave a zombie node).
        while not self._shutdown:
            try:
                await self._connect_gcs(deadline_s=60.0)
                return
            except Exception:
                await asyncio.sleep(1.0)

    def _pending_demand(self) -> dict:
        """Aggregate resources of queued-but-unplaceable work (autoscaler signal)."""
        demand: dict[str, float] = {}
        for spec in self.task_queue:
            for r, amt in (spec.get("resources") or {}).items():
                demand[r] = demand.get(r, 0.0) + float(amt)
        return demand

    async def _heartbeat_loop(self):
        while not self._shutdown:
            try:
                await self.gcs.call(
                    "heartbeat", self.node_id, self.resources.available,
                    self._pending_demand(),
                )
                nodes = await self.gcs.call("get_nodes")
                self.node_view = {n["node_id"]: n for n in nodes if n["alive"]}
                self._full_node_view = {n["node_id"]: n for n in nodes}
                await self._check_delegations()
            except rpc.NotPrimaryError:
                # Our candidate was deposed but its socket survived: close it
                # so the on_close path re-probes the candidate list and
                # re-registers with the new primary.
                try:
                    await self.gcs.close()
                except Exception:
                    pass  # already-dead conn; on_close reconnect still fires
            except rpc.RpcError:
                pass
            await asyncio.sleep(CONFIG.heartbeat_interval_s)

    async def _check_delegations(self):
        """Backstop for a missed node-removal pubsub event.

        A target the GCS affirmatively marks dead (alive=False) is recovered at
        once. A target merely *absent* from the view gets a longer grace — after
        a GCS restart, get_nodes only lists re-registered raylets, so a slow
        peer must not be treated as dead (that would duplicate normal tasks and
        spuriously fail in-flight actor calls against a live node)."""
        now = time.monotonic()
        full_view = getattr(self, "_full_node_view", {})
        in_reconnect_grace = now - self._gcs_connected_at < 4 * CONFIG.heartbeat_interval_s
        dead_targets = set()
        for entry in self.delegated.values():
            target = entry["target"]
            if target in self.node_view:
                entry["missing_since"] = None
            elif target in full_view:  # present but alive=False: confirmed dead
                dead_targets.add(target)
            elif in_reconnect_grace:
                entry["missing_since"] = None
            elif entry["missing_since"] is None:
                entry["missing_since"] = now
            elif now - entry["missing_since"] > 2 * CONFIG.heartbeat_interval_s:
                dead_targets.add(target)
        for target in dead_targets:
            await self._recover_delegated(target)

    async def _idle_reaper_loop(self):
        while not self._shutdown:
            await asyncio.sleep(10)
            # Reclaim arena blocks of direct-path puts whose writer died between
            # alloc and seal (no raylet create record exists for them).
            srv = getattr(self.store, "_srv", None)
            if srv is not None:
                try:
                    srv.reap_stale_allocated(60_000)
                except Exception:
                    pass  # reaping is advisory; the next sweep retries
            now = time.monotonic()
            idle = [
                w
                for w in self.workers.values()
                if w.kind == "worker"
                and w.busy_task is None
                and w.actor_id is None
                and w.leased_to is None
                and w.alive
                and now - w.last_idle > CONFIG.idle_worker_kill_s
            ]
            # Keep a small warm pool.
            for w in idle[2:]:
                await self._kill_worker(w)

    # ------------------------------------------------------------------ peers

    async def _peer(self, node_id: NodeID) -> rpc.Connection | None:
        conn = self.peer_conns.get(node_id)
        if conn is not None and not conn.closed:
            return conn
        info = self.node_view.get(node_id)
        if info is None:
            try:
                nodes = await self.gcs.call("get_nodes")
                self.node_view = {n["node_id"]: n for n in nodes if n["alive"]}
            except rpc.RpcError:
                return None
            info = self.node_view.get(node_id)
            if info is None:
                return None
        host, port = info["address"]
        try:
            # raylint: disable=RL902 (one-shot per-peer dial, memoized in peer_conns above; the steady-state scheduling loop never reaches it)
            conn = await rpc.connect(host, port, handler=self, name=f"raylet->{node_id.hex()[:8]}")
        except OSError:
            return None
        self.peer_conns[node_id] = conn
        return conn

    # ------------------------------------------------------------------ worker pool

    def _spawn_worker(self, kind: str = "worker", python_exe: str | None = None,
                      env_key: str | None = None) -> WorkerHandle:
        worker_id = WorkerID.from_random()
        log_dir = os.path.join(self.session_dir, "logs")
        os.makedirs(log_dir, exist_ok=True)
        log_path = os.path.join(log_dir, f"worker-{worker_id.hex()[:12]}.log")
        out = open(log_path, "wb")
        env = dict(os.environ)
        env.update(self.worker_env)
        from ray_tpu._private.node import _package_pythonpath

        env["PYTHONPATH"] = _package_pythonpath(env.get("PYTHONPATH"))
        env["RAY_TPU_WORKER_ID"] = worker_id.hex()
        env["RAY_TPU_NODE_ID"] = self.node_id.hex()
        # Workers must agree with this raylet on the node's advertised IP: they
        # bind their direct server per get_node_ip(), and the raylet publishes
        # direct_addr on self.node_ip — a mismatch (e.g. Raylet(node_ip=...)
        # without the env var) would advertise an interface the worker never
        # bound.
        env["RAY_TPU_NODE_IP"] = self.node_ip
        env["RAY_TPU_RAYLET_PORT"] = str(self.port)
        # Full candidate list, current primary first: a worker spawned during
        # a failover window still finds the control plane.
        _gcs_order = [self.gcs_addr] + [
            a for a in self.gcs_addrs if a != self.gcs_addr
        ]
        env["RAY_TPU_GCS_ADDR"] = ",".join(f"{h}:{p}" for h, p in _gcs_order)
        # Unbuffered so crash tracebacks reach the log file even on abrupt death
        # (reference: worker stdout/stderr files tailed by log_monitor.py).
        env["PYTHONUNBUFFERED"] = "1"
        renv = self._env_specs.get(env_key) if env_key else None
        if renv and renv.get("image_uri"):
            # Containerized worker (reference runtime_env/image_uri.py): the
            # engine runs on the host; host network/IPC keeps raylet RPC and
            # the shm object store reachable. PYTHONPATH stays host-side —
            # the image must contain ray_tpu.
            from ray_tpu._private import runtime_env as runtime_env_mod

            passthrough = {k: v for k, v in env.items()
                           if k.startswith("RAY_TPU_") or k == "PYTHONUNBUFFERED"
                           or k in self.worker_env}
            cmd = runtime_env_mod.container_command(
                renv, session_dir=self.session_dir, env=passthrough,
            )
        else:
            cmd = [python_exe or sys.executable,
                   "-m", "ray_tpu._private.default_worker"]
        proc = subprocess.Popen(
            cmd,
            env=env,
            stdout=out,
            stderr=subprocess.STDOUT,
        )
        out.close()  # child owns its duplicated fd; don't leak one per spawn
        if self._cgroup is not None and not (renv and renv.get("image_uri")):
            # Containerized workers: proc is the engine CLI, not the worker —
            # the engine owns the container's cgroup, placing the client pid
            # would cap the wrong process.
            self._cgroup.place_worker(proc.pid)
        handle = WorkerHandle(worker_id, proc, kind, env_key=env_key, log_path=log_path)
        self.workers[worker_id] = handle
        return handle

    def _find_idle_worker(self, env_key: str | None = None) -> WorkerHandle | None:
        for w in self.workers.values():
            if (
                w.kind == "worker" and w.alive and w.registered.is_set()
                and w.busy_task is None and w.actor_id is None
                and w.leased_to is None and w.env_key == env_key
            ):
                return w
        return None

    # -- pip runtime-env venvs --------------------------------------------

    def _venv_cache_root(self) -> str:
        return os.path.join(self.session_dir, "runtime_envs")

    def _resolve_env_python(self, spec: dict) -> tuple[str | None, bool]:
        """(python_exe, ready). Starts an async venv build on first sight; the
        scheduler retries the task until the env is ready (or fails it)."""
        from ray_tpu._private import runtime_env as runtime_env_mod

        key = runtime_env_mod.env_key(spec.get("runtime_env"))
        if key is None:
            return None, True
        if key in self._venv_python:
            return self._venv_python[key], True
        failed = self._venv_failed.get(key)
        if failed is not None:
            err, at = failed
            if time.monotonic() - at < 60.0:
                raise RuntimeError(f"runtime_env setup failed: {err}")
            # Retry window: a transient failure (wheel house mid-populate, disk
            # pressure) must not poison the env forever.
            self._venv_failed.pop(key, None)
        if key not in self._venv_building:
            self._venv_building.add(key)
            loop = asyncio.get_running_loop()
            renv = spec["runtime_env"]
            self._env_specs[key] = renv

            def build():
                if "conda" in renv:
                    return runtime_env_mod.ensure_conda_env(
                        renv, self._venv_cache_root()
                    )
                if "image_uri" in renv:
                    # No python to build — just fail fast here when no
                    # container engine exists on this node (the spawn would
                    # otherwise die repeatedly and opaquely).
                    runtime_env_mod.container_command(
                        renv, session_dir=self.session_dir, env={}
                    )
                    return None
                return runtime_env_mod.ensure_pip_env(renv, self._venv_cache_root())

            fut = loop.run_in_executor(None, build)

            def done(f):
                self._venv_building.discard(key)
                try:
                    self._venv_python[key] = f.result()
                except Exception as e:  # noqa: BLE001
                    self._venv_failed[key] = (str(e), time.monotonic())
                self._sched_wakeup.set()

            fut.add_done_callback(done)  # asyncio future: callback runs on the loop
        return None, False

    def _maybe_spawn_worker(self, env_key: str | None = None,
                            python_exe: str | None = None):
        """Background worker prestart. Bounded to the node's CPU slots plus slack
        under normal load, but when EVERY task worker is busy (e.g. nested
        zero-resource tasks whose parents block in get()), the pool may grow past
        the cap one spawn at a time — otherwise a parent waiting on a child that
        can never get a worker deadlocks the node."""
        cap = max(4, int(self.resources.total.get("CPU", 1))) + 2
        # Registered only: handles for in-flight spawns are already in
        # self.workers and would otherwise double-count against the cap
        # alongside self._spawning.
        task_workers = [
            w for w in self.workers.values()
            if w.kind == "worker" and w.alive and w.actor_id is None
            and w.registered.is_set()
        ]
        if env_key is not None:
            # Env-keyed pool: vanilla idle workers cannot serve this task, so the
            # vanilla cap must not block the spawn; bound the keyed pool itself.
            keyed = [w for w in task_workers if w.env_key == env_key]
            if any(w.busy_task is None for w in keyed):
                return  # an idle keyed worker exists; dispatch will find it
            # Count in-flight spawns against the keyed bound too: the 20ms
            # dispatch poll must not stack duplicate spawns while the first
            # keyed worker is still registering.
            if len(keyed) + self._spawning >= max(2, cap // 2) or self._spawning >= 4:
                return
            self._spawning += 1
            handle = self._spawn_worker(python_exe=python_exe, env_key=env_key)
            self._await_registration(handle)
            return
        all_busy = all(w.busy_task is not None for w in task_workers)
        over_cap = len(task_workers) + self._spawning >= cap
        if over_cap and not (all_busy and self._spawning == 0):
            return
        if self._spawning >= 4:
            return
        self._spawning += 1
        handle = self._spawn_worker(python_exe=python_exe, env_key=env_key)
        self._await_registration(handle)

    def _await_registration(self, handle: WorkerHandle):
        async def wait_registered():
            try:
                await asyncio.wait_for(
                    handle.registered.wait(), CONFIG.worker_register_timeout_s
                )
                self._sched_wakeup.set()
            except asyncio.TimeoutError:
                await self._kill_worker(handle)
            finally:
                self._spawning -= 1

        asyncio.get_running_loop().create_task(wait_registered())

    async def _kill_worker(self, handle: WorkerHandle):
        self.workers.pop(handle.worker_id, None)
        if handle.conn is not None:
            await handle.conn.close()
        if handle.proc is not None:
            try:
                handle.proc.terminate()
            except Exception:
                pass

    async def _death_cause(self, handle: WorkerHandle, base: str) -> str:
        """Structured death cause: exit code / signal + tail of the worker's log.

        Reference: ActorDeathCause (src/ray/protobuf/common.proto) attaches the
        why to actor death instead of a bare "actor died".
        """
        rc = None
        if handle.proc is not None:
            for _ in range(10):  # give the OS up to ~1s to reap the exit status
                rc = handle.proc.poll()
                if rc is not None:
                    break
                await asyncio.sleep(0.1)
        cause = base
        if handle.oom_killed is not None:
            frac, threshold = handle.oom_killed
            cause = (
                f"{base}: killed by the node memory monitor (memory usage "
                f"{frac:.2f} > threshold {threshold:.2f})"
            )
        if rc is not None:
            if rc < 0:
                try:
                    signame = signal.Signals(-rc).name
                except ValueError:
                    signame = f"signal {-rc}"
                cause += f" (killed by {signame})"
            else:
                cause += f" (exit code {rc})"
        tail = self._tail_log(handle.log_path)
        if tail:
            cause += f"; last lines of {os.path.basename(handle.log_path)}:\n{tail}"
        return cause

    @staticmethod
    def _tail_log(log_path: str | None, max_bytes: int = 4096, max_lines: int = 20) -> str:
        if not log_path:
            return ""
        try:
            with open(log_path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - max_bytes))
                data = f.read().decode("utf-8", "replace")
        except OSError:
            return ""
        lines = [ln for ln in data.splitlines() if ln.strip()]
        return "\n".join(lines[-max_lines:])

    async def _memory_monitor_loop(self):
        """OOM defense: kill workers (group-by-owner, retriable first) when node
        memory crosses the threshold, instead of letting the node thrash/die.

        Reference: memory_monitor.h:52 polling + worker_killing_policy_group_by_owner.h:87.
        """
        refresh_ms = CONFIG.memory_monitor_refresh_ms
        if refresh_ms <= 0:
            return
        from ray_tpu._private.memory_monitor import MemoryMonitor, pick_worker_to_kill

        monitor = MemoryMonitor(CONFIG.meminfo_path)
        threshold = CONFIG.memory_usage_threshold
        above_since: float | None = None
        while not self._shutdown:
            await asyncio.sleep(refresh_ms / 1000.0)
            frac = monitor.usage_fraction()
            if frac is None or frac < threshold:
                above_since = None
                continue
            now = time.monotonic()
            if above_since is None:
                above_since = now
                continue
            if now - above_since < CONFIG.memory_monitor_min_wait_s:
                continue
            victim = pick_worker_to_kill(list(self.workers.values()))
            if victim is None:
                continue
            victim.oom_killed = (frac, threshold)
            above_since = None  # re-debounce before the next kill
            if victim.leased_to is not None:
                # The raylet holds no spec for leased pushed tasks: hand the
                # lessee the cause so exhausted retries surface OutOfMemoryError
                # instead of a generic crash. A CALL (not notify): the ack
                # guarantees the cause is recorded before the conn-close from
                # the kill races it.
                owner = self.workers.get(victim.leased_to)
                if owner is not None and owner.alive:
                    try:
                        await asyncio.wait_for(
                            owner.conn.call(
                                "lease_oom",
                                {"worker_id": victim.worker_id,
                                 "cause": f"killed by the node memory monitor "
                                          f"(memory usage {frac:.2f} > "
                                          f"threshold {threshold:.2f})"},
                            ),
                            2.0,
                        )
                    except Exception:
                        pass  # event publish is advisory; the kill proceeds regardless
            await self._kill_worker(victim)

    async def _log_monitor_loop(self):
        """Tail every worker's log file and publish new lines to the driver.

        Reference: python/ray/_private/log_monitor.py streams per-worker
        stdout/stderr files back to the driver via GCS pubsub.
        """
        offsets: dict[str, int] = {}  # log_path -> bytes already shipped
        while not self._shutdown:
            await asyncio.sleep(0.5)
            for handle in list(self.workers.values()):
                path = handle.log_path
                if not path or handle.kind == "driver":
                    continue
                try:
                    size = os.path.getsize(path)
                except OSError:
                    continue
                off = offsets.get(path, 0)
                if size <= off:
                    continue
                try:
                    with open(path, "rb") as f:
                        f.seek(off)
                        chunk = f.read(min(size - off, 256 * 1024))
                except OSError:
                    continue
                # Ship whole lines only; hold a trailing partial line for later —
                # unless the window is full with no newline (one giant line):
                # ship it truncated and advance, or the tail would stall forever.
                cut = chunk.rfind(b"\n")
                if cut < 0:
                    if len(chunk) < 256 * 1024:
                        continue
                    offsets[path] = off + len(chunk)
                    text = chunk.decode("utf-8", "replace") + "...[line truncated]"
                else:
                    offsets[path] = off + cut + 1
                    text = chunk[:cut].decode("utf-8", "replace")
                lines = [ln for ln in text.splitlines() if ln.strip()]
                if not lines:
                    continue
                owner = getattr(handle, "log_owner", None)
                msg = {
                    "kind": handle.kind,
                    "pid": handle.proc.pid if handle.proc else None,
                    "node": self.node_id.hex(),
                    "worker": handle.worker_id.hex(),  # log-viewer identity
                    "owner": owner,  # driver scoping: worker_id hex of work's owner
                    "lines": lines[:200],
                }
                try:
                    await self.gcs.notify("publish_worker_logs", msg)
                except Exception:
                    pass  # GCS briefly unreachable: lines ship on the next poll
            # Drop offsets of files whose workers are gone (bounded memory).
            live = {h.log_path for h in self.workers.values() if h.log_path}
            for path in list(offsets):
                if path not in live:
                    offsets.pop(path)

    def _on_worker_lost(self, handle: WorkerHandle):
        """Worker connection dropped: fail or retry its in-flight work."""
        self.workers.pop(handle.worker_id, None)
        if self._cgroup is not None and handle.proc is not None:
            self._cgroup.remove_worker(handle.proc.pid)
        if handle.acquired:
            self.resources.release(handle.acquired, handle.pg_key)
            handle.acquired = {}
            handle.pg_key = None
            handle.leased_to = None
        # A dying owner's cached leases must not strand workers (reference:
        # leases are tied to the lessee's liveness). The worker may still be
        # executing a pushed task the raylet cannot see (leased tasks never set
        # busy_task here), so returning it to the idle pool would double-book
        # it — kill it instead; _on_worker_lost releases its resources.
        loop = asyncio.get_running_loop()
        for w in list(self.workers.values()):
            if w.leased_to == handle.worker_id:
                w.leased_to = None
                loop.create_task(self._kill_worker(w))
        self._sched_wakeup.set()
        spec = handle.busy_task
        loop = asyncio.get_running_loop()
        if spec is not None:
            handle.busy_task = None
            self.running.pop(spec["task_id"], None)
            # Streaming tasks are not retried: a replay would re-emit items the
            # consumer already took (and rewrite sealed item buffers); fail the
            # stream cleanly instead.
            if spec.get("retries_left", 0) > 0 and spec.get("num_returns") != "streaming":
                spec["retries_left"] -= 1
                self.task_queue.append(spec)
                self._sched_wakeup.set()
            else:
                async def fail_with_cause(spec=spec):
                    await self._fail_task(
                        spec,
                        await self._death_cause(handle, "worker died during execution"),
                        oom=handle.oom_killed is not None,
                    )

                loop.create_task(fail_with_cause())
        if handle.actor_id is not None or handle.inflight_actor_tasks:
            actor_id = handle.actor_id
            inflight = list(handle.inflight_actor_tasks.values())
            handle.inflight_actor_tasks.clear()

            async def report_with_cause():
                cause = await self._death_cause(handle, "actor worker process died")
                if actor_id is not None:
                    await self._report_actor_failure(actor_id, cause)
                # Fail actor calls that were pushed but never completed
                # (caller would hang otherwise).
                for spec in inflight:
                    await self._fail_actor_task(spec, cause)

            if actor_id is not None:
                self.actors.pop(actor_id, None)
            loop.create_task(report_with_cause())

    async def _report_actor_failure(self, actor_id: ActorID, reason: str):
        try:
            await self.gcs.call("actor_failed", actor_id, reason)
        except rpc.RpcError:
            pass

    async def _fail_task(self, spec: dict, reason: str, oom: bool = False):
        from ray_tpu._private import serialization
        from ray_tpu.exceptions import OutOfMemoryError, WorkerCrashedError

        err_cls = OutOfMemoryError if oom else WorkerCrashedError
        err = serialization.dumps(err_cls(f"task {spec.get('name')} failed: {reason}"))
        results = [
            {"object_id": oid, "inline": err, "error": True}
            for oid in spec["return_ids"]
        ]
        await self._route_results_to_owner(spec, results)
        if spec.get("num_returns") == "streaming":
            owner = spec["owner"]
            await self._route_to_worker(
                owner["node_id"], owner["worker_id"], "stream_abort",
                {"task_id": spec["task_id"], "reason": reason},
            )
        await self._settle_delegation(spec)

    # ------------------------------------------------------------------ delegation

    async def _forward_to_peer(self, spec: dict, target: NodeID, method: str = "submit_task") -> bool:
        """Track-then-notify a spec to a peer; untrack if the send fails so a
        never-delivered task is not 'recovered' into a duplicate later."""
        peer = await self._peer(target)
        if peer is None:
            return False
        self._track_delegation(spec, target)
        try:
            await peer.notify(method, spec)
        except rpc.RpcError:
            self.delegated.pop(spec["task_id"], None)
            return False
        return True

    def _track_delegation(self, spec: dict, target: NodeID):
        """Remember a spec forwarded to `target` until its results reach the owner."""
        if spec.get("type") not in ("task", "actor_task"):
            return
        via = spec.setdefault("via", [])
        if self.node_id not in via:
            via.append(self.node_id)
        self.delegated[spec["task_id"]] = {
            "spec": spec, "target": target, "missing_since": None,
        }

    async def _settle_delegation(self, spec: dict):
        """Results reached the routing stage: release every forwarder on the path."""
        for nid in spec.get("via", ()):
            if nid == self.node_id:
                self.delegated.pop(spec["task_id"], None)
                continue
            peer = await self._peer(nid)
            if peer is not None:
                try:
                    await peer.notify("task_settled", spec["task_id"])
                except rpc.RpcError:
                    pass

    async def rpc_task_settled(self, conn, task_id):
        self.delegated.pop(task_id, None)
        return True

    async def _recover_delegated(self, dead: NodeID):
        """The node a task was handed to died: re-queue it here (normal tasks,
        within the retry budget) or fail it to the owner (actor calls)."""
        for task_id, entry in list(self.delegated.items()):
            if entry["target"] != dead:
                continue
            self.delegated.pop(task_id, None)
            spec = entry["spec"]
            if spec["type"] == "actor_task":
                await self._fail_actor_task(spec, "actor's node died with call in flight")
            elif (
                spec.get("retries_left", 0) > 0
                and spec.get("num_returns") != "streaming"
            ):
                spec["retries_left"] -= 1
                self.task_queue.append(spec)
                self._sched_wakeup.set()
            else:
                await self._fail_task(spec, f"node {dead.hex()[:8]} died (retries exhausted)")

    # ------------------------------------------------------------------ scheduling

    def _pg_key(self, spec) -> tuple | None:
        pg = spec.get("placement_group")
        if pg is None:
            return None
        return (pg["pg_id"], pg["bundle_index"])

    async def _scheduler_loop(self):
        """Reference: ClusterLeaseManager::ScheduleAndGrantLeases.

        Each wakeup makes ONE full pass, but a resource shape that failed to
        dispatch is memoized for the pass and later tasks with the same shape are
        skipped without the (await-laden) dispatch attempt — a deep homogeneous
        queue (10k queued 1-CPU tasks) costs one real attempt plus cheap dict
        checks instead of the O(n^2)-awaits rescans that capped bulk-async
        throughput, while heterogeneous queues still get every distinct shape
        tried (no head-of-line starvation).
        """
        while not self._shutdown:
            # Event-driven with a poll fallback: completions/registrations set the
            # wakeup and dispatch IMMEDIATELY; an unconditional sleep here would
            # gate throughput to (idle workers)/(sleep) per second.
            try:
                await asyncio.wait_for(
                    self._sched_wakeup.wait(), timeout=0.02 if self.task_queue else None
                )
            except asyncio.TimeoutError:
                pass
            self._sched_wakeup.clear()
            remaining = []
            queue, self.task_queue = self.task_queue, []
            failed_shapes: set = set()
            for spec in queue:
                shape = self._dispatch_shape(spec)
                if shape in failed_shapes:
                    remaining.append(spec)
                    continue
                try:
                    dispatched = await self._try_dispatch(spec)
                except Exception:
                    # e.g. a peer connection dying mid-notify: the spec stays
                    # queued and the loop survives (an escaping exception after
                    # the queue swap would silently lose every queued task).
                    traceback.print_exc()
                    dispatched = False
                if not dispatched:
                    remaining.append(spec)
                    failed_shapes.add(shape)
            # Work submitted while this pass ran landed in the fresh task_queue.
            self.task_queue = remaining + self.task_queue

    def _dispatch_shape(self, spec: dict) -> tuple:
        """Pass-local memo key: specs with equal shape dispatch-or-fail together.
        Includes the runtime-env key: a pip-env task waiting on its venv must not
        poison the memo for plain tasks with the same resource shape."""
        from ray_tpu._private import runtime_env as runtime_env_mod

        strategy = spec.get("scheduling_strategy") or {}
        # Label/composite selectors join the key for the same reason env_key
        # did: an undispatchable labeled task must not poison the memo for
        # plain tasks of the same resource shape.
        label_key = None
        if strategy.get("labels") or strategy.get("composite"):
            label_key = repr((strategy.get("labels"), strategy.get("composite")))
        return (
            tuple(sorted((spec.get("resources") or {}).items())),
            self._pg_key(spec),
            strategy.get("node_id"),
            label_key,
            runtime_env_mod.env_key(spec.get("runtime_env")),
        )

    def _label_feasible_nodes(self, hard: dict, demand: dict,
                              views: dict | None = None) -> list:
        """Alive peers (from the GCS view) matching a hard label selector with
        the resource shape in their total supply."""
        from ray_tpu.util.scheduling_strategies import match_labels

        out = []
        for node_id, view in (views or self.node_view).items():
            if node_id == self.node_id or not view.get("alive", True):
                continue
            if not match_labels(view.get("labels"), hard):
                continue
            total = view.get("resources_total") or {}
            if all(total.get(r, 0) >= amt for r, amt in demand.items()):
                out.append((node_id, view))
        return out

    async def _authoritative_views(self) -> dict:
        """Current cluster membership straight from the GCS: composite
        resolution must not miss a labeled node whose subscription update is
        still in flight."""
        try:
            nodes = await self.gcs.call("get_nodes")
            return {v["node_id"]: v for v in nodes}
        except Exception:
            return self.node_view

    def _composite_choose(self, spec: dict, subs: list,
                          views: dict | None = None) -> dict | None:
        """First sub-strategy that is satisfiable RIGHT NOW (reference shape:
        composite policies over node_label_scheduling_policy.cc). None = no
        sub currently satisfiable (the task stays queued)."""
        from ray_tpu.util.scheduling_strategies import match_labels

        demand = spec.get("resources") or {}
        views = views if views is not None else self.node_view
        for sub in subs:
            sub = sub or {}
            if sub.get("node_id") is not None:
                view = views.get(sub["node_id"])
                if sub["node_id"] == self.node_id or (
                    view is not None and view.get("alive", True)
                ):
                    return sub
                continue
            hard = (sub.get("labels") or {}).get("hard")
            if hard:
                local_ok = match_labels(self.labels, hard) and self.resources.feasible(
                    demand, None
                )
                if local_ok or self._label_feasible_nodes(hard, demand, views):
                    return sub
                continue
            # plain resource scheduling: satisfiable if anyone can ever run it
            if self.resources.feasible(demand, None):
                return sub
            for _nid, view in views.items():
                total = view.get("resources_total") or {}
                if view.get("alive", True) and all(
                    total.get(r, 0) >= amt for r, amt in demand.items()
                ):
                    return sub
        return None

    async def _try_dispatch(self, spec: dict) -> bool:
        demand = spec.get("resources") or {}
        strategy = spec.get("scheduling_strategy")
        views = None  # None => the subscribed node_view
        if strategy and strategy.get("composite"):
            chosen = self._composite_choose(spec, strategy["composite"])
            if chosen is None:
                # The subscribed view may lag a just-registered labeled node:
                # consult the GCS directly, but rate-limited — this loop runs
                # per queued task per pass and must not head-of-line block on
                # an RPC each time.
                now = time.monotonic()
                if now - self._last_authoritative_views < 1.0:
                    return False
                self._last_authoritative_views = now
                views = await self._authoritative_views()
                chosen = self._composite_choose(spec, strategy["composite"], views)
                if chosen is None:
                    return False  # nothing satisfiable yet: stay queued
            # chosen applies to THIS dispatch only (spec keeps the composite,
            # so forwarded peers and retries re-evaluate against fresh views)
            strategy = dict(chosen) or None
        if strategy and strategy.get("labels"):
            from ray_tpu.util.scheduling_strategies import match_labels

            sel = strategy["labels"]
            hard = sel.get("hard")
            soft = sel.get("soft")
            if hard and not match_labels(self.labels, hard):
                # Must run on a labeled node: forward to a matching peer
                # (soft-preferred), else wait for one to join. Reuse the fresh
                # views when the composite step fetched them — the node that
                # made the sub satisfiable may not be in the subscribed view.
                peers = self._label_feasible_nodes(hard, demand, views)
                if soft:
                    preferred = [
                        p for p in peers if match_labels(p[1].get("labels"), soft)
                    ]
                    peers = preferred or peers
                for node_id, _view in peers:
                    if await self._forward_to_peer(spec, node_id):
                        return True
                return False
            if soft and not match_labels(self.labels, soft):
                # Soft-only preference: route to an idle soft-matching peer if
                # one exists (it will keep the task — its own labels match);
                # otherwise run here.
                for node_id, view in self._label_feasible_nodes(
                    {**(hard or {})}, demand, views
                ):
                    if not match_labels(view.get("labels"), soft):
                        continue
                    avail = view.get("resources_available") or {}
                    if all(avail.get(r, 0) >= amt for r, amt in demand.items()):
                        if await self._forward_to_peer(spec, node_id):
                            return True
                # no idle preferred peer: fall through to local dispatch
            # local node matches (or soft best-effort): normal dispatch
        if strategy and strategy.get("node_id") is not None:
            target = strategy["node_id"]
            if target != self.node_id:
                if await self._forward_to_peer(spec, target):
                    return True
                if not strategy.get("soft"):
                    await self._fail_task(spec, f"affinity node {target} unavailable")
                    return True
                # soft affinity: fall through to normal scheduling
        pg_key = self._pg_key(spec)
        if pg_key is not None and pg_key not in self.resources.bundles:
            # Bundle not on this node: hand off asynchronously (pg readiness can take
            # seconds; never head-of-line block the scheduler loop on it).
            asyncio.get_running_loop().create_task(self._route_pg_task(spec))
            return True
        if not self.resources.feasible(demand, pg_key):
            return await self._spill(spec)
        if not self.resources.can_acquire(demand, pg_key):
            # Feasible but busy; consider spreading if another node is free.
            if await self._maybe_spread(spec):
                return True
            return False
        from ray_tpu._private import runtime_env as runtime_env_mod

        env_key = runtime_env_mod.env_key(spec.get("runtime_env"))
        if env_key is not None:
            try:
                python_exe, ready = self._resolve_env_python(spec)
            except RuntimeError as e:
                await self._fail_task(spec, str(e))
                return True
            if not ready:
                return False  # venv building; wakeup re-dispatches
        else:
            python_exe = None
        worker = self._find_idle_worker(env_key)
        if worker is None:
            # Spawn happens in the BACKGROUND: awaiting a worker's registration
            # inside the dispatch loop would serialize the whole scheduler behind
            # process startup. The task stays queued; registration wakes us.
            self._maybe_spawn_worker(env_key=env_key, python_exe=python_exe)
            return False
        # No await separates can_acquire from here (single-threaded loop), so this
        # acquire cannot fail; it performs the actual bookkeeping.
        if not self.resources.acquire(demand, pg_key):
            return False
        worker.acquired = demand
        worker.pg_key = pg_key
        worker.busy_task = spec
        worker.task_started_at = time.monotonic()
        owner_wid = (spec.get("owner") or {}).get("worker_id")
        worker.log_owner = owner_wid.hex() if hasattr(owner_wid, "hex") else None
        self.running[spec["task_id"]] = spec
        try:
            await worker.conn.notify("push_task", spec)
        except rpc.RpcError:
            self._on_worker_lost(worker)
            return False
        return True

    @staticmethod
    def _spec_hard_labels(spec: dict) -> dict | None:
        strategy = spec.get("scheduling_strategy") or {}
        return (strategy.get("labels") or {}).get("hard") or None

    def _peer_label_ok(self, spec: dict, view: dict) -> bool:
        hard = self._spec_hard_labels(spec)
        if not hard:
            return True
        from ray_tpu.util.scheduling_strategies import match_labels

        return match_labels(view.get("labels"), hard)

    async def _spill(self, spec: dict) -> bool:
        """Task infeasible on this node: find a feasible node and forward (spillback)."""
        demand = spec.get("resources") or {}
        for node_id, info in self.node_view.items():
            if node_id == self.node_id or not self._peer_label_ok(spec, info):
                continue
            if all(info["resources_total"].get(r, 0) >= amt for r, amt in demand.items()):
                if await self._forward_to_peer(spec, node_id):
                    return True
        return False  # keep queued; cluster may gain a node

    async def _maybe_spread(self, spec: dict) -> bool:
        demand = spec.get("resources") or {}
        if not demand:
            return False
        for node_id, info in self.node_view.items():
            if node_id == self.node_id or not self._peer_label_ok(spec, info):
                continue
            avail = info.get("resources_available", {})
            if all(avail.get(r, 0) >= amt for r, amt in demand.items()):
                if await self._forward_to_peer(spec, node_id):
                    return True
        return False

    async def _route_pg_task(self, spec: dict):
        """Off-loop placement-group routing: wait for the PG, then deliver the task to
        its bundle's node (or fail it if the PG can't be placed)."""
        pg = spec["placement_group"]
        idx = pg["bundle_index"]
        for _attempt in range(10):
            try:
                info = await self.gcs.call("pg_wait_ready", pg["pg_id"], 30.0)
            except rpc.RpcError:
                await asyncio.sleep(0.5)
                continue
            if info.get("state") == "DEAD":
                await self._fail_task(spec, "placement group could not be scheduled")
                return
            allocations = info.get("allocations") or []
            if idx >= len(allocations):
                await self._fail_task(spec, f"placement group has no bundle {idx}")
                return
            target = allocations[idx]
            if target is None:
                await asyncio.sleep(0.2)
                continue
            if target == self.node_id:
                # Bundle is (now) local: re-enter the normal queue.
                self.task_queue.append(spec)
                self._sched_wakeup.set()
                return
            if await self._forward_to_peer(spec, target):
                return
            await asyncio.sleep(0.2)
        await self._fail_task(spec, "placement group routing failed")

    # ------------------------------------------------------------------ RPC: workers

    async def rpc_register_worker(self, conn, worker_id: WorkerID, kind: str, pid: int,
                                  direct_port: int | None = None,
                                  direct_bind_host: str | None = None):
        handle = self.workers.get(worker_id)
        if handle is None:
            handle = WorkerHandle(worker_id, None, kind)
            self.workers[worker_id] = handle
        handle.conn = conn
        handle.kind = kind if handle.kind == "worker" and kind == "driver" else handle.kind
        if direct_port:
            # Advertise the node IP only when the worker's bind actually covers
            # it (raylet-spawned workers always do — they inherit
            # RAY_TPU_NODE_IP — but an externally-started driver may have bound
            # loopback while this raylet advertises a routable IP). A loopback
            # direct_addr stays correct for same-host peers; the GCS vets it
            # out of cross-host records.
            covers = direct_bind_host in (None, "0.0.0.0", self.node_ip)
            handle.direct_addr = (
                (self.node_ip, direct_port) if covers else ("127.0.0.1", direct_port)
            )
        handle.registered.set()
        conn.on_close(lambda c: self._on_worker_lost(handle))
        return {"node_id": self.node_id, "store_capacity": self.store.capacity,
                "node_ip": self.node_ip,
                # Native arenas support the workers' zero-RPC put/get fast path.
                "store_arena": getattr(self.store, "_arena_name", None)}

    async def rpc_submit_task(self, conn, spec: dict):
        self.task_queue.append(spec)
        self._sched_wakeup.set()
        return True

    async def rpc_task_done(self, conn, task_id, results: list, extra: dict | None = None,
                            resources_released=True):
        spec = self.running.pop(task_id, None)
        handle = None
        for w in self.workers.values():
            if w.busy_task is not None and w.busy_task["task_id"] == task_id:
                handle = w
                break
        if handle is not None:
            self.resources.release(handle.acquired, handle.pg_key)
            handle.acquired = {}
            handle.pg_key = None
            handle.busy_task = None
            handle.last_idle = time.monotonic()
            self._sched_wakeup.set()
        if spec is not None:
            await self._route_results_to_owner(spec, results, extra)
            await self._settle_delegation(spec)
        return True

    async def _route_results_to_owner(self, spec: dict, results: list,
                                      extra: dict | None = None):
        owner = spec["owner"]
        payload = {"task_id": spec["task_id"], "results": results, **(extra or {})}
        await self._route_to_worker(owner["node_id"], owner["worker_id"], "task_result", payload)

    async def _route_to_worker(self, node_id: NodeID, worker_id: WorkerID, method: str, payload):
        if node_id == self.node_id:
            handle = self.workers.get(worker_id)
            if handle is not None and handle.alive:
                try:
                    await handle.conn.notify(method, payload)
                except rpc.RpcError:
                    pass
            return
        peer = await self._peer(node_id)
        if peer is not None:
            try:
                await peer.notify("route", worker_id, method, payload)
            except rpc.RpcError:
                pass

    async def rpc_route(self, conn, worker_id: WorkerID, method: str, payload):
        handle = self.workers.get(worker_id)
        if handle is not None and handle.alive:
            try:
                await handle.conn.notify(method, payload)
            except rpc.RpcError:
                pass
        return True

    async def rpc_route_call(self, conn, worker_id: WorkerID, method: str, payload):
        """Routed request that needs an answer (e.g. inline-object fetch from owner)."""
        handle = self.workers.get(worker_id)
        if handle is None or not handle.alive:
            return {"error": "worker_not_found"}
        try:
            return await handle.conn.call(method, payload)
        except rpc.RpcError:
            return {"error": "worker_lost"}

    async def rpc_request_lease(self, conn, resources: dict, runtime_env=None,
                                owner_worker_id: WorkerID | None = None):
        """Grant a cached worker lease to a submitting worker.

        Reference: NormalTaskSubmitter's lease caching
        (task_submission/normal_task_submitter.h:81) — the owner holds the lease
        and pushes same-shape tasks straight to the worker, returning it when the
        local queue drains. The raylet only does resource accounting here; the
        per-task hot path never touches it.
        """
        from ray_tpu._private import runtime_env as runtime_env_mod

        demand = resources or {"CPU": 1}
        if not self.resources.feasible(demand, None):
            return {"ok": False, "infeasible": True}
        env_key = runtime_env_mod.env_key(runtime_env)
        python_exe = None
        if env_key is not None:
            try:
                python_exe, ready = self._resolve_env_python({"runtime_env": runtime_env})
            except RuntimeError as e:
                return {"ok": False, "error": str(e)}
            if not ready:
                return {"ok": False}
        if not self.resources.can_acquire(demand, None):
            return {"ok": False}
        worker = self._find_idle_worker(env_key)
        if worker is None or worker.direct_addr is None:
            self._maybe_spawn_worker(env_key=env_key, python_exe=python_exe)
            return {"ok": False}
        self.resources.acquire(demand, None)
        worker.acquired = demand
        worker.leased_to = owner_worker_id
        owner_hex = owner_worker_id.hex() if hasattr(owner_worker_id, "hex") else None
        worker.log_owner = owner_hex
        return {"ok": True, "worker_id": worker.worker_id,
                "direct_addr": worker.direct_addr}

    async def rpc_release_lease(self, conn, worker_id: WorkerID):
        handle = self.workers.get(worker_id)
        if handle is None or handle.leased_to is None:
            return False
        self.resources.release(handle.acquired, None)
        handle.acquired = {}
        handle.leased_to = None
        handle.log_owner = None
        handle.last_idle = time.monotonic()
        self._sched_wakeup.set()
        return True

    async def rpc_call_worker(self, conn, target: dict, method: str, payload):
        """Worker-to-worker request routed by address (e.g. borrower asking the
        owner to reconstruct a lost object)."""
        node_id, worker_id = target["node_id"], target["worker_id"]
        if node_id == self.node_id:
            return await self.rpc_route_call(conn, worker_id, method, payload)
        peer = await self._peer(node_id)
        if peer is None:
            return {"error": "node_unreachable"}
        try:
            return await peer.call("route_call", worker_id, method, payload)
        except rpc.RpcError:
            return {"error": "node_unreachable"}

    async def rpc_stream_item(self, conn, owner: dict, task_id, index: int, result: dict):
        """Route one streaming-task item to the owning worker."""
        await self._route_to_worker(
            owner["node_id"], owner["worker_id"], "stream_item",
            {"task_id": task_id, "index": index, "result": result},
        )
        return True

    async def rpc_stream_end(self, conn, owner: dict, task_id, count: int):
        await self._route_to_worker(
            owner["node_id"], owner["worker_id"], "stream_end",
            {"task_id": task_id, "count": count},
        )
        return True

    async def rpc_report_borrow(self, conn, object_id: ObjectID, owner: dict, delta: int,
                                borrower=None):
        """Forward a borrower's ref registration/release to the parent worker."""
        await self._route_to_worker(
            owner["node_id"], owner["worker_id"], "borrow_update",
            {"object_id": object_id, "delta": delta, "borrower": borrower},
        )
        return True

    async def rpc_check_borrows(self, conn, node_hex: str, worker_hex: str,
                                object_ids):
        """Borrow-audit holdings probe: ask the worker which of object_ids it
        still borrows. None = no verdict (unreachable); the audit must not
        reconcile on a maybe."""
        if node_hex == self.node_id.hex():
            for wid, handle in self.workers.items():
                if wid.hex() == worker_hex:
                    if not handle.alive:
                        return None
                    try:
                        return await handle.conn.call(
                            "borrow_check", {"object_ids": object_ids},
                            timeout=10.0,
                        )
                    except Exception:
                        return None  # worker unreachable != borrow released; audit treats as unknown
            return None
        target = None
        for nid in self.node_view:
            if nid.hex() == node_hex:
                target = nid
                break
        if target is None:
            return None
        peer = await self._peer(target)
        if peer is None:
            return None
        try:
            return await peer.call("check_borrows", node_hex, worker_hex,
                                   object_ids, timeout=15.0)
        except Exception:
            return None  # peer raylet unreachable: verdict unknown, not not-held

    async def rpc_check_worker_alive(self, conn, node_hex: str, worker_hex: str):
        """Borrow-audit probe: True = alive, False = CONFIRMED dead (its own
        raylet denies it, or the GCS marked its node dead), None = no verdict
        (unreachable/partitioned — the audit must not free on a maybe)."""
        if node_hex == self.node_id.hex():
            for wid, handle in self.workers.items():
                if wid.hex() == worker_hex:
                    return handle.alive
            return False  # our own table is authoritative for our node
        target = None
        for nid in self.node_view:
            if nid.hex() == node_hex:
                target = nid
                break
        if target is None:
            # Not in the live view: only a confirmed-dead record is a verdict.
            for nid, view in self._full_node_view.items():
                if nid.hex() == node_hex and not view.get("alive", True):
                    return False
            return None
        peer = await self._peer(target)
        if peer is None:
            return None  # dial failure != death
        try:
            return await peer.call("check_worker_alive", node_hex, worker_hex,
                                   timeout=5.0)
        except Exception:
            return None  # dial/call failure != death; only a definite answer counts

    # ------------------------------------------------------------------ RPC: object store

    def _queue_object_report(self, object_id: ObjectID, size: int, owner):
        self._obj_pending_report[object_id] = len(self._obj_ops)
        self._obj_ops.append(("report", object_id, self.node_id, size, owner))
        self._schedule_obj_flush()

    def _queue_object_free(self, object_id: ObjectID):
        idx = self._obj_pending_report.pop(object_id, None)
        if idx is not None and object_id not in self._obj_known:
            # Sealed and freed within one window AND never flushed before:
            # the GCS never knew — both ops cancel.
            self._obj_ops[idx] = None
            return
        self._obj_ops.append(("free", object_id))
        self._schedule_obj_flush()

    def _drain_obj_ops(self) -> list:
        ops = [op for op in self._obj_ops if op is not None]
        self._obj_ops.clear()
        self._obj_pending_report.clear()
        for op in ops:
            if op[0] == "report":
                self._obj_known.add(op[1])
            else:
                self._obj_known.discard(op[1])
        return ops

    def _schedule_obj_flush(self):
        if self._obj_flush_scheduled:
            return
        self._obj_flush_scheduled = True

        async def _flush():
            await asyncio.sleep(CONFIG.object_report_flush_s)
            self._obj_flush_scheduled = False
            ops = self._drain_obj_ops()
            if not ops:
                return
            try:
                await self.gcs.notify("object_ops_batch", ops)
            except Exception:
                # GCS down/reconnecting: sealed objects are re-reported by the
                # reconnect sync (sync_node_state); frees are best-effort.
                pass

        asyncio.get_running_loop().create_task(_flush())

    async def rpc_store_create(self, conn, object_id: ObjectID, size: int):
        # Off-loop: under memory pressure create() spills LRU objects to disk,
        # which must not stall scheduling/heartbeats/resolves on the event loop.
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(None, self.store.create, object_id, size)

    async def rpc_store_seal(self, conn, object_id: ObjectID, size: int, owner):
        self.store.seal(object_id)
        self._sealed_objects[object_id] = (size, owner)
        self._queue_object_report(object_id, size, owner)
        return True

    async def rpc_store_ops_batch(self, conn, ops: list):
        """Batched worker store bookkeeping for the zero-RPC direct-arena data
        plane: [("sealed", oid, size, owner) | ("free", oid)], in the order the
        worker performed them. The store itself needs no action for "sealed"
        (the worker sealed in shared memory); only location bookkeeping runs."""
        for op in ops:
            if op[0] == "sealed":
                _, object_id, size, owner = op
                self._sealed_objects[object_id] = (size, owner)
                self._queue_object_report(object_id, size, owner)
            else:
                _, object_id = op
                self.store.free(object_id, eager=True)
                self._sealed_objects.pop(object_id, None)
                self._queue_object_free(object_id)

    async def rpc_store_put_bytes(self, conn, object_id: ObjectID, data: bytes, owner):
        loop = asyncio.get_running_loop()
        name = await loop.run_in_executor(None, self.store.put_bytes, object_id, data)
        self._sealed_objects[object_id] = (len(data), owner)
        self._queue_object_report(object_id, len(data), owner)
        return name

    async def rpc_store_info(self, conn, object_id: ObjectID):
        return self.store.info(object_id)

    async def rpc_store_free(self, conn, object_id: ObjectID):
        # The owner's refcount hit zero: no ObjectRef exists anywhere, so the
        # payload can never be legally read again. Eager eviction returns the
        # block to the freelist immediately (reuse keeps put pages warm) —
        # pinned readers still defer the actual recycle to their release.
        self.store.free(object_id, eager=True)
        self._sealed_objects.pop(object_id, None)
        self._queue_object_free(object_id)
        return True

    async def rpc_evict_object(self, conn, object_id: ObjectID):
        self.store.free(object_id, eager=True)
        self._sealed_objects.pop(object_id, None)
        return True

    async def rpc_read_chunk(self, conn, object_id: ObjectID, offset: int, length: int):
        return self.store.read_bytes(object_id, offset, length)

    async def rpc_store_stats(self, conn):
        stats = self.store.stats()
        stats["pull_inflight_bytes"] = self.pull_manager.inflight_bytes
        return stats

    async def rpc_resolve_object(self, conn, object_id: ObjectID, owner=None, timeout: float = 300.0,
                                 priority: int = 1):
        """Ensure the object is readable on this node.

        Returns {"shm": (name, size)} for store objects or {"inline": bytes} fetched from
        the owner's in-process memory store. Reference: CoreWorker::Get's plasma-provider
        path + PullManager for remote objects.
        """
        deadline = time.monotonic() + timeout
        lost_polls = 0
        unknown_polls = 0
        while True:
            info = self.store.info(object_id)
            if info is not None:
                return {"shm": info}
            inflight = self._pulls_inflight.get(object_id)
            if inflight is not None:
                await inflight
                continue
            loc = None
            got_loc = False
            try:
                loc = await self.gcs.call("object_locations", object_id)
                got_loc = True
            except rpc.RpcError:
                pass
            if loc is not None and not loc["locations"]:
                # The directory knows this object but every node holding a copy is
                # gone: report it lost quickly so the owner can reconstruct from
                # lineage instead of burning the full resolve timeout. Two polls of
                # grace cover a copy in transit between seal and report.
                lost_polls += 1
                if lost_polls >= 2:
                    return {"error": "lost"}
            else:
                lost_polls = 0
            if got_loc and loc is None:
                # The directory has never heard of this object. Location reports
                # are batched, so a fresh seal can be unknown for a window — but
                # a persistently-unknown plasma object means its holder died
                # before its report flushed. Declare it lost so the owner can
                # rebuild from lineage instead of burning the resolve timeout.
                unknown_polls += 1
                if unknown_polls >= 25 and owner is not None:
                    return {"error": "lost"}
            else:
                unknown_polls = 0
            if loc and loc["locations"]:
                fut = asyncio.get_running_loop().create_future()
                self._pulls_inflight[object_id] = fut
                try:
                    ok = await self._pull_object(object_id, loc, priority)
                finally:
                    self._pulls_inflight.pop(object_id, None)
                    fut.set_result(None)
                if ok:
                    continue
            elif owner is not None:
                # Small object living in the owner's memory store.
                reply = await self._fetch_inline_from_owner(object_id, owner)
                if reply is not None:
                    return {"inline": reply}
            if time.monotonic() > deadline:
                return {"error": "timeout"}
            await asyncio.sleep(CONFIG.get_poll_interval_s * 10)

    async def _fetch_inline_from_owner(self, object_id: ObjectID, owner) -> bytes | None:
        node_id, worker_id = owner["node_id"], owner["worker_id"]
        payload = {"object_id": object_id}
        if node_id == self.node_id:
            handle = self.workers.get(worker_id)
            if handle is None or not handle.alive:
                return None
            try:
                reply = await handle.conn.call("fetch_inline", payload)
            except rpc.RpcError:
                return None
        else:
            peer = await self._peer(node_id)
            if peer is None:
                return None
            try:
                reply = await peer.call("route_call", worker_id, "fetch_inline", payload)
            except rpc.RpcError:
                return None
        if isinstance(reply, dict) and reply.get("data") is not None:
            return reply["data"]
        return None

    async def _pull_object(self, object_id: ObjectID, loc: dict,
                           priority: int = 1) -> bool:
        """Pull a remote object under the pull manager's byte budget
        (reference: pull_manager.h:49 — prioritized admission with in-flight
        byte caps so a burst of large pulls cannot exhaust the store)."""
        await self.pull_manager.admit(object_id, loc["size"], priority)
        try:
            return await self._pull_object_now(object_id, loc)
        finally:
            self.pull_manager.release(object_id, loc["size"])

    async def _pull_object_now(self, object_id: ObjectID, loc: dict) -> bool:
        """Chunked-parallel pull from a remote node (reference: PullManager +
        ObjectBufferPool chunked receives). A window of pipelined read_chunk
        requests keeps the wire full instead of paying one RTT per chunk."""
        size = loc["size"]
        for location in loc["locations"]:
            if location["node_id"] == self.node_id:
                continue
            peer = await self._peer(location["node_id"])
            if peer is None:
                continue
            try:
                shm_name = self.store.create(object_id, size)
                from ray_tpu._private.object_store import LocalObjectReader

                chunk = CONFIG.object_store_min_chunk_bytes
                window = max(1, CONFIG.pull_chunk_window)
                reader = LocalObjectReader()
                try:
                    # write_view, NOT read(): this buffer receives the pulled
                    # chunks. read() takes a pinned READ view, which degrades
                    # to a read-only copy on Python < 3.12 — writes would
                    # TypeError (and silently vanish if they didn't).
                    buf = reader.write_view(shm_name, size)
                    sem = asyncio.Semaphore(window)

                    async def fetch(off: int):
                        ln = min(chunk, size - off)
                        async with sem:
                            data = await peer.call("read_chunk", object_id, off, ln)
                        if not data or len(data) != ln:
                            raise IOError(
                                f"short chunk at {off}: {0 if not data else len(data)}"
                                f"/{ln} of {object_id}"
                            )
                        buf[off : off + ln] = data

                    # return_exceptions: every fetch settles before this line
                    # passes, so a failed attempt never leaves orphan tasks
                    # writing into the buffer during the next location's retry.
                    results = await asyncio.gather(
                        *[fetch(o) for o in range(0, size, chunk)],
                        return_exceptions=True,
                    )
                    errs = [r for r in results if isinstance(r, BaseException)]
                    if errs:
                        raise errs[0]
                    del buf
                finally:
                    reader.close()
                self.store.seal(object_id)
                self._sealed_objects[object_id] = (size, loc.get("owner"))
                self._queue_object_report(object_id, size, loc.get("owner"))
                return True
            except Exception:
                traceback.print_exc()
                self.store.free(object_id, eager=True)
        return False

    # ------------------------------------------------------------------ RPC: actors

    async def rpc_create_actor(self, conn, actor_id: ActorID, spec: dict):
        """From GCS: lease a dedicated worker and instantiate the actor."""
        from ray_tpu._private import runtime_env as runtime_env_mod

        demand = dict(spec.get("resources") or {})
        pg_key = self._pg_key(spec)
        # pip runtime env: the actor's worker must run inside the env's venv.
        # Routed through the same single-flight builder as tasks so concurrent
        # creations of the same env never race one cache directory.
        python_exe = None
        if runtime_env_mod.env_key(spec.get("runtime_env")) is not None:
            deadline = time.monotonic() + 600
            while True:
                try:
                    python_exe, ready = self._resolve_env_python(spec)
                except RuntimeError as e:
                    return {"ok": False, "reason": f"runtime_env failed: {e}",
                            "fatal": True}
                if ready:
                    break
                if time.monotonic() > deadline:
                    return {"ok": False, "reason": "runtime_env build timed out",
                            "fatal": True}
                await asyncio.sleep(0.25)
        if not self.resources.acquire(demand, pg_key):
            return {"ok": False, "reason": "resources"}

        async def cleanup(handle):
            # Detach bookkeeping BEFORE killing so _on_worker_lost (conn-close
            # callback) neither double-releases nor reports a spurious actor death.
            handle.acquired = {}
            handle.pg_key = None
            handle.actor_id = None
            self.resources.release(demand, pg_key)
            await self._kill_worker(handle)

        handle = self._spawn_worker(
            kind="actor", python_exe=python_exe,
            env_key=runtime_env_mod.env_key(spec.get("runtime_env")),
        )
        try:
            await asyncio.wait_for(handle.registered.wait(), CONFIG.worker_register_timeout_s)
        except asyncio.TimeoutError:
            # Kill first so _death_cause sees the exit status immediately
            # instead of polling a still-live process for its full wait.
            await cleanup(handle)
            reason = await self._death_cause(handle, "actor worker failed to register")
            return {"ok": False, "reason": reason}
        handle.acquired = demand
        handle.pg_key = pg_key
        try:
            result = await handle.conn.call("init_actor", actor_id, spec, timeout=300)
        except rpc.RpcError as e:
            await cleanup(handle)
            reason = await self._death_cause(handle, f"worker died during init: {e}")
            return {"ok": False, "reason": reason}
        if not result.get("ok"):
            await cleanup(handle)
            # Application error in __init__: retrying cannot help.
            return {"ok": False, "reason": result.get("error", "init failed"), "fatal": True}
        handle.actor_id = actor_id
        if (self._cgroup is not None and handle.proc is not None
                and demand.get("memory")
                and not (spec.get("runtime_env") or {}).get("image_uri")):
            # A declared memory resource becomes a hard per-worker memory.max
            # (native workers only: for containers, proc is the engine CLI).
            self._cgroup.place_worker(handle.proc.pid,
                                      memory_bytes=int(demand["memory"]))
        owner_wid = (spec.get("owner") or {}).get("worker_id")
        handle.log_owner = owner_wid.hex() if hasattr(owner_wid, "hex") else None
        self.actors[actor_id] = handle.worker_id
        return {"ok": True, "worker_id": handle.worker_id,
                "direct_addr": handle.direct_addr}

    async def rpc_submit_actor_task(self, conn, spec: dict):
        """Route an actor method call to the actor's host node/worker."""
        actor_id = spec["actor_id"]
        worker_id = self.actors.get(actor_id)
        if worker_id is not None:
            handle = self.workers.get(worker_id)
            if handle is not None and handle.alive:
                handle.inflight_actor_tasks[spec["task_id"]] = spec
                await handle.conn.notify("push_task", spec)
                return True
            # Actor worker died; report and fall through to error.
            await self._report_actor_failure(actor_id, "actor worker dead at submit")
            await self._fail_actor_task(spec, "actor worker died")
            return False
        addr = await self._actor_address(actor_id)
        if addr is None:
            reason = "actor not found or dead"
            try:  # surface the GCS-recorded death cause, not a bare "dead"
                info = await self.gcs.call("get_actor_info", actor_id)
                if info is not None and info.get("death_cause"):
                    reason = f"actor is dead: {info['death_cause']}"
            except rpc.RpcError:
                pass
            await self._fail_actor_task(spec, reason)
            return False
        if addr["node_id"] == self.node_id:
            handle = self.workers.get(addr["worker_id"])
            if handle is not None and handle.alive:
                handle.inflight_actor_tasks[spec["task_id"]] = spec
                await handle.conn.notify("push_task", spec)
                return True
            await self._fail_actor_task(spec, "actor worker dead")
            return False
        if not await self._forward_to_peer(spec, addr["node_id"], "submit_actor_task"):
            await self._fail_actor_task(spec, "actor node unreachable")
            return False
        return True

    async def _actor_address(self, actor_id: ActorID):
        cached = self.actor_addr_cache.get(actor_id)
        if cached is not None:
            return cached
        info = None
        for _attempt in range(20):  # survive a GCS restart mid-lookup
            try:
                info = await self.gcs.call("wait_actor_alive", actor_id, 60.0)
                break
            except rpc.ConnectionLost:
                await asyncio.sleep(0.5)
            except rpc.RpcError:
                return None
        if info is None:
            return None
        if info is None or info["state"] != "ALIVE":
            return None
        self.actor_addr_cache[actor_id] = info["address"]
        return info["address"]

    async def _fail_actor_task(self, spec: dict, reason: str):
        from ray_tpu._private import serialization
        from ray_tpu.exceptions import ActorDiedError

        err = serialization.dumps(ActorDiedError(spec.get("actor_id"), reason))
        results = [
            {"object_id": oid, "inline": err, "error": True} for oid in spec["return_ids"]
        ]
        await self._route_results_to_owner(spec, results)
        if spec.get("num_returns") == "streaming":
            owner = spec["owner"]
            await self._route_to_worker(
                owner["node_id"], owner["worker_id"], "stream_abort",
                {"task_id": spec["task_id"], "reason": reason},
            )
        await self._settle_delegation(spec)

    async def rpc_actor_task_done(self, conn, spec_owner, task_id, results,
                                  extra: dict | None = None):
        """Actor worker finished a method call; route results to owner."""
        spec = None
        for w in self.workers.values():
            if w.conn is conn:
                spec = w.inflight_actor_tasks.pop(task_id, None)
                break
        await self._route_to_worker(
            spec_owner["node_id"],
            spec_owner["worker_id"],
            "task_result",
            {"task_id": task_id, "results": results, **(extra or {})},
        )
        if spec is not None:
            await self._settle_delegation(spec)
        return True

    async def rpc_kill_actor_worker(self, conn, actor_id: ActorID):
        worker_id = self.actors.pop(actor_id, None)
        if worker_id is None:
            return False
        handle = self.workers.get(worker_id)
        if handle is not None:
            self.resources.release(handle.acquired, handle.pg_key)
            handle.acquired = {}
            handle.pg_key = None
            handle.actor_id = None
            await self._kill_worker(handle)
        return True

    async def rpc_invalidate_actor_cache(self, conn, actor_id: ActorID):
        self.actor_addr_cache.pop(actor_id, None)
        return True

    # ------------------------------------------------------------------ RPC: bundles

    async def rpc_reserve_bundle(self, conn, pg_id, bundle_index, resources):
        return self.resources.reserve_bundle((pg_id, bundle_index), resources)

    async def rpc_cancel_bundle(self, conn, pg_id, bundle_index):
        self.resources.cancel_bundle((pg_id, bundle_index))
        return True

    # ------------------------------------------------------------------ RPC: misc

    async def rpc_publish(self, conn, channel, message):
        """Pubsub fan-in from GCS: actor restarts/deaths and node membership."""
        if channel == "actors":
            view = message.get("actor", {})
            actor_id = view.get("actor_id")
            if actor_id is not None:
                if view.get("state") == "ALIVE" and view.get("address"):
                    self.actor_addr_cache[actor_id] = view["address"]
                else:
                    self.actor_addr_cache.pop(actor_id, None)
        elif channel == "nodes" and message.get("event") == "removed":
            node_id = message["node"]["node_id"]
            self.node_view.pop(node_id, None)
            conn_dead = self.peer_conns.pop(node_id, None)
            if conn_dead is not None:
                await conn_dead.close()
            await self._recover_delegated(node_id)
        return True

    async def rpc_node_stats(self, conn):
        return {
            "node_id": self.node_id,
            "resources_total": self.resources.total,
            "resources_available": self.resources.available,
            "num_workers": len(self.workers),
            "queued_tasks": len(self.task_queue),
            "running_tasks": len(self.running),
            "store": self.store.stats(),
            # Who holds what: the first question of every "why is this node
            # full" investigation (reference: node manager debug state dump).
            "resource_holders": [
                {
                    "worker_id": h.worker_id.hex()[:12],
                    "kind": h.kind,
                    "actor_id": h.actor_id.hex()[:12] if h.actor_id else None,
                    "leased": h.leased_to is not None,
                    "acquired": dict(h.acquired),
                    "pg_key": repr(h.pg_key) if h.pg_key else None,
                }
                for h in self.workers.values() if h.acquired
            ],
            "pg_bundles": {
                repr(k): v["reserved"] for k, v in self.resources.bundles.items()
            },
        }

    async def shutdown(self):
        self._shutdown = True
        # Flush batched object-directory traffic: a clean shutdown must not
        # strand seals/frees in the window (holders that die unreported are
        # covered by the resolve path's unknown-object lost detection).
        ops = self._drain_obj_ops()
        if ops:
            try:
                await self.gcs.notify("object_ops_batch", ops)
            except Exception:
                pass  # GCS down: ops re-drain after the reconnect path replays
        for handle in list(self.workers.values()):
            if handle.kind != "driver":
                await self._kill_worker(handle)
        if self.server is not None:
            await self.server.close()
        self.store.destroy()
        if self._cgroup is not None:
            self._cgroup.teardown()
