"""Replicated GCS: lease-based quorum HA across head candidates.

Design role: the reference Ray outsources GCS durability to an external Redis
(`redis_store_client.h:126`) and treats head loss as restart-recovery; this
framework has no Redis, so the `gcs_store.FileStoreClient` append log becomes
its own replicated store (docs/fault_tolerance.md §replicated GCS):

- `gcs_replicas` head **candidates** each run this module over their own
  `ReplicatedFileStore`. Exactly one is **primary** at a time; the rest are
  warm standbys whose stores track the primary's log record-for-record.
- The primary streams every durable mutation `(op, table, key, value)` to the
  followers and acks a client mutation only after a **majority** of
  candidates (itself included) has flushed it. No full Raft: a single
  epoch-fenced leader lease over a replicated log is enough for a control
  plane whose live state (nodes, object locations) is re-reported by raylets
  anyway.
- The primary holds a time-bounded **lease** renewed through the same quorum
  (renew every lease_s/3; stop serving when a majority hasn't confirmed
  within lease_s). On lease expiry a follower elects itself at a higher
  epoch; grants require the requester to be at least as caught up as the
  grantor, so only a most-caught-up follower can win.
- **Epoch fencing**: every replication RPC carries the sender's epoch;
  candidates reject anything below their highest promised epoch, so a
  deposed primary's stragglers bounce and the deposed primary demotes. A
  rejoining candidate is resynced from the new primary's snapshot, which
  truncates any unacked tail it accumulated while deposed.
- Clients never see any of this beyond `rpc.NotPrimaryError` (a redirect
  carrying the current primary's address) and multi-address candidate lists:
  `gcs_call`/raylet reconnect machinery probes `repl_status` and retries
  idempotent calls against the new primary exactly like today's
  restart-reconnect path.

With `gcs_replicas=1` none of this is instantiated — `gcs_main` runs the
classic single `GcsService` and behavior is byte-for-byte the old one.
"""

from __future__ import annotations

import asyncio
import logging
import random
import time
from collections import deque
from typing import Any, Callable, Optional

from ray_tpu._private import rpc
from ray_tpu._private.config import CONFIG
from ray_tpu._private.gcs_store import FileStoreClient

logger = logging.getLogger(__name__)

#: Internal store table carrying the replication position; rides the same
#: append log as the data it describes, so compaction (which rewrites every
#: live key) keeps the (epoch, seq) stamp consistent with the tables — an
#: epoch-stamped compacted log still knows exactly where it stands.
_REPL_TABLE = "_repl"
_STATE_KEY = "state"

#: Records the primary retains in memory for incremental follower catch-up;
#: a follower further behind than this is resynced from a full snapshot.
_REPL_RING = 50000


def parse_addrs(spec) -> list:
    """Normalize an address spec — "h:p,h:p", (h, p), or a list of either —
    into a list of (host, port) tuples."""
    if spec is None:
        return []
    if isinstance(spec, str):
        out = []
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            host, port = part.rsplit(":", 1)
            out.append((host, int(port)))
        return out
    spec = list(spec)
    if not spec:
        return []
    if isinstance(spec[0], (list, tuple)):
        return [(a[0], int(a[1])) for a in spec]
    return [(spec[0], int(spec[1]))]


def format_addrs(addrs) -> str:
    return ",".join(f"{h}:{p}" for h, p in parse_addrs(addrs))


def probe_status(addr, timeout: float = 2.0) -> Optional[dict]:
    """Synchronous repl_status probe of one candidate (driver/test helper);
    None when the candidate is unreachable."""

    async def _probe():
        conn = await rpc.connect(addr[0], addr[1], name="gcs-probe",
                                 timeout=timeout)
        try:
            return await asyncio.wait_for(conn.call("repl_status"), timeout)
        finally:
            await conn.close()

    try:
        return asyncio.run(_probe())
    except Exception:
        return None


class ReplicatedFileStore(FileStoreClient):
    """A FileStoreClient that knows its replication position.

    Every mutation also persists the ("_repl", "state") row carrying
    (epoch, seq, promised), so crash recovery and compaction restore the
    coordinates together with the data. Two mutation paths:

    - primary-originated `put`/`delete`: assign the next seq, persist, and
      hand (seq, record) to the candidate's replication fan-out. When no
      fan-out callback is installed (this candidate is NOT primary) the write
      is dropped — that is the local half of epoch fencing: a zombie
      GcsService task on a deposed candidate cannot diverge the follower log.
    - follower `apply_replicated`: adopt the primary's (epoch, seq) verbatim.
    """

    def __init__(self, store_dir: str):
        super().__init__(store_dir)
        self.epoch = 0      # epoch of the primary whose records we hold
        self.seq = 0        # last applied/assigned replicated mutation
        self.promised = 0   # highest epoch this candidate granted a lease for
        self._mutation_cb: Optional[Callable] = None  # primary fan-out hook

    def load(self):
        super().load()
        st = self.get(_REPL_TABLE, _STATE_KEY)
        if st:
            self.epoch = int(st.get("epoch", 0))
            self.seq = int(st.get("seq", 0))
            self.promised = int(st.get("promised", 0))

    def _persist_state(self):
        FileStoreClient.put(self, _REPL_TABLE, _STATE_KEY, {
            "epoch": self.epoch, "seq": self.seq, "promised": self.promised,
        })

    def grant(self, epoch: int):
        """Persist a lease promise BEFORE replying to the requester: a
        granted-then-forgotten promise could elect two primaries."""
        if epoch > self.promised:
            self.promised = epoch
            self._persist_state()

    # ------------------------------------------------- primary-originated
    def put(self, table: str, key, value):
        if table == _REPL_TABLE:
            FileStoreClient.put(self, table, key, value)
            return
        if self._mutation_cb is None:
            return  # fenced: only the primary image originates mutations
        self.seq += 1
        FileStoreClient.put(self, table, key, value)
        self._persist_state()
        self._mutation_cb(self.seq, ("put", table, key, value))

    def delete(self, table: str, key):
        if table == _REPL_TABLE:
            FileStoreClient.delete(self, table, key)
            return
        if self._mutation_cb is None:
            return
        self.seq += 1
        FileStoreClient.delete(self, table, key)
        self._persist_state()
        self._mutation_cb(self.seq, ("del", table, key, None))

    # ------------------------------------------------------- follower apply
    def apply_replicated(self, epoch: int, seq: int, record):
        op, table, key, value = record
        if op == "put":
            FileStoreClient.put(self, table, key, value)
        else:
            FileStoreClient.delete(self, table, key)
        self.epoch = epoch
        self.seq = seq
        self._persist_state()

    def snapshot(self) -> dict:
        """Live-table image for follower resync (the replication coordinates
        travel beside it, not inside it)."""
        with self._lock:
            return {t: dict(kv) for t, kv in self._tables.items()
                    if t != _REPL_TABLE}

    def reset_from_snapshot(self, tables: dict, epoch: int, seq: int):
        """Adopt the primary's image wholesale. This is where a deposed
        primary's unacked tail is truncated away: the snapshot IS the quorum
        state, and the local log is rewritten (compaction-style) to match."""
        with self._lock:
            self._tables = {t: dict(kv) for t, kv in tables.items()}
            self.epoch = int(epoch)
            self.seq = int(seq)
            self._tables[_REPL_TABLE] = {_STATE_KEY: {
                "epoch": self.epoch, "seq": self.seq,
                "promised": self.promised,
            }}
            if self._log is not None:
                self._compact_locked()


class PeerLink:
    """A primary->follower replication connection. Explicit acquire/release
    pair (leaklint: `open_peer` -> `close`): a deposed primary that failed to
    close its links would keep streaming stale-epoch appends at live
    followers forever."""

    def __init__(self, addr, conn: rpc.Connection):
        self.addr = tuple(addr)
        self.conn = conn
        from ray_tpu.devtools import leaksan

        leaksan.track("gcs_repl_peer", self, detail=f"peer {self.addr}")

    async def close(self):
        from ray_tpu.devtools import leaksan

        leaksan.untrack("gcs_repl_peer", self)
        if self.conn is not None and not self.conn.closed:
            try:
                await self.conn.close()
            except Exception:
                logger.debug("peer link close failed", exc_info=True)


class LeaseToken:
    """The primary lease as an explicit handle (leaklint: `acquire_lease` ->
    `release`): promotion acquires it, demotion MUST release it — a candidate
    that kept serving on a released lease would split-brain the cluster."""

    def __init__(self, epoch: int):
        self.epoch = epoch
        self.released = False
        from ray_tpu.devtools import leaksan

        leaksan.track("gcs_lease", self, detail=f"epoch {epoch}")

    def release(self):
        if not self.released:
            self.released = True
            from ray_tpu.devtools import leaksan

            leaksan.untrack("gcs_lease", self)


class _CandidateFacade:
    """Per-connection RPC handler: replication RPCs (rpc_repl_*, plus the
    role-agnostic status/stats endpoints) are served in any role; everything
    else is a client call, answered by the primary's GcsService or with a
    NOT_PRIMARY redirect."""

    def __init__(self, cand: "GcsCandidate"):
        self._cand = cand

    def __getattr__(self, name: str):
        if not name.startswith("rpc_"):
            raise AttributeError(name)
        if getattr(type(self._cand), name, None) is not None:
            return getattr(self._cand, name)
        cand = self._cand

        async def _serve(conn, *args, **kwargs):
            return await cand.serve_client(conn, name[4:], args, kwargs)

        return _serve


class GcsCandidate:
    """One GCS head candidate: follower by default, primary while it holds
    the quorum lease. See the module docstring for the protocol."""

    def __init__(self, candidate_id: int, peers, store_dir: str,
                 lease_s: float | None = None,
                 quorum_timeout_s: float | None = None):
        self.candidate_id = int(candidate_id)
        self.peers = parse_addrs(peers)
        self.addr = self.peers[self.candidate_id]
        self.lease_s = float(lease_s if lease_s is not None
                             else CONFIG.gcs_lease_s)
        self.quorum_timeout_s = float(
            quorum_timeout_s if quorum_timeout_s is not None
            else CONFIG.gcs_quorum_timeout_s)
        self.store = ReplicatedFileStore(store_dir)
        self.store.load()
        self.role = "follower"
        self.gcs = None  # GcsService while primary
        self.server: rpc.RpcServer | None = None
        self.failovers = 0  # promotions past the cluster's first election
        self._lease: LeaseToken | None = None
        self._primary_hint: Optional[tuple] = None
        # follower: primary silence past this -> start an election. Staggered
        # by candidate id so concurrent expiries don't split the vote.
        self._lease_deadline = (
            time.monotonic() + 0.25 * self.lease_s * self.candidate_id
        )
        # primary: serving allowed while a majority confirmed us this recently
        self._peer_renewed: dict[int, float] = {}
        self._lease_ok_until = 0.0
        self._links: dict[int, PeerLink] = {}
        self._peer_acked: dict[int, int] = {}
        self._repl_log: deque = deque(maxlen=_REPL_RING)  # (seq, record)
        self._send_events: dict[int, asyncio.Event] = {}
        self._commit_waiters: list = []  # (seq, future)
        self._sender_tasks: dict[int, asyncio.Task] = {}
        self._renew_task: asyncio.Task | None = None
        self._election_task: asyncio.Task | None = None
        self._demoting = False
        self._stopping = False

    # ------------------------------------------------------------- helpers

    @property
    def _majority(self) -> int:
        return len(self.peers) // 2 + 1

    def _other_ids(self):
        return [i for i in range(len(self.peers)) if i != self.candidate_id]

    def facade(self, conn) -> _CandidateFacade:
        return _CandidateFacade(self)

    def start_background(self):
        loop = asyncio.get_running_loop()
        self._election_task = loop.create_task(self._election_loop())

    def repl_lag(self) -> dict:
        """Per-peer records behind the primary's log head (primary only)."""
        if self.role != "primary":
            return {}
        return {str(i): max(0, self.store.seq - self._peer_acked.get(i, 0))
                for i in self._other_ids()}

    def status_view(self) -> dict:
        return {
            "role": self.role,
            "epoch": self.store.epoch,
            "seq": self.store.seq,
            "promised": self.store.promised,
            "candidate_id": self.candidate_id,
            "replicas": len(self.peers),
            "primary": (tuple(self.addr) if self.role == "primary"
                        else self._primary_hint),
            "failovers": self.failovers,
        }

    # ------------------------------------------------------- client serving

    async def serve_client(self, conn, method: str, args, kwargs):
        if self.role == "primary" and time.monotonic() > self._lease_ok_until:
            # Can't prove a majority still honors us: stop serving rather
            # than hand out possibly-stale reads beside a promoted follower.
            await self._demote("lease lapsed without quorum confirmation")
        gcs = self.gcs
        if self.role != "primary" or gcs is None:
            raise rpc.NotPrimaryError(self._primary_hint)
        fn = getattr(gcs, "rpc_" + method, None)
        if fn is None:
            raise rpc.RpcError(
                f"GcsService has no method {method!r}")
        start_seq = self.store.seq
        result = fn(conn, *args, **kwargs)
        if asyncio.iscoroutine(result):
            result = await result
        if self.store.seq > start_seq:
            # Majority-ack before the client sees success: an acked mutation
            # survives any single candidate's loss.
            await self._wait_committed(self.store.seq)
        return result

    def _committed_seq(self) -> int:
        acked = sorted(
            [self.store.seq] + [self._peer_acked.get(i, 0)
                                for i in self._other_ids()],
            reverse=True,
        )
        return acked[self._majority - 1]

    async def _wait_committed(self, seq: int):
        if self._committed_seq() >= seq:
            return
        fut = asyncio.get_running_loop().create_future()
        self._commit_waiters.append((seq, fut))
        try:
            await asyncio.wait_for(fut, self.quorum_timeout_s)
        except asyncio.TimeoutError:
            await self._demote("quorum ack timeout")
            raise rpc.NotPrimaryError(None)

    def _resolve_commit_waiters(self):
        if not self._commit_waiters:
            return
        committed = self._committed_seq()
        keep = []
        for seq, fut in self._commit_waiters:
            if seq <= committed:
                if not fut.done():
                    fut.set_result(None)
            else:
                keep.append((seq, fut))
        self._commit_waiters = keep

    def _note_peer_alive(self, idx: int):
        """A follower acked traffic at our epoch: it still honors the lease.
        The lease is valid while the majority-th freshest confirmation is
        within lease_s."""
        now = time.monotonic()
        self._peer_renewed[idx] = now
        times = sorted(
            [now] + [self._peer_renewed.get(i, 0.0)
                     for i in self._other_ids()],
            reverse=True,
        )
        self._lease_ok_until = times[self._majority - 1] + self.lease_s

    def _on_local_mutation(self, seq: int, record):
        self._repl_log.append((seq, record))
        for ev in self._send_events.values():
            ev.set()

    # ------------------------------------------------------- replication RPC

    async def rpc_repl_status(self, conn):
        view = self.status_view()
        view["store"] = self.store.stats_view()
        view["lag"] = self.repl_lag()
        return view

    async def rpc_store_stats(self, conn):
        """Report path for observability (docs/raylint.md leaksan lesson:
        metrics objects live driver-side in control_plane_stats(), never in
        this process's append/replication paths)."""
        return {"store": self.store.stats_view(), "repl": {
            **self.status_view(), "lag": self.repl_lag(),
        }}

    async def rpc_repl_request_lease(self, conn, epoch: int, last_seq: int,
                                     candidate_id: int):
        if epoch <= max(self.store.promised,
                        self.store.epoch if self.role == "primary" else 0):
            return {"granted": False, "promised": self.store.promised,
                    "seq": self.store.seq}
        if last_seq < self.store.seq:
            # Most-caught-up rule: never grant to a candidate that would
            # lose acked records we hold.
            return {"granted": False, "promised": self.store.promised,
                    "seq": self.store.seq, "behind": True}
        if self.role == "primary":
            # A higher-epoch candidate with our full log asked while we
            # could not renew: step down before granting.
            await self._demote(f"deposed by lease request at epoch {epoch}")
        self.store.grant(epoch)
        self._primary_hint = tuple(self.peers[candidate_id])
        self._lease_deadline = time.monotonic() + self.lease_s
        return {"granted": True, "seq": self.store.seq}

    async def rpc_repl_sync(self, conn, epoch: int, seq: int, tables: dict,
                            candidate_id: int):
        if epoch < self.store.promised:
            return {"ok": False, "promised": self.store.promised}
        if self.role == "primary":
            if epoch <= self.store.epoch:
                return {"ok": False, "promised": self.store.epoch}
            await self._demote(f"snapshot from higher-epoch primary {epoch}")
        self.store.grant(epoch)
        self.store.reset_from_snapshot(tables, epoch, seq)
        self._primary_hint = tuple(self.peers[candidate_id])
        self._lease_deadline = time.monotonic() + self.lease_s
        return {"ok": True, "seq": self.store.seq}

    async def rpc_repl_append(self, conn, epoch: int, batch: list,
                              candidate_id: int | None = None):
        if epoch < self.store.promised or (
                self.role == "primary" and epoch <= self.store.epoch):
            # Epoch fencing: a deposed primary's straggler lands here.
            return {"ok": False,
                    "promised": max(self.store.promised, self.store.epoch)}
        if self.role == "primary":
            await self._demote(f"appends from higher-epoch primary {epoch}")
        self.store.grant(epoch)
        for seq, record in batch:
            if seq <= self.store.seq:
                continue  # duplicate delivery after a sender retry
            if seq != self.store.seq + 1:
                return {"ok": False, "resync": True, "seq": self.store.seq}
            self.store.apply_replicated(epoch, seq, record)
        if candidate_id is not None:
            self._primary_hint = tuple(self.peers[candidate_id])
        self._lease_deadline = time.monotonic() + self.lease_s
        return {"ok": True, "seq": self.store.seq}

    async def rpc_repl_renew(self, conn, epoch: int, candidate_id: int):
        if epoch < self.store.promised or (
                self.role == "primary" and epoch <= self.store.epoch):
            return {"ok": False,
                    "promised": max(self.store.promised, self.store.epoch)}
        if self.role == "primary":
            await self._demote(f"renewal from higher-epoch primary {epoch}")
        self.store.grant(epoch)
        self._primary_hint = tuple(self.peers[candidate_id])
        self._lease_deadline = time.monotonic() + self.lease_s
        return {"ok": True, "seq": self.store.seq}

    # ----------------------------------------------------- election / lease

    async def _election_loop(self):
        while not self._stopping:
            await asyncio.sleep(min(0.05, self.lease_s / 10))
            if self.role != "follower" or self._stopping:
                continue
            if time.monotonic() < self._lease_deadline:
                continue
            try:
                await self._try_elect()
            except Exception:
                logger.exception("gcs candidate %d: election attempt failed",
                                 self.candidate_id)
            if self.role != "primary":
                # Lost (or aborted): back off with jitter + id stagger so
                # concurrent candidates interleave instead of colliding.
                self._lease_deadline = time.monotonic() + self.lease_s * (
                    random.uniform(0.2, 0.5) + 0.15 * self.candidate_id
                )

    async def _try_elect(self):
        epoch = max(self.store.promised, self.store.epoch) + 1
        self.store.grant(epoch)  # our own vote, persisted first

        async def ask(idx):
            try:
                conn = await rpc.connect(
                    *self.peers[idx], timeout=2.0,
                    name=f"gcs-cand{self.candidate_id}->elect{idx}",
                )
                try:
                    return await asyncio.wait_for(
                        conn.call("repl_request_lease", epoch,
                                  self.store.seq, self.candidate_id),
                        2.0,
                    )
                finally:
                    await conn.close()
            except Exception:
                return None  # unreachable peer: no vote either way

        replies = await asyncio.gather(*(ask(i) for i in self._other_ids()))
        grants = 1 + sum(1 for r in replies if r and r.get("granted"))
        if (grants >= self._majority and self.role == "follower"
                and self.store.promised == epoch and not self._stopping):
            await self._promote(epoch)

    async def _promote(self, epoch: int):
        logger.warning("gcs candidate %d: promoting to primary at epoch %d "
                       "(seq %d)", self.candidate_id, epoch, self.store.seq)
        self.store.epoch = epoch
        self.store._persist_state()
        self.role = "primary"
        self._demoting = False
        self._primary_hint = tuple(self.addr)
        self._lease = self.acquire_lease(epoch)
        if epoch > 1:
            self.failovers += 1
        self._peer_acked = {}
        self._peer_renewed = {}
        self._repl_log.clear()
        self._commit_waiters = []
        self.store._mutation_cb = self._on_local_mutation
        self._lease_ok_until = time.monotonic() + self.lease_s
        # Warm standby -> serving image: the store's tables are already
        # replayed, so building the GcsService is cheap; live state (nodes,
        # actor addresses, object locations) arrives via raylet
        # re-registration exactly like the restart-recovery path.
        from ray_tpu._private.gcs import GcsService

        self.gcs = GcsService(store=self.store)
        self.gcs.start_background()
        loop = asyncio.get_running_loop()
        for idx in self._other_ids():
            self._send_events[idx] = asyncio.Event()
            self._sender_tasks[idx] = loop.create_task(self._sender(idx))
        self._renew_task = loop.create_task(self._renew_loop())

    def acquire_lease(self, epoch: int) -> LeaseToken:
        return LeaseToken(epoch)

    async def _demote(self, reason: str):
        if self.role != "primary" or self._demoting:
            return
        self._demoting = True
        logger.warning("gcs candidate %d: demoting (epoch %d): %s",
                       self.candidate_id, self.store.epoch, reason)
        self.role = "follower"
        self.store._mutation_cb = None
        for task in list(self._sender_tasks.values()):
            task.cancel()
        self._sender_tasks.clear()
        if self._renew_task is not None:
            self._renew_task.cancel()
            self._renew_task = None
        for link in list(self._links.values()):
            await link.close()
        self._links.clear()
        self._send_events.clear()
        if self._lease is not None:
            self._lease.release()
            self._lease = None
        gcs, self.gcs = self.gcs, None
        if gcs is not None and gcs._death_task is not None:
            gcs._death_task.cancel()
        for seq, fut in self._commit_waiters:
            if not fut.done():
                fut.set_exception(rpc.NotPrimaryError(None))
        self._commit_waiters = []
        # Full silence window before this candidate may re-elect itself.
        self._lease_deadline = time.monotonic() + self.lease_s
        self._demoting = False
        # Kick clients off the deposed endpoint so every one re-discovers the
        # primary through its reconnect path. Deferred so an in-flight
        # replication reply (the very RPC that deposed us) can still go out.
        if self.server is not None:
            asyncio.get_running_loop().create_task(self._kick_clients())

    async def _kick_clients(self):
        await asyncio.sleep(0.05)
        if self.role == "primary" or self.server is None:
            return
        for conn in list(self.server.connections):
            try:
                await conn.close()
            except Exception:
                logger.debug("client kick failed", exc_info=True)

    async def _renew_loop(self):
        period = self.lease_s / 3.0
        while self.role == "primary" and not self._stopping:
            await asyncio.sleep(period)
            for idx in self._other_ids():
                link = self._links.get(idx)
                if link is None:
                    continue
                try:
                    reply = await asyncio.wait_for(
                        link.conn.call("repl_renew", self.store.epoch,
                                       self.candidate_id),
                        period,
                    )
                except (rpc.RpcError, OSError, asyncio.TimeoutError):
                    continue  # sender loop owns reconnect
                if reply.get("ok"):
                    self._note_peer_alive(idx)
                elif reply.get("promised", 0) > self.store.epoch:
                    await self._demote(
                        f"peer {idx} promised epoch {reply['promised']}")
                    return

    # ------------------------------------------------------------ streaming

    async def _sender(self, idx: int):
        """Per-follower replication pump: snapshot on (re)connect, then
        incremental (seq, record) batches; every ack feeds the commit index
        and the lease."""
        addr = self.peers[idx]
        ev = self._send_events[idx]
        while self.role == "primary" and not self._stopping:
            link = self._links.get(idx)
            try:
                if link is None:
                    conn = await rpc.connect(
                        *addr, timeout=2.0,
                        name=f"gcs-primary{self.candidate_id}->peer{idx}",
                    )
                    link = self.open_peer(addr, conn)
                    self._links[idx] = link
                    sync_seq = self.store.seq
                    reply = await asyncio.wait_for(
                        link.conn.call("repl_sync", self.store.epoch,
                                       sync_seq, self.store.snapshot(),
                                       self.candidate_id),
                        self.quorum_timeout_s,
                    )
                    if not reply.get("ok"):
                        if reply.get("promised", 0) > self.store.epoch:
                            await self._demote(
                                f"peer {idx} fenced our epoch "
                                f"{self.store.epoch}")
                            return
                        raise rpc.RpcError("sync rejected")
                    self._peer_acked[idx] = sync_seq
                    self._note_peer_alive(idx)
                    self._resolve_commit_waiters()
                try:
                    await asyncio.wait_for(ev.wait(), self.lease_s / 3.0)
                except asyncio.TimeoutError:
                    pass
                ev.clear()
                acked = self._peer_acked.get(idx, 0)
                # Walk from the ring's tail only as far as this peer's ack:
                # batch cost is O(records to send), not O(ring).
                batch = []
                for s, r in reversed(self._repl_log):
                    if s <= acked:
                        break
                    batch.append((s, r))
                batch.reverse()
                if not batch:
                    continue
                if batch[0][0] != acked + 1:
                    # The ring dropped records this follower still needs:
                    # fall back to a fresh snapshot.
                    raise rpc.RpcError("follower behind the repl ring")
                reply = await asyncio.wait_for(
                    link.conn.call("repl_append", self.store.epoch, batch,
                                   self.candidate_id),
                    self.quorum_timeout_s,
                )
                if reply.get("ok"):
                    if reply["seq"] > self.store.seq:
                        # The follower is AHEAD of our log: it holds a stale
                        # tail from an era we never saw — snapshot it back.
                        raise rpc.RpcError("follower ahead of primary log")
                    self._peer_acked[idx] = reply["seq"]
                    self._note_peer_alive(idx)
                    self._resolve_commit_waiters()
                elif reply.get("resync"):
                    raise rpc.RpcError("follower requested resync")
                elif reply.get("promised", 0) > self.store.epoch:
                    await self._demote(
                        f"peer {idx} fenced our epoch {self.store.epoch}")
                    return
            except asyncio.CancelledError:
                return
            except (rpc.RpcError, OSError, asyncio.TimeoutError):
                link = self._links.pop(idx, None)
                if link is not None:
                    await link.close()
                await asyncio.sleep(0.2)

    def open_peer(self, addr, conn) -> PeerLink:
        return PeerLink(addr, conn)

    # ------------------------------------------------------------- teardown

    async def shutdown(self):
        self._stopping = True
        if self._election_task is not None:
            self._election_task.cancel()
            self._election_task = None
        await self._demote("shutting down")
        self.store.close()
        if self.server is not None:
            await self.server.close()
