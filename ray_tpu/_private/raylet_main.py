"""Node process entry point: runs a raylet, plus the GCS when started as head.

Design parity: reference `src/ray/raylet/main.cc` (raylet binary hosting NodeManager +
ObjectManager) and `src/ray/gcs/gcs_server_main.cc` (gcs_server binary). Both services
share one asyncio loop in one process per node; the head node hosts both.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import signal
import sys

from ray_tpu._private import rpc
from ray_tpu._private.config import bind_host_for, get_node_ip
from ray_tpu._private.gcs import GcsService
from ray_tpu._private.ids import NodeID
from ray_tpu._private.raylet import Raylet


async def amain(args):
    from ray_tpu._private.gcs_replication import parse_addrs

    gcs_addrs = parse_addrs(args.gcs_addrs) if args.gcs_addrs else []
    if not gcs_addrs and args.gcs_port:
        gcs_addrs = [(args.gcs_host, args.gcs_port)]
    if args.head and not gcs_addrs:
        # Fallback for direct invocation: host the GCS in-process. The normal path
        # (node.py) runs the GCS as its own restartable process via gcs_main.
        gcs = GcsService()
        gcs_server = rpc.RpcServer(lambda conn: gcs)
        await gcs_server.start(
            host=bind_host_for(args.node_ip or get_node_ip()), port=0
        )
        gcs.start_background()
        gcs_addrs = [(args.gcs_host, gcs_server.port)]
    gcs_port = gcs_addrs[0][1]

    node_id = NodeID.from_hex(args.node_id) if args.node_id else NodeID.from_random()
    raylet = Raylet(
        node_id=node_id,
        gcs_addr=gcs_addrs,
        resources=json.loads(args.resources),
        labels=json.loads(args.labels),
        is_head=args.head,
        session_dir=args.session_dir,
        object_store_bytes=args.object_store_bytes or None,
        worker_env=json.loads(args.worker_env),
        node_ip=args.node_ip or None,
    )
    await raylet.start(port=args.port)

    # Report the bound ports to the parent via a ready file.
    ready = {
        "node_id": node_id.hex(),
        "raylet_port": raylet.port,
        "gcs_port": gcs_port,
        "pid": os.getpid(),
    }
    if args.ready_file:
        tmp = args.ready_file + ".tmp"
        with open(tmp, "w") as f:
            json.dump(ready, f)
        os.replace(tmp, args.ready_file)

    stop = asyncio.Event()

    def _sig(*_a):
        stop.set()

    loop = asyncio.get_running_loop()
    for s in (signal.SIGTERM, signal.SIGINT):
        loop.add_signal_handler(s, _sig)
    await stop.wait()
    await raylet.shutdown()


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--head", action="store_true")
    p.add_argument("--gcs-host", default="127.0.0.1")
    p.add_argument("--gcs-port", type=int, default=0)
    p.add_argument("--gcs-addrs", default="",
                   help="comma host:port list of GCS candidates (replicated "
                        "mode lists every head candidate)")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--node-id", default="")
    p.add_argument("--node-ip", default="")
    p.add_argument("--resources", default="{}")
    p.add_argument("--labels", default="{}")
    p.add_argument("--worker-env", default="{}")
    p.add_argument("--session-dir", default="/tmp/ray_tpu")
    p.add_argument("--object-store-bytes", type=int, default=0)
    p.add_argument("--ready-file", default="")
    args = p.parse_args()
    asyncio.run(amain(args))


if __name__ == "__main__":
    sys.exit(main())
