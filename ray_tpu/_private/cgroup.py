"""cgroup-v2 resource isolation for worker processes.

Design parity: reference `src/ray/common/cgroup2/` (SysFsCgroupDriver +
CgroupManager: a per-session cgroup subtree splitting "system" daemons from
"workers", memory/cpu controllers enabled, workers placed on spawn and capped
so a runaway task cannot OOM the raylet/GCS). Re-designed for this runtime:

    <base>/ray_tpu_<session>/
        system/            raylet + GCS (memory.min reservation)
        workers/           memory.max = node total - reservation; NO procs —
                           cgroup-v2's no-internal-process rule forbids member
                           pids in a cgroup whose subtree_control is enabled
        workers/shared/    leaf pool where workers actually live
        workers/w_<pid>/   per-worker leaf when the task/actor declares a
                           "memory" resource (memory.max = that many bytes)

Setup order matters on real kernels: children are created and the base's
existing member pids migrate into system/ BEFORE subtree_control is written
(a cgroup with member procs rejects controller enablement with EBUSY).
Everything degrades gracefully: on hosts where /sys/fs/cgroup isn't writable
(non-root, shared CI) `available` is False and the raylet runs exactly as
before. The sysfs root is injectable (RAY_TPU_CGROUP_BASE) so tests drive the
full write path against a fake tree.
"""

from __future__ import annotations

import os
from typing import Optional

# Flag semantics: "auto" = enable iff the base is writable; "1" = required
# (setup failures are logged loudly); "0" = off.
ENV_FLAG = "RAY_TPU_CGROUP_ISOLATION"
ENV_BASE = "RAY_TPU_CGROUP_BASE"
ENV_RESERVED = "RAY_TPU_CGROUP_SYSTEM_RESERVED_BYTES"
_DEFAULT_RESERVED = 2 << 30  # memory.min for raylet/GCS (reference default ~2G)


class CgroupV2Manager:
    """Owns one session's cgroup subtree. All methods are best-effort: cgroup
    writes that fail (race with worker death, controller missing) log through
    the caller, never raise into scheduling paths."""

    def __init__(self, session_name: str, *, base: Optional[str] = None,
                 total_memory: Optional[int] = None,
                 system_reserved: Optional[int] = None):
        self._base = base or os.environ.get(ENV_BASE) or self._discover_base()
        self._session_dir = (
            os.path.join(self._base, f"ray_tpu_{session_name}") if self._base else None
        )
        self._system = self._workers = self._shared = None
        if total_memory is None:
            total_memory = _host_memory_bytes()
        self._total_memory = total_memory
        self._reserved = (
            system_reserved
            if system_reserved is not None
            else int(os.environ.get(ENV_RESERVED, _DEFAULT_RESERVED))
        )
        self._active = False

    # -- discovery ---------------------------------------------------------
    @staticmethod
    def _discover_base() -> Optional[str]:
        """The deepest cgroup-v2 dir this process may create children in: its
        own cgroup (delegated subtrees) or the root mount when running as root."""
        from ray_tpu._private.memory_monitor import _own_cgroup_v2_path

        for candidate in (_own_cgroup_v2_path(), "/sys/fs/cgroup"):
            if candidate and os.path.isdir(candidate) and os.access(candidate, os.W_OK):
                return candidate
        return None

    @property
    def available(self) -> bool:
        return self._active

    # -- lifecycle ---------------------------------------------------------
    def setup(self) -> bool:
        """Create the session subtree and enable memory/cpu controllers.
        Returns True when isolation is active."""
        if not self._session_dir:
            return False
        try:
            self._reap_stale_siblings()
            os.makedirs(self._session_dir, exist_ok=True)
            self._system = os.path.join(self._session_dir, "system")
            self._workers = os.path.join(self._session_dir, "workers")
            self._shared = os.path.join(self._workers, "shared")
            os.makedirs(self._system, exist_ok=True)
            os.makedirs(self._shared, exist_ok=True)
            # Migrate the base's member pids (this raylet, co-located daemons)
            # into system/ FIRST — a cgroup holding procs rejects
            # subtree_control writes (no-internal-process rule).
            self._migrate_base_procs()
            for d in (self._base, self._session_dir, self._workers):
                self._enable_controllers(d)
            # Reserve memory for the control plane; cap the worker pool at the
            # remainder so worker pressure lands on workers, not the raylet.
            self._write(os.path.join(self._system, "memory.min"),
                        str(self._reserved))
            if self._total_memory:
                cap = max(self._total_memory - self._reserved, 256 << 20)
                self._write(os.path.join(self._workers, "memory.max"), str(cap))
            self._active = True
            return True
        except OSError:
            self._active = False
            return False

    def _migrate_base_procs(self) -> None:
        procs = os.path.join(self._base, "cgroup.procs")
        try:
            with open(procs) as f:
                pids = [p.strip() for p in f if p.strip()]
        except OSError:
            return  # base is the cgroupfs root (kernel hides procs) or gone
        for pid in pids:
            self._write(os.path.join(self._system, "cgroup.procs"), pid)

    def _reap_stale_siblings(self) -> None:
        """rmdir leftover ray_tpu_* trees whose processes are gone (empty
        cgroups remove cleanly; live ones refuse with EBUSY and are kept)."""
        try:
            entries = os.listdir(self._base)
        except OSError:
            return
        for name in entries:
            if not name.startswith("ray_tpu_") or name == os.path.basename(
                self._session_dir or ""
            ):
                continue
            top = os.path.join(self._base, name)
            for root, dirs, _files in os.walk(top, topdown=False):
                for d in dirs:
                    try:
                        os.rmdir(os.path.join(root, d))
                    except OSError:
                        pass
            try:
                os.rmdir(top)
            except OSError:
                pass

    def place_system_process(self, pid: int) -> bool:
        """Move a control-plane process (raylet, GCS) into system/."""
        if not self._active:
            return False
        return self._write(os.path.join(self._system, "cgroup.procs"), str(pid))

    def place_worker(self, pid: int, *, memory_bytes: Optional[int] = None,
                     cpu_weight: Optional[int] = None) -> bool:
        """Place a worker: the shared pool by default, a dedicated capped
        sub-group when the task/actor declared a memory resource."""
        if not self._active:
            return False
        if memory_bytes or cpu_weight:
            d = os.path.join(self._workers, f"w_{pid}")
            try:
                os.makedirs(d, exist_ok=True)
            except OSError:
                return False
            if memory_bytes:
                self._write(os.path.join(d, "memory.max"), str(int(memory_bytes)))
            if cpu_weight:
                self._write(os.path.join(d, "cpu.weight"), str(int(cpu_weight)))
            return self._write(os.path.join(d, "cgroup.procs"), str(pid))
        # Leaf pool, not workers/ itself: workers/ has subtree_control enabled
        # and therefore cannot hold member pids (no-internal-process rule).
        return self._write(os.path.join(self._shared, "cgroup.procs"), str(pid))

    def remove_worker(self, pid: int) -> None:
        """Reap a dead worker's dedicated sub-group (empty cgroups rmdir)."""
        if not self._active:
            return
        d = os.path.join(self._workers, f"w_{pid}")
        if os.path.isdir(d):
            try:
                os.rmdir(d)
            except OSError:
                pass  # still has procs or already gone

    def teardown(self) -> None:
        if not self._active or not self._session_dir:
            return
        # Best-effort: move this process back to the base so system/ empties.
        # Fails (EBUSY) when the base's subtree_control was enabled by setup —
        # then the tree lingers until the next session's stale reap.
        self._write(os.path.join(self._base, "cgroup.procs"), str(os.getpid()))
        for sub in (self._shared, self._system, self._workers, self._session_dir):
            try:
                if sub and os.path.isdir(sub):
                    for child in os.listdir(sub):
                        p = os.path.join(sub, child)
                        if os.path.isdir(p):
                            try:
                                os.rmdir(p)
                            except OSError:
                                pass
                    os.rmdir(sub)
            except OSError:
                pass
        self._active = False

    # -- helpers -----------------------------------------------------------
    def _enable_controllers(self, path: str) -> None:
        # The kernel materializes cgroup.subtree_control in every cgroup dir;
        # writing may still fail when the controller isn't delegated — then
        # limits simply won't apply (isolation stays best-effort).
        try:
            with open(os.path.join(path, "cgroup.subtree_control"), "w") as f:
                f.write("+memory +cpu")
        except OSError:
            pass

    @staticmethod
    def _write(path: str, value: str) -> bool:
        try:
            with open(path, "w") as f:
                f.write(value)
            return True
        except OSError:
            return False


def _host_memory_bytes() -> Optional[int]:
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    return None


def manager_from_env(session_name: str) -> Optional[CgroupV2Manager]:
    """Build + set up a manager per the env flag; None when disabled/unavailable."""
    flag = os.environ.get(ENV_FLAG, "auto").lower()
    if flag in ("0", "false", "off"):
        return None
    mgr = CgroupV2Manager(session_name)
    if mgr.setup():
        return mgr
    if flag in ("1", "true", "on", "required"):
        import logging

        logging.getLogger("ray_tpu.cgroup").warning(
            "cgroup isolation requested (%s=1) but setup failed at base %r",
            ENV_FLAG, mgr._base,
        )
    return None
