"""Worker process entry point.

Design parity: reference `python/ray/_private/workers/default_worker.py` — connect the
CoreWorker, then block in the task loop (here the loop is the event-driven io thread).
"""

from __future__ import annotations

import os
import threading

from ray_tpu._private.ids import WorkerID
from ray_tpu._private.worker import CoreWorker, set_global_worker


def main():
    worker_id = WorkerID.from_hex(os.environ["RAY_TPU_WORKER_ID"])
    raylet_port = int(os.environ["RAY_TPU_RAYLET_PORT"])
    worker = CoreWorker(
        mode="worker",
        raylet_addr=("127.0.0.1", raylet_port),
        # Comma-separated candidate list under a replicated GCS; CoreWorker
        # normalizes and fails over between them.
        gcs_addr=os.environ["RAY_TPU_GCS_ADDR"],
        worker_id=worker_id,
    )
    set_global_worker(worker)
    worker.connect()
    threading.Event().wait()  # serve tasks until the raylet connection closes


if __name__ == "__main__":
    main()
