"""Post-mortem task debugging over a socket.

Design parity: reference `python/ray/util/rpdb.py` (RemotePdb: a pdb bound to
a TCP socket, sessions advertised through the GCS, `ray debug` attaches) +
the `RAY_DEBUG_POST_MORTEM` trigger. Here: when a task raises and
RAY_TPU_POST_MORTEM=1, the worker PARKS the failing frame — it opens a
listening socket, registers {task, host, port, error} in the GCS KV under the
"debug_sessions" namespace, and blocks the failing task until a debugger
attaches (or a wait budget expires), then lets the error propagate normally.
`ray_tpu debug` lists the advertised sessions and bridges the operator's
terminal to the worker's pdb.
"""

from __future__ import annotations

import json
import os
import pdb
import socket
import time

KV_NS = "debug_sessions"
# RAY_TPU_POST_MORTEM / RAY_TPU_POST_MORTEM_WAIT_S ride the standard flag
# table (config.py "post_mortem"/"post_mortem_wait_s").

# At most ONE parked session per worker process: each park blocks a
# task-executor thread, and a correlated failure wave (bad batch, missing
# module) parking every executor thread would stall HEALTHY tasks for the
# whole wait budget. Further failures while parked propagate immediately.
import threading as _threading

_park_slot = _threading.Semaphore(1)


def post_mortem_enabled() -> bool:
    # RAY_TPU_POST_MORTEM rides the standard flag table (config.py
    # "post_mortem"); the env spelling is unchanged.
    from ray_tpu._private.config import CONFIG

    return bool(CONFIG.post_mortem)


def park_post_mortem(worker, spec, exc: BaseException) -> bool:
    """Advertise a debug session for the failing task and block until a
    debugger drives pdb over the socket (returns True) or the wait budget
    expires (returns False). Runs on the task-executor thread, so the task's
    reply — and its error — are delayed exactly as long as the operator
    debugs; every other worker thread keeps serving."""
    tb = exc.__traceback__
    if tb is None:
        return False
    if not _park_slot.acquire(blocking=False):
        return False  # another task is already parked on this worker
    try:
        return _park_locked(worker, spec, exc, tb)
    finally:
        _park_slot.release()


def _park_locked(worker, spec, exc, tb) -> bool:
    from ray_tpu._private.config import CONFIG as _CFG

    task_hex = spec["task_id"].hex()
    # The pdb socket is an unauthenticated interactive interpreter: bind
    # loopback unless the operator explicitly opted into external exposure
    # with RAY_TPU_POST_MORTEM_EXTERNAL=1 (reference: util/rpdb.py binds
    # localhost unless ray debugger_external was requested).
    external = bool(_CFG.post_mortem_external)
    srv = socket.create_server(("" if external else "127.0.0.1", 0))
    port = srv.getsockname()[1]
    info = {
        "task_id": task_hex,
        "name": spec.get("name"),
        # Advertise an address `ray_tpu debug` can actually reach: the node
        # IP only when the server listens beyond loopback.
        "ip": (getattr(worker, "node_ip", None) or "127.0.0.1")
        if external else "127.0.0.1",
        "port": port,
        "pid": os.getpid(),
        "error": repr(exc),
        "time": time.time(),
    }
    try:
        worker.gcs_kv_put(KV_NS, task_hex.encode(), json.dumps(info).encode())
    except Exception:
        srv.close()
        return False
    from ray_tpu._private.config import CONFIG

    srv.settimeout(float(CONFIG.post_mortem_wait_s))
    attached = False
    try:
        try:
            conn, _addr = srv.accept()
        except (socket.timeout, OSError):
            return False
        fh = conn.makefile("rw")
        try:
            fh.write(
                f"*** ray_tpu post-mortem: task {spec.get('name')!r} "
                f"({task_hex}) raised {exc!r}\n"
                "*** you are at the raising frame; `up`/`p`/`pp` to inspect, "
                "`c` or `q` to release the task error\n"
            )
            fh.flush()
            dbg = pdb.Pdb(stdin=fh, stdout=fh)
            dbg.use_rawinput = False
            dbg.prompt = "(ray_tpu-pdb) "
            dbg.reset()
            dbg.interaction(None, tb)
            attached = True
        except Exception:
            pass  # a dropped connection must never mask the task's own error
        finally:
            try:
                fh.close()
                conn.close()
            except Exception:
                pass
        return attached
    finally:
        try:
            worker.gcs_call("kv_del", KV_NS, task_hex.encode())
        except Exception:
            pass
        srv.close()


def list_sessions(worker) -> list[dict]:
    """Advertised parked sessions, newest first. A SIGKILLed worker never
    runs its kv_del, so entries can be stale — attach() raises
    ConnectionError for those and drop_session() cleans them up (the CLI
    does both); listings are advertisements, not liveness proofs."""
    out = []
    try:
        keys = worker.gcs_call("kv_keys", KV_NS, b"")
    except Exception:
        return out
    for key in keys:
        try:
            raw = worker.gcs_kv_get(KV_NS, bytes(key))
            if raw:
                out.append(json.loads(bytes(raw).decode()))
        except Exception:
            continue
    out.sort(key=lambda s: -s.get("time", 0.0))
    return out


def drop_session(worker, session: dict) -> None:
    """Remove a (stale) session advertisement."""
    try:
        worker.gcs_call("kv_del", KV_NS, session["task_id"].encode())
    except Exception:
        pass


def attach(session: dict, stdin=None, stdout=None) -> None:
    """Bridge a terminal (or test harness streams) to a parked session's pdb."""
    import sys

    stdin = stdin or sys.stdin
    stdout = stdout or sys.stdout
    with socket.create_connection((session["ip"], session["port"]),
                                  timeout=30) as conn:
        conn_f = conn.makefile("rw")
        try:
            # Reader thread: worker pdb output -> stdout; main thread:
            # stdin -> worker. EOF on either side ends the bridge.
            import threading

            done = threading.Event()

            def pump_out():
                try:
                    while True:
                        chunk = conn_f.readline()
                        if not chunk:
                            break
                        stdout.write(chunk)
                        stdout.flush()
                finally:
                    done.set()

            t = threading.Thread(target=pump_out, daemon=True)
            t.start()
            while not done.is_set():
                line = stdin.readline()
                if not line:
                    break
                try:
                    conn_f.write(line)
                    conn_f.flush()
                except (OSError, ValueError):
                    break
            done.wait(timeout=5)
        finally:
            try:
                conn_f.close()
            except Exception:
                pass
