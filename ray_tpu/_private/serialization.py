"""Object serialization: cloudpickle + pickle5 out-of-band buffers.

Design parity: reference `python/ray/_private/serialization.py` (cloudpickle with protocol-5
buffer callbacks so large numpy arrays are written out-of-band and can be mapped zero-copy
from the shared-memory store). TPU-native addition: `jax.Array` values are serialized as
host numpy plus sharding-free metadata — device placement is a property of the *runtime*
(mesh + sharding specs), not of the serialized bytes, which is the correct model under XLA
where arrays are re-sharded on the receiving mesh.

Wire format of a sealed object:
    [8-byte LE header len][msgpack header][payload bytes...]
    header = {"pickled": len, "buffers": [len, ...], "meta": {...}}
Payload = pickled bytes followed by each raw out-of-band buffer, contiguously.
"""

from __future__ import annotations

import pickle
import struct
from typing import Any

import cloudpickle
import msgpack

_HEADER_LEN_FMT = "<Q"
_HEADER_LEN_SIZE = 8

# Registered custom (reducer, class) pairs: ray.util.serialization parity.
_custom_serializers: dict[type, tuple] = {}


def register_serializer(cls: type, *, serializer, deserializer):
    """Parity with `ray.util.serialization.register_serializer`."""
    _custom_serializers[cls] = (serializer, deserializer)


def deregister_serializer(cls: type):
    _custom_serializers.pop(cls, None)


class _Pickler(cloudpickle.CloudPickler):
    def __init__(self, file, buffer_callback):
        super().__init__(file, protocol=5, buffer_callback=buffer_callback)

    def reducer_override(self, obj):
        custom = _custom_serializers.get(type(obj))
        if custom is not None:
            serializer, deserializer = custom
            return (_apply_deserializer, (deserializer, serializer(obj)))
        return super().reducer_override(obj)


def _apply_deserializer(deserializer, payload):
    return deserializer(payload)


def _jax_device_put_guard(obj):
    """Convert jax.Arrays to numpy for the wire; see module docstring."""
    try:
        import jax
    except ImportError:  # pragma: no cover
        return obj
    if isinstance(obj, jax.Array):
        import numpy as np

        return np.asarray(obj)
    return obj


def serialize(value: Any) -> tuple[bytes, list]:
    """Return (header_and_pickled, buffers). Buffers are pickle.PickleBuffer objects."""
    import io

    buffers: list[pickle.PickleBuffer] = []
    value = _jax_device_put_guard(value)
    bio = io.BytesIO()
    pickler = _Pickler(bio, buffers.append)
    pickler.dump(value)
    pickled = bio.getvalue()
    return pickled, buffers


_BUF_ALIGN = 64


def _layout(pickled: bytes, raw_buffers: list) -> tuple[bytes, list[int], int]:
    """Compute the wire layout: (length-prefixed header bytes, absolute buffer
    offsets, total size). Each out-of-band buffer starts at a 64-byte boundary:
    aligned destinations keep the big memcpy on the fast SIMD path (~40% put
    bandwidth on this host) and deserialized arrays alias aligned memory."""
    header = msgpack.packb(
        {"pickled": len(pickled), "buffers": [len(b) for b in raw_buffers],
         "align": _BUF_ALIGN}
    )
    head = struct.pack(_HEADER_LEN_FMT, len(header)) + header
    off = len(head) + len(pickled)
    offsets = []
    for b in raw_buffers:
        off = (off + _BUF_ALIGN - 1) & ~(_BUF_ALIGN - 1)
        offsets.append(off)
        off += len(b)
    return head, offsets, off


def dumps(value: Any) -> bytes:
    """Serialize to a single contiguous byte string (wire format above)."""
    pickled, buffers = serialize(value)
    raw_buffers = [b.raw() for b in buffers]
    head, offsets, total = _layout(pickled, raw_buffers)
    out = bytearray(total)
    write_parts(memoryview(out), pickled, raw_buffers, _precomputed=(head, offsets))
    return bytes(out)


def dumps_into(value: Any, dest: memoryview) -> int:
    """Serialize directly into a writable buffer (a shm mapping). Returns bytes written."""
    blob = dumps(value)  # one copy; fine until the C++ store lands
    n = len(blob)
    if n > len(dest):
        raise ValueError(f"object of {n} bytes exceeds destination of {len(dest)}")
    dest[:n] = blob
    return n


def serialized_size(value: Any) -> tuple[bytes, list, int]:
    pickled, buffers = serialize(value)
    raw = [b.raw() for b in buffers]
    _head, _offsets, total = _layout(pickled, raw)
    return pickled, raw, total


def write_parts(dest: memoryview, pickled: bytes, raw_buffers: list,
                _precomputed: tuple | None = None) -> int:
    """Write the wire format into a destination buffer without re-pickling.

    Out-of-band buffers are copied straight from their memoryviews into their
    aligned slots — one memcpy per buffer, no intermediate `bytes`
    materialization (that extra copy halved put bandwidth for large arrays)."""
    head, offsets = _precomputed or _layout(pickled, raw_buffers)[:2]
    dest[: len(head)] = head
    off = len(head)
    dest[off : off + len(pickled)] = pickled
    off += len(pickled)
    end = off
    for part, boff in zip(raw_buffers, offsets):
        if boff > off:
            dest[off:boff] = bytes(boff - off)  # alignment gap
        n = len(part)
        dest[boff : boff + n] = part
        off = end = boff + n
    return end


def assemble(pickled: bytes, raw_buffers: list) -> bytes:
    """Assemble the full wire blob from pre-serialized parts."""
    head, offsets, total = _layout(pickled, raw_buffers)
    out = bytearray(total)
    write_parts(memoryview(out), pickled, raw_buffers, _precomputed=(head, offsets))
    return bytes(out)


def loads(data) -> Any:
    """Deserialize from bytes or a memoryview (zero-copy for buffers)."""
    view = memoryview(data)
    (header_len,) = struct.unpack(_HEADER_LEN_FMT, view[:_HEADER_LEN_SIZE])
    off = _HEADER_LEN_SIZE
    header = msgpack.unpackb(bytes(view[off : off + header_len]))
    off += header_len
    pickled = view[off : off + header["pickled"]]
    off += header["pickled"]
    align = header.get("align", 1)
    buffers = []
    for blen in header["buffers"]:
        off = (off + align - 1) & ~(align - 1)
        buffers.append(view[off : off + blen])
        off += blen
    return pickle.loads(pickled, buffers=buffers)
