"""Object serialization: cloudpickle + pickle5 out-of-band buffers.

Design parity: reference `python/ray/_private/serialization.py` (cloudpickle with protocol-5
buffer callbacks so large numpy arrays are written out-of-band and can be mapped zero-copy
from the shared-memory store). TPU-native addition: `jax.Array` values are serialized as
host numpy plus sharding-free metadata — device placement is a property of the *runtime*
(mesh + sharding specs), not of the serialized bytes, which is the correct model under XLA
where arrays are re-sharded on the receiving mesh.

Wire format of a sealed object:
    [8-byte LE header len][msgpack header][payload bytes...]
    header = {"pickled": len, "buffers": [len, ...], "meta": {...}}
Payload = pickled bytes followed by each raw out-of-band buffer, contiguously.
"""

from __future__ import annotations

import pickle
import struct
from typing import Any

import cloudpickle
import msgpack

_HEADER_LEN_FMT = "<Q"
_HEADER_LEN_SIZE = 8

# Registered custom (reducer, class) pairs: ray.util.serialization parity.
_custom_serializers: dict[type, tuple] = {}


def register_serializer(cls: type, *, serializer, deserializer):
    """Parity with `ray.util.serialization.register_serializer`."""
    _custom_serializers[cls] = (serializer, deserializer)


def deregister_serializer(cls: type):
    _custom_serializers.pop(cls, None)


class _Pickler(cloudpickle.CloudPickler):
    def __init__(self, file, buffer_callback):
        super().__init__(file, protocol=5, buffer_callback=buffer_callback)

    def reducer_override(self, obj):
        custom = _custom_serializers.get(type(obj))
        if custom is not None:
            serializer, deserializer = custom
            return (_apply_deserializer, (deserializer, serializer(obj)))
        return super().reducer_override(obj)


def _apply_deserializer(deserializer, payload):
    return deserializer(payload)


def _jax_device_put_guard(obj):
    """Convert jax.Arrays to numpy for the wire; see module docstring."""
    try:
        import jax
    except ImportError:  # pragma: no cover
        return obj
    if isinstance(obj, jax.Array):
        import numpy as np

        return np.asarray(obj)
    return obj


def serialize(value: Any) -> tuple[bytes, list]:
    """Return (header_and_pickled, buffers). Buffers are pickle.PickleBuffer objects."""
    import io

    buffers: list[pickle.PickleBuffer] = []
    value = _jax_device_put_guard(value)
    bio = io.BytesIO()
    pickler = _Pickler(bio, buffers.append)
    pickler.dump(value)
    pickled = bio.getvalue()
    return pickled, buffers


def dumps(value: Any) -> bytes:
    """Serialize to a single contiguous byte string (wire format above)."""
    pickled, buffers = serialize(value)
    raw_buffers = [b.raw() for b in buffers]
    header = msgpack.packb(
        {"pickled": len(pickled), "buffers": [len(b) for b in raw_buffers]}
    )
    parts = [struct.pack(_HEADER_LEN_FMT, len(header)), header, pickled]
    parts.extend(bytes(b) for b in raw_buffers)
    return b"".join(parts)


def dumps_into(value: Any, dest: memoryview) -> int:
    """Serialize directly into a writable buffer (a shm mapping). Returns bytes written."""
    blob = dumps(value)  # one copy; fine until the C++ store lands
    n = len(blob)
    if n > len(dest):
        raise ValueError(f"object of {n} bytes exceeds destination of {len(dest)}")
    dest[:n] = blob
    return n


def serialized_size(value: Any) -> tuple[bytes, list, int]:
    pickled, buffers = serialize(value)
    raw = [b.raw() for b in buffers]
    header = msgpack.packb({"pickled": len(pickled), "buffers": [len(b) for b in raw]})
    total = _HEADER_LEN_SIZE + len(header) + len(pickled) + sum(len(b) for b in raw)
    return pickled, raw, total


def _header_bytes(pickled: bytes, raw_buffers: list) -> bytes:
    header = msgpack.packb(
        {"pickled": len(pickled), "buffers": [len(b) for b in raw_buffers]}
    )
    return struct.pack(_HEADER_LEN_FMT, len(header)) + header


def write_parts(dest: memoryview, pickled: bytes, raw_buffers: list) -> int:
    """Write the wire format into a destination buffer without re-pickling."""
    head = _header_bytes(pickled, raw_buffers)
    off = 0
    for part in [head, pickled, *raw_buffers]:
        n = len(part)
        dest[off : off + n] = bytes(part) if not isinstance(part, (bytes, bytearray)) else part
        off += n
    return off


def assemble(pickled: bytes, raw_buffers: list) -> bytes:
    """Assemble the full wire blob from pre-serialized parts."""
    return b"".join([_header_bytes(pickled, raw_buffers), pickled, *(bytes(b) for b in raw_buffers)])


def loads(data) -> Any:
    """Deserialize from bytes or a memoryview (zero-copy for buffers)."""
    view = memoryview(data)
    (header_len,) = struct.unpack(_HEADER_LEN_FMT, view[:_HEADER_LEN_SIZE])
    off = _HEADER_LEN_SIZE
    header = msgpack.unpackb(bytes(view[off : off + header_len]))
    off += header_len
    pickled = view[off : off + header["pickled"]]
    off += header["pickled"]
    buffers = []
    for blen in header["buffers"]:
        buffers.append(view[off : off + blen])
        off += blen
    return pickle.loads(pickled, buffers=buffers)
