"""GCS: cluster control plane.

Design parity: reference `src/ray/gcs/` — node membership + health (gcs_node_manager,
gcs_health_check_manager), actor registry & scheduling (gcs_actor_manager/_scheduler),
placement groups (gcs_placement_group_manager/_scheduler), internal KV (gcs_kv_manager),
function table (gcs_function_manager), resource view (gcs_resource_manager), pubsub
(GcsPublisher). One asyncio service; storage is in-memory (the reference's default
InMemoryStoreClient; a persistent store client can be slotted in behind `self.kv`).

Actor scheduling follows the reference's two-phase flow (gcs_actor_manager.h:60-92):
register (owner alive check, name registration) then schedule (lease a worker via a
raylet, push the creation task, publish ALIVE).
"""

from __future__ import annotations

import asyncio
import time
import traceback
from collections import deque
from typing import Any

from ray_tpu._private.config import CONFIG, _LOOPBACK
from ray_tpu._private.ids import ActorID, JobID, NodeID, ObjectID, PlacementGroupID
from ray_tpu._private.rpc import Connection

ALIVE = "ALIVE"
DEAD = "DEAD"
PENDING = "PENDING_CREATION"
RESTARTING = "RESTARTING"

# pubsub channel -> export source type (reference export_*.proto source set).
_EXPORT_CHANNELS = {
    "nodes": "node",
    "actors": "actor",
    "placement_groups": "placement_group",
}


def _export_clean(v):
    """Render a pubsub/event payload JSON-safe: ids as hex, tuples as lists."""
    if isinstance(v, dict):
        return {str(k): _export_clean(x) for k, x in v.items()}
    if isinstance(v, (list, tuple, set)):
        return [_export_clean(x) for x in v]
    if hasattr(v, "hex") and not isinstance(v, (str, bytes, float)):
        try:
            return v.hex()
        except TypeError:
            return str(v)
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return str(v)


class NodeInfo:
    def __init__(self, node_id: NodeID, address, resources_total, labels, conn):
        self.node_id = node_id
        self.address = address  # (host, port) of the raylet RPC server
        self.resources_total = dict(resources_total)
        self.resources_available = dict(resources_total)
        self.labels = dict(labels or {})
        self.conn: Connection = conn
        self.alive = True
        self.last_heartbeat = time.monotonic()
        self.is_head = False
        self.pending_demand: dict = {}

    def view(self):
        return {
            "node_id": self.node_id,
            "address": self.address,
            "resources_total": self.resources_total,
            "resources_available": self.resources_available,
            "pending_demand": self.pending_demand,
            "labels": self.labels,
            "alive": self.alive,
            "is_head": self.is_head,
        }


class ActorInfo:
    def __init__(self, actor_id: ActorID, spec: dict):
        self.actor_id = actor_id
        self.spec = spec
        self.state = PENDING
        self.address = None  # {"node_id": NodeID, "worker_id": WorkerID}
        self.name = spec.get("name")
        self.namespace = spec.get("namespace", "")
        self.restarts_left = spec.get("max_restarts", 0)
        self.num_restarts = 0
        self.death_cause = None
        self.placing = False  # a create_actor RPC is in flight to a chosen node
        self.awaiting_report = False  # restored after GCS restart; host not yet re-reported
        # kill() arrived while creation was in flight: the schedule coroutine
        # must reap the worker when create_actor returns, or its resources leak
        # (the raylet only learns the actor_id->worker binding at completion).
        self.kill_requested = False

    def view(self):
        return {
            "actor_id": self.actor_id,
            "state": self.state,
            "address": self.address,
            "name": self.name,
            "namespace": self.namespace,
            "class_name": self.spec.get("class_name"),
            "num_restarts": self.num_restarts,
            "death_cause": self.death_cause,
            # Handle-shaping metadata: get_actor handles must behave like the
            # creator's (method num_returns/group bindings, ooo transport).
            "method_names": self.spec.get("method_names") or [],
            "method_opts": self.spec.get("method_opts") or {},
            "out_of_order": self.spec.get("allow_out_of_order_execution", False),
        }


class PlacementGroupInfo:
    def __init__(self, pg_id: PlacementGroupID, bundles, strategy, name=""):
        self.pg_id = pg_id
        self.bundles = bundles  # list[dict resource->amount]
        self.strategy = strategy
        self.name = name
        self.state = PENDING
        self.allocations: list[NodeID | None] = [None] * len(bundles)
        self.ready_event = asyncio.Event()
        self.awaiting_report = False  # restored after GCS restart


class GcsService:
    """The control plane. One instance; serves every connection (raylets + workers).

    With a persistent store (`gcs_store.FileStoreClient`) the GCS can restart and
    re-learn cluster state: durable tables (kv, jobs, actor specs, PG specs) load
    from storage, and live state (actor addresses, object locations, reserved
    bundles) is re-reported by raylets when they re-register
    (reference: gcs_init_data.cc + redis_store_client.h:126).
    """

    def __init__(self, store=None):
        from ray_tpu._private.gcs_store import InMemoryStoreClient

        self.store = store if store is not None else InMemoryStoreClient()
        self.nodes: dict[NodeID, NodeInfo] = {}
        self.actors: dict[ActorID, ActorInfo] = {}
        self.named_actors: dict[tuple[str, str], ActorID] = {}
        self.placement_groups: dict[PlacementGroupID, PlacementGroupInfo] = {}
        self.kv: dict[str, dict[bytes, bytes]] = {}
        self.object_dir: dict[ObjectID, dict] = {}
        self.subscribers: dict[str, set[Connection]] = {}
        self.job_counter = 0
        self.task_events: list[dict] = []
        self._task_event_seq = 0
        self._task_event_chunks: "deque[tuple[int, int]]" = deque()
        self._recent_logs: dict[str, dict] = {}  # worker hex -> {buf, meta, at}
        self._task_events_total = 0  # monotonic: events ever received
        self._actor_events: dict[ActorID, asyncio.Event] = {}
        self._death_task = None
        self._restored_from_store = False
        self._restore()

    def _restore(self):
        """Load durable tables; live state arrives via raylet re-registration."""
        self.store.load()
        for (ns, key), value in self.store.items("kv"):
            self.kv.setdefault(ns, {})[key] = value
        self.job_counter = self.store.get("meta", "job_counter", 0)
        # Seq derives from the stored chunk keys (no separate counter record:
        # it would double append traffic and reusing a stale counter after a
        # crash between the two puts would overwrite a persisted chunk).
        for seq, events in sorted(self.store.items("task_events")):
            self.task_events.extend(events)
            self._task_event_chunks.append((seq, len(events)))
            self._task_event_seq = max(self._task_event_seq, seq)
        for actor_id, rec in self.store.items("actors"):
            spec = rec["spec"]
            actor = ActorInfo(actor_id, spec)
            actor.restarts_left = rec.get("restarts_left", actor.restarts_left)
            actor.num_restarts = rec.get("num_restarts", 0)
            # Await the hosting raylet's re-report; a sweep reschedules/buries
            # actors whose node never comes back (_restored_actor_sweep).
            actor.state = RESTARTING
            actor.placing = True
            actor.awaiting_report = True
            self.actors[actor_id] = actor
            if actor.name:
                self.named_actors[(actor.namespace, actor.name)] = actor_id
            self._restored_from_store = True
        for pg_id, rec in self.store.items("pgs"):
            pg = PlacementGroupInfo(pg_id, rec["bundles"], rec["strategy"], rec.get("name", ""))
            pg.awaiting_report = True
            self.placement_groups[pg_id] = pg
            self._restored_from_store = True

    def start_background(self):
        loop = asyncio.get_running_loop()
        self._death_task = loop.create_task(self._death_check_loop())
        if self._restored_from_store:
            loop.create_task(self._restored_state_sweep())

    async def _restored_state_sweep(self, grace: float = 10.0):
        """After a GCS restart, anything not re-reported within the grace window is
        treated as having died during the outage."""
        await asyncio.sleep(grace)
        for actor in list(self.actors.values()):
            if getattr(actor, "awaiting_report", False) and actor.state == RESTARTING:
                actor.awaiting_report = False
                actor.placing = False
                await self._handle_actor_failure(actor, "node lost while GCS was down")
        for pg in list(self.placement_groups.values()):
            if getattr(pg, "awaiting_report", False) and pg.state == PENDING:
                pg.awaiting_report = False
                # Cancel whatever partial reservations were re-reported, then
                # schedule from scratch.
                for idx, nid in enumerate(pg.allocations):
                    node = self.nodes.get(nid) if nid else None
                    if node is not None and node.alive:
                        try:
                            await node.conn.call("cancel_bundle", pg.pg_id, idx)
                        except Exception:
                            pass  # node died mid-cancel; its bundles die with it
                    pg.allocations[idx] = None
                asyncio.get_running_loop().create_task(self._schedule_pg(pg))

    # ---------------- helpers ----------------

    async def publish(self, channel: str, message: Any):
        if channel in _EXPORT_CHANNELS:
            self._export_event(_EXPORT_CHANNELS[channel], message)
        for conn in list(self.subscribers.get(channel, ())):
            if conn.closed:
                self.subscribers[channel].discard(conn)
                continue
            try:
                await conn.notify("publish", channel, message)
            except Exception:
                self.subscribers[channel].discard(conn)

    def _export_event(self, source_type: str, data: Any):
        self._export_events(source_type, [data])

    def _export_events(self, source_type: str, batch: list):
        """Structured export events (reference: src/ray/protobuf/export_*.proto
        written by ray_event_recorder.cc; consumed by the dashboard aggregator).
        One JSONL file per source type under CONFIG.export_events_dir; each
        record is {source_type, event_id, timestamp, event_data} with ids
        rendered as hex. A whole batch lands in ONE append so a task-event
        flush doesn't stall the GCS loop on thousands of file opens, and the
        append itself runs on a dedicated writer thread behind a bounded
        queue — a slow or network-mounted export dir can't stall control-plane
        RPCs sharing the GCS event loop (events drop, oldest-first pressure,
        rather than block). Disabled (the default) costs one string compare."""
        dirpath = CONFIG.export_events_dir
        if not dirpath or not batch:
            return
        import json
        import uuid

        now = time.time()
        lines = []
        for data in batch:
            lines.append(json.dumps({
                "source_type": source_type,
                "event_id": uuid.uuid4().hex[:16],
                "timestamp": now,
                "event_data": _export_clean(data),
            }))
        self._export_writer_put(dirpath, source_type, lines)

    def _export_writer_put(self, dirpath: str, source_type: str, lines: list):
        import queue as _queue
        import threading

        q = getattr(self, "_export_queue", None)
        if q is None:
            q = self._export_queue = _queue.Queue(
                maxsize=CONFIG.gcs_export_queue_size
            )

            def drain():
                import os as _os

                while True:
                    item = q.get()
                    if item is None:
                        return
                    dp, st, ls = item
                    try:
                        _os.makedirs(dp, exist_ok=True)
                        with open(_os.path.join(dp, f"export_{st}.jsonl"),
                                  "a") as f:
                            f.write("\n".join(ls) + "\n")
                    except OSError:
                        pass  # export is observability, never a control-plane failure

            self._export_thread = threading.Thread(
                target=drain, name="gcs-export-writer", daemon=True
            )
            self._export_thread.start()
        try:
            q.put_nowait((dirpath, source_type, lines))
        except _queue.Full:
            # Shed OLDEST-first: an operator debugging a live incident needs
            # the most recent events in the export files.
            try:
                q.get_nowait()
                q.put_nowait((dirpath, source_type, lines))
            except (_queue.Empty, _queue.Full):
                pass  # racing the writer; never stall the control plane

    def _node_of_conn(self, conn) -> NodeInfo | None:
        for node in self.nodes.values():
            if node.conn is conn:
                return node
        return None

    # ---------------- node management ----------------

    def _vet_direct_addr(self, node_id, direct_addr):
        """Drop loopback direct addrs published by workers on nodes that
        registered a routable IP: a loopback addr is only dialable from the
        same host, so remote peers would reach themselves (or an unrelated
        local process on port collision). Dropping it makes callers fall back
        to the raylet-mediated route, which is always correct."""
        if not direct_addr:
            return None
        node = self.nodes.get(node_id)
        if (node is not None
                and node.address[0] not in _LOOPBACK
                and direct_addr[0] in _LOOPBACK):
            return None
        return tuple(direct_addr)

    async def rpc_register_node(self, conn, node_id: NodeID, address, resources, labels, is_head):
        info = NodeInfo(node_id, tuple(address), resources, labels, conn)
        info.is_head = bool(is_head)
        self.nodes[node_id] = info
        conn.on_close(lambda c: asyncio.get_running_loop().create_task(self._on_node_lost(node_id)))
        await self.publish("nodes", {"event": "added", "node": info.view()})
        return {"ok": True}

    async def rpc_sync_node_state(self, conn, node_id: NodeID, hosted_actors: dict,
                                  sealed_objects: list, reserved_bundles: list):
        """A raylet re-registered (typically after a GCS restart): re-learn the live
        state it hosts — actor addresses, object locations, PG bundle reservations."""
        for actor_id, info in hosted_actors.items():
            # info is {"worker_id", "direct_addr"} (bare worker_id accepted for
            # compatibility with older raylets mid-rolling-restart).
            worker_id = info["worker_id"] if isinstance(info, dict) else info
            direct_addr = info.get("direct_addr") if isinstance(info, dict) else None
            actor = self.actors.get(actor_id)
            if actor is None or actor.state == ALIVE:
                continue
            actor.state = ALIVE
            actor.address = {"node_id": node_id, "worker_id": worker_id,
                             "direct_addr": self._vet_direct_addr(node_id, direct_addr)}
            actor.placing = False
            actor.awaiting_report = False
            await self.publish("actors", {"actor": actor.view()})
            ev = self._actor_events.pop(actor_id, None)
            if ev:
                ev.set()
        for oid, size, owner in sealed_objects:
            entry = self.object_dir.setdefault(
                oid, {"size": size, "owner": owner, "locations": set()}
            )
            entry["locations"].add(node_id)
        for pg_id, bundle_index in reserved_bundles:
            pg = self.placement_groups.get(pg_id)
            if pg is None or bundle_index >= len(pg.bundles):
                continue
            pg.allocations[bundle_index] = node_id
            if all(a is not None for a in pg.allocations):
                pg.state = ALIVE
                pg.awaiting_report = False
                pg.ready_event.set()
        return True

    async def rpc_heartbeat(self, conn, node_id: NodeID, resources_available,
                            pending_demand=None):
        node = self.nodes.get(node_id)
        if node is None:
            return {"ok": False}
        node.last_heartbeat = time.monotonic()
        node.resources_available = dict(resources_available)
        node.pending_demand = dict(pending_demand or {})
        return {"ok": True}

    async def rpc_cluster_demand(self, conn):
        """Unplaceable-work summary for the autoscaler: queued task resources per
        node, actors stuck waiting for capacity (PENDING or RESTARTING, excluding
        those whose placement is already in flight), and unallocated PG bundles."""
        pending: dict[str, float] = {}
        for node in self.nodes.values():
            if not node.alive:
                continue
            for r, amt in node.pending_demand.items():
                pending[r] = pending.get(r, 0.0) + amt
        pending_actors = 0
        for actor in self.actors.values():
            if actor.state in (PENDING, RESTARTING) and not actor.placing:
                pending_actors += 1
                for r, amt in (actor.spec.get("resources") or {}).items():
                    pending[r] = pending.get(r, 0.0) + float(amt)
        for pg in self.placement_groups.values():
            if pg.state not in (ALIVE, DEAD):
                for bundle in pg.bundles:
                    for r, amt in bundle.items():
                        pending[r] = pending.get(r, 0.0) + float(amt)
        # Nodes that are NOT safe to downscale even when resource-idle: they host
        # live actors (zero-resource actors reserve nothing) or hold the only
        # copies of objects a consumer may still fetch.
        occupied: set = set()
        for actor in self.actors.values():
            if actor.state == ALIVE and actor.address:
                occupied.add(actor.address["node_id"])
        for entry in self.object_dir.values():
            for nid in entry["locations"]:
                occupied.add(nid)
        return {
            "pending": pending,
            "pending_actors": pending_actors,
            "occupied_nodes": [n.hex() for n in occupied],
        }

    async def rpc_get_nodes(self, conn):
        return [n.view() for n in self.nodes.values()]

    async def _on_node_lost(self, node_id: NodeID):
        node = self.nodes.get(node_id)
        if node is None or not node.alive:
            return
        node.alive = False
        await self.publish("nodes", {"event": "removed", "node": node.view()})
        # Fail actors on the dead node (restart where allowed).
        for actor in list(self.actors.values()):
            if actor.address and actor.address["node_id"] == node_id and actor.state == ALIVE:
                await self._handle_actor_failure(actor, f"node {node_id.hex()[:8]} died")
        # Drop object locations.
        for entry in self.object_dir.values():
            entry["locations"].discard(node_id)

    async def _death_check_loop(self):
        # A hung/partitioned raylet stops heartbeating without its TCP conn erroring;
        # stale heartbeat alone marks the node dead (conn close is handled eagerly).
        while True:
            await asyncio.sleep(CONFIG.heartbeat_interval_s)
            deadline = time.monotonic() - CONFIG.node_death_timeout_s
            for node in list(self.nodes.values()):
                if node.alive and node.last_heartbeat < deadline:
                    await self._on_node_lost(node.node_id)

    # ---------------- kv / functions / jobs ----------------

    async def rpc_kv_put(self, conn, namespace: str, key: bytes, value: bytes, overwrite=True):
        ns = self.kv.setdefault(namespace, {})
        if not overwrite and key in ns:
            # Idempotent retry detection: report success if the stored value is
            # already exactly what this put carried.
            return ns[key] == value
        ns[key] = value
        self.store.put("kv", (namespace, key), value)
        return True

    async def rpc_kv_get(self, conn, namespace: str, key: bytes):
        return self.kv.get(namespace, {}).get(key)

    async def rpc_kv_del(self, conn, namespace: str, key: bytes):
        existed = self.kv.get(namespace, {}).pop(key, None) is not None
        if existed:
            self.store.delete("kv", (namespace, key))
        return existed

    async def rpc_kv_keys(self, conn, namespace: str, prefix: bytes = b""):
        return [k for k in self.kv.get(namespace, {}) if k.startswith(prefix)]

    async def rpc_next_job_id(self, conn):
        self.job_counter += 1
        self.store.put("meta", "job_counter", self.job_counter)
        return JobID.from_int(self.job_counter)

    # ---------------- pubsub ----------------

    async def rpc_subscribe(self, conn, channel: str):
        self.subscribers.setdefault(channel, set()).add(conn)
        return True

    async def rpc_list_log_workers(self, conn):
        """Workers with retained log lines (dashboard log-viewer index)."""
        return [
            {"worker": wid, **entry["meta"], "lines": len(entry["buf"])}
            for wid, entry in self._recent_logs.items()
        ]

    async def rpc_get_worker_log(self, conn, worker_hex: str, limit: int = 200):
        entry = self._recent_logs.get(worker_hex)
        if entry is None or limit <= 0:
            return []
        return list(entry["buf"])[-limit:]

    def _retain_log_tail(self, message: dict):
        """Keep a bounded per-worker tail so the dashboard can show any
        worker's recent output without tailing files on its node (reference:
        dashboard log endpoints read the log_monitor's files; here the stream
        already flows through GCS pubsub, so a ring buffer rides along)."""
        wid = message.get("worker")
        if not wid:
            return
        entry = self._recent_logs.get(wid)
        if entry is None:
            if len(self._recent_logs) >= 512:
                # bound memory: drop the stalest worker's tail
                oldest = min(self._recent_logs,
                             key=lambda k: self._recent_logs[k]["at"])
                self._recent_logs.pop(oldest, None)
            entry = self._recent_logs[wid] = {
                "buf": deque(maxlen=400),
                "meta": {"kind": message.get("kind"),
                         "pid": message.get("pid"),
                         "node": message.get("node")},
                "at": 0.0,
            }
        entry["buf"].extend(message.get("lines", ()))
        entry["at"] = time.monotonic()

    async def rpc_publish_worker_logs(self, conn, message):
        """Raylet log monitor relay: retain a tail, then fan out to drivers."""
        self._retain_log_tail(message)
        await self.publish("worker_logs", message)
        return True

    async def rpc_unsubscribe(self, conn, channel: str):
        self.subscribers.get(channel, set()).discard(conn)
        return True

    # ---------------- object directory ----------------

    async def _report_object(self, conn, object_id: ObjectID, node_id: NodeID, size, owner):
        # Not an rpc_ verb: raylets batch directory traffic through
        # rpc_object_ops_batch; exposing this directly would be dead API
        # surface (raylint RL1006).
        entry = self.object_dir.setdefault(
            object_id, {"size": size, "owner": owner, "locations": set()}
        )
        entry["locations"].add(node_id)
        entry["size"] = size
        return True

    async def rpc_object_ops_batch(self, conn, ops: list):
        """Amortized directory update (raylets batch per-object seal/free
        traffic; on small hosts per-put GCS round trips dominated put cost).
        Ops apply in the order the raylet recorded them, so free-then-re-seal
        and seal-then-free sequences resolve exactly as unbatched calls would."""
        for op in ops:
            if op[0] == "report":
                _, object_id, node_id, size, owner = op
                await self._report_object(conn, object_id, node_id, size, owner)
            else:
                await self._free_object(conn, op[1])

    async def rpc_object_locations(self, conn, object_id: ObjectID):
        entry = self.object_dir.get(object_id)
        if entry is None:
            return None
        locs = []
        for nid in entry["locations"]:
            node = self.nodes.get(nid)
            if node is not None and node.alive:
                locs.append({"node_id": nid, "address": node.address})
        return {"size": entry["size"], "owner": entry["owner"], "locations": locs}

    async def _free_object(self, conn, object_id: ObjectID):
        # Not an rpc_ verb: reachable only through rpc_object_ops_batch (see
        # _report_object above).
        entry = self.object_dir.pop(object_id, None)
        if entry is None:
            return False
        for nid in entry["locations"]:
            node = self.nodes.get(nid)
            if node is not None and node.alive:
                try:
                    await node.conn.notify("evict_object", object_id)
                except Exception:
                    pass  # best-effort evict; a dead node has no copy to evict
        return True

    # ---------------- actors ----------------

    async def rpc_register_actor(self, conn, actor_id: ActorID, spec: dict):
        # Idempotent on the client-generated actor_id: a retry after a GCS crash
        # (applied but unacknowledged) must not re-register a fresh PENDING record
        # over a live/restoring actor.
        if actor_id in self.actors:
            return {"ok": True, "existing": False, "actor_id": actor_id}
        name = spec.get("name")
        ns = spec.get("namespace", "")
        if name:
            existing_id = self.named_actors.get((ns, name))
            if existing_id is not None:
                existing = self.actors.get(existing_id)
                if existing is not None and existing.state != DEAD:
                    if spec.get("get_if_exists"):
                        return {"ok": True, "existing": True, "actor_id": existing_id}
                    raise ValueError(f"actor with name {name!r} already exists in namespace {ns!r}")
        actor = ActorInfo(actor_id, spec)
        self.actors[actor_id] = actor
        if name:
            self.named_actors[(ns, name)] = actor_id
        self._persist_actor(actor)
        asyncio.get_running_loop().create_task(self._schedule_actor(actor))
        return {"ok": True, "existing": False, "actor_id": actor_id}

    def _persist_actor(self, actor: ActorInfo):
        self.store.put("actors", actor.actor_id, {
            "spec": actor.spec,
            "restarts_left": actor.restarts_left,
            "num_restarts": actor.num_restarts,
        })

    def _pick_node_for(self, resources: dict, scheduling=None) -> NodeInfo | None:
        """Reference: GcsActorScheduler + hybrid policy + label policy
        (`node_label_scheduling_policy.cc`). Greedy best-fit over alive nodes;
        composite strategies take the first sub-strategy with any candidate."""
        if scheduling and scheduling.get("composite"):
            # Same semantics as the raylet task path: a sub-strategy is
            # COMMITTED when any node's TOTAL supply can ever satisfy it —
            # transient busyness waits (the caller retries) rather than
            # falling through to a weaker sub, so actors and tasks place
            # identically under one strategy.
            for sub in scheduling["composite"]:
                node = self._pick_node_for(resources, sub or None)
                if node is not None:
                    return node
                if self._satisfiable_by_total(resources, sub or None):
                    return None  # right sub, currently busy: wait here
            return None
        from ray_tpu.util.scheduling_strategies import match_labels

        labels = (scheduling or {}).get("labels") or {}
        candidates = []
        for node in self.nodes.values():
            if not node.alive:
                continue
            if scheduling and scheduling.get("node_id") is not None:
                if node.node_id != scheduling["node_id"]:
                    continue
            if labels.get("hard") and not match_labels(node.labels, labels["hard"]):
                continue
            if all(node.resources_available.get(r, 0) >= amt for r, amt in resources.items()):
                candidates.append(node)
        if not candidates:
            return None
        soft = labels.get("soft")
        if soft:
            preferred = [n for n in candidates if match_labels(n.labels, soft)]
            if preferred:
                candidates = preferred
        # Pack onto the most-utilized feasible node (hybrid default behavior).
        def utilization(n: NodeInfo):
            tot = sum(n.resources_total.values()) or 1
            avail = sum(n.resources_available.values())
            return (tot - avail) / tot

        return max(candidates, key=utilization)

    def _satisfiable_by_total(self, resources: dict, scheduling) -> bool:
        """Could ANY alive node ever run this (total supply, labels, affinity)?"""
        from ray_tpu.util.scheduling_strategies import match_labels

        hard = ((scheduling or {}).get("labels") or {}).get("hard")
        for node in self.nodes.values():
            if not node.alive:
                continue
            if scheduling and scheduling.get("node_id") is not None:
                if node.node_id != scheduling["node_id"]:
                    continue
            if hard and not match_labels(node.labels, hard):
                continue
            if all(node.resources_total.get(r, 0) >= amt
                   for r, amt in resources.items()):
                return True
        return False

    def _node_for_pg_bundle(self, pg_spec: dict) -> NodeInfo | None:
        """PG-bound actors go to their bundle's allocated node — the bundle has
        the resources RESERVED there, so availability-based picking would (a)
        land elsewhere and (b) find nothing when the bundle claims a node's
        whole supply (reference: bundle scheduling policy)."""
        pg = self.placement_groups.get(pg_spec.get("pg_id"))
        if pg is None or pg.state != ALIVE:
            return None
        idx = pg_spec.get("bundle_index", 0)
        if idx >= len(pg.allocations) or pg.allocations[idx] is None:
            return None
        node = self.nodes.get(pg.allocations[idx])
        return node if node is not None and node.alive else None

    async def _schedule_actor(self, actor: ActorInfo, retries: int = 60):
        spec = actor.spec
        resources = dict(spec.get("resources") or {})
        pg_spec = spec.get("placement_group")
        for attempt in range(retries):
            if actor.kill_requested or actor.state == DEAD:
                return  # killed while waiting for placement: nothing to reap yet
            if pg_spec:
                node = self._node_for_pg_bundle(pg_spec)
            else:
                node = self._pick_node_for(resources, spec.get("scheduling_strategy"))
            if node is None:
                actor.placing = False  # truly unplaceable: autoscaler demand
                await asyncio.sleep(0.25)
                continue
            actor.placing = True  # placement in flight: resources already picked
            try:
                result = await node.conn.call("create_actor", actor.actor_id, spec)
            except Exception:
                actor.placing = False
                await asyncio.sleep(0.1)
                continue
            if result.get("ok"):
                if actor.kill_requested or actor.state == DEAD:
                    # kill() landed during the create_actor flight. The raylet
                    # registered the binding just now, so the kill can finally
                    # reach the worker — without this, the worker and its
                    # resources outlive the DEAD actor forever.
                    try:
                        await node.conn.call("kill_actor_worker", actor.actor_id)
                    except Exception:
                        pass  # raylet gone: the worker is dying with its node anyway
                    if actor.state != DEAD:
                        await self._mark_actor_dead(
                            actor, "killed via ray_tpu.kill (during creation)"
                        )
                    return
                actor.state = ALIVE
                actor.address = {"node_id": node.node_id,
                                 "worker_id": result["worker_id"],
                                 "direct_addr": self._vet_direct_addr(
                                     node.node_id, result.get("direct_addr"))}
                await self.publish("actors", {"actor": actor.view()})
                ev = self._actor_events.pop(actor.actor_id, None)
                if ev:
                    ev.set()
                return
            if result.get("fatal"):
                # Application error in __init__: surface it, don't retry 60 workers.
                await self._mark_actor_dead(actor, result.get("reason", "actor __init__ failed"))
                return
            await asyncio.sleep(0.1)
        avail = {
            n.node_id.hex()[:8]: dict(n.resources_available)
            for n in self.nodes.values() if n.alive
        }
        async def probe(n):
            try:
                stats = await asyncio.wait_for(n.conn.call("node_stats"), 5)
            except Exception:
                return None  # unreachable node: reported as no stats, not an error
            hs = stats.get("resource_holders") or []
            for h in hs:
                prefix = h.get("actor_id") or ""
                for aid, info in self.actors.items():
                    if prefix and aid.hex().startswith(prefix):
                        h["actor_class"] = str(
                            (info.spec or {}).get("class_name")
                            or (info.spec or {}).get("name")
                        )
                        h["actor_state"] = info.state
                        h["restarts"] = info.num_restarts
                        break
            return (n.node_id.hex()[:8], hs)

        alive = [n for n in self.nodes.values() if n.alive]
        holders = dict(
            r for r in await asyncio.gather(*(probe(n) for n in alive)) if r
        )
        await self._mark_actor_dead(
            actor, "unschedulable: no node with resources " + repr(resources)
            + f" (alive-node availability: {avail!r}; holders: {holders!r})"
        )

    async def _mark_actor_dead(self, actor: ActorInfo, reason: str):
        actor.state = DEAD
        actor.death_cause = reason
        self.store.delete("actors", actor.actor_id)
        if actor.name:
            self.named_actors.pop((actor.namespace, actor.name), None)
        await self.publish("actors", {"actor": actor.view()})
        ev = self._actor_events.pop(actor.actor_id, None)
        if ev:
            ev.set()

    async def rpc_wait_actor_alive(self, conn, actor_id: ActorID, timeout: float = 60.0):
        actor = self.actors.get(actor_id)
        if actor is None:
            raise ValueError(f"unknown actor {actor_id}")
        if actor.state in (ALIVE, DEAD):
            return actor.view()
        ev = self._actor_events.setdefault(actor_id, asyncio.Event())
        try:
            await asyncio.wait_for(ev.wait(), timeout)
        except asyncio.TimeoutError:
            pass
        return actor.view()

    async def rpc_get_actor_info(self, conn, actor_id: ActorID = None, name: str = None, namespace: str = ""):
        if actor_id is None and name is not None:
            actor_id = self.named_actors.get((namespace, name))
            if actor_id is None:
                return None
        actor = self.actors.get(actor_id)
        return actor.view() if actor else None

    async def rpc_list_actors(self, conn):
        return [a.view() for a in self.actors.values()]

    async def rpc_actor_failed(self, conn, actor_id: ActorID, reason: str):
        actor = self.actors.get(actor_id)
        if actor is None or actor.state == DEAD:
            return False
        await self._handle_actor_failure(actor, reason)
        return True

    async def rpc_kill_actor(self, conn, actor_id: ActorID, no_restart: bool = True):
        actor = self.actors.get(actor_id)
        if actor is None:
            return False
        if no_restart:
            actor.restarts_left = 0
            # If a create_actor RPC is in flight, only the schedule coroutine
            # will ever learn the worker binding — flag it to reap on return.
            actor.kill_requested = True
        if actor.address is not None:
            node = self.nodes.get(actor.address["node_id"])
            if node is not None and node.alive:
                try:
                    await node.conn.call("kill_actor_worker", actor.actor_id)
                except Exception:
                    pass  # raylet gone: node death reaps the actor's worker
        if actor.state == DEAD:
            return True
        if actor.restarts_left != 0:
            if actor.placing and actor.address is None:
                # Creation still in flight: the schedule coroutine owns
                # placement. Restart-killing a not-yet-started actor is a
                # no-op; a second _schedule_actor here would double-create
                # and leak the first worker's resources.
                return True
            # kill(no_restart=False): restart immediately, per the kill contract.
            await self._handle_actor_failure(actor, "killed via ray_tpu.kill (restarting)")
        else:
            await self._mark_actor_dead(actor, "killed via ray_tpu.kill")
        return True

    async def _handle_actor_failure(self, actor: ActorInfo, reason: str):
        if actor.restarts_left != 0:
            if actor.restarts_left > 0:
                actor.restarts_left -= 1
            actor.num_restarts += 1
            self._persist_actor(actor)
            actor.state = RESTARTING
            actor.address = None
            await self.publish("actors", {"actor": actor.view()})
            await self._schedule_actor(actor)
        else:
            await self._mark_actor_dead(actor, reason)

    # ---------------- placement groups ----------------

    async def rpc_create_placement_group(self, conn, pg_id: PlacementGroupID, bundles, strategy, name=""):
        if pg_id in self.placement_groups:
            # Idempotent under gcs_call's reconnect-retry (same guard as
            # rpc_register_actor): a replay must not re-reserve bundles.
            return True
        pg = PlacementGroupInfo(pg_id, bundles, strategy, name)
        self.placement_groups[pg_id] = pg
        self.store.put("pgs", pg_id, {"bundles": bundles, "strategy": strategy, "name": name})
        asyncio.get_running_loop().create_task(self._schedule_pg(pg))
        return True

    async def _schedule_pg(self, pg: PlacementGroupInfo, retries: int = 120):
        """Reference: gcs_placement_group_scheduler bundle placement (PACK/SPREAD/STRICT_*)."""
        for attempt in range(retries):
            plan = self._plan_bundles(pg)
            if plan is None:
                await asyncio.sleep(0.25)
                continue
            ok = True
            reserved: list[tuple[NodeInfo, int]] = []
            for bundle_index, node in plan:
                try:
                    res = await node.conn.call(
                        "reserve_bundle", pg.pg_id, bundle_index, pg.bundles[bundle_index]
                    )
                except Exception:
                    res = False
                if not res:
                    ok = False
                    break
                reserved.append((node, bundle_index))
            if ok:
                for node, bundle_index in reserved:
                    pg.allocations[bundle_index] = node.node_id
                pg.state = ALIVE
                pg.ready_event.set()
                await self.publish("placement_groups", {"pg_id": pg.pg_id, "state": ALIVE})
                return
            for node, bundle_index in reserved:  # roll back partial reservation
                try:
                    await node.conn.call("cancel_bundle", pg.pg_id, bundle_index)
                except Exception:
                    pass  # rollback to a dead node is moot; retry loop continues
            await asyncio.sleep(0.25)
        pg.state = DEAD
        pg.ready_event.set()

    def _plan_bundles(self, pg: PlacementGroupInfo):
        alive = [n for n in self.nodes.values() if n.alive]
        if not alive:
            return None
        avail = {n.node_id: dict(n.resources_available) for n in alive}
        by_id = {n.node_id: n for n in alive}

        def fits(nid, bundle):
            return all(avail[nid].get(r, 0) >= amt for r, amt in bundle.items())

        def take(nid, bundle):
            for r, amt in bundle.items():
                avail[nid][r] = avail[nid].get(r, 0) - amt

        plan = []
        if pg.strategy == "STRICT_PACK":
            # All bundles must fit on one node.
            for nid in avail:
                trial = dict(avail[nid])
                feasible = True
                for bundle in pg.bundles:
                    if all(trial.get(r, 0) >= amt for r, amt in bundle.items()):
                        for r, amt in bundle.items():
                            trial[r] = trial.get(r, 0) - amt
                    else:
                        feasible = False
                        break
                if feasible:
                    return [(i, by_id[nid]) for i in range(len(pg.bundles))]
            return None
        if pg.strategy in ("STRICT_SPREAD",):
            used_nodes = set()
            for i, bundle in enumerate(pg.bundles):
                placed = False
                for nid in avail:
                    if nid in used_nodes or not fits(nid, bundle):
                        continue
                    take(nid, bundle)
                    used_nodes.add(nid)
                    plan.append((i, by_id[nid]))
                    placed = True
                    break
                if not placed:
                    return None
            return plan
        # PACK / SPREAD: best effort; PACK prefers fewest nodes, SPREAD round-robins.
        order = list(avail)
        rr = 0
        for i, bundle in enumerate(pg.bundles):
            placed = False
            span = order if pg.strategy == "PACK" else order[rr:] + order[:rr]
            for nid in span:
                if fits(nid, bundle):
                    take(nid, bundle)
                    plan.append((i, by_id[nid]))
                    placed = True
                    rr = (order.index(nid) + 1) % len(order)
                    break
            if not placed:
                return None
        return plan

    async def rpc_pg_wait_ready(self, conn, pg_id: PlacementGroupID, timeout: float = 60.0):
        pg = self.placement_groups.get(pg_id)
        if pg is None:
            raise ValueError(f"unknown placement group {pg_id}")
        try:
            await asyncio.wait_for(pg.ready_event.wait(), timeout)
        except asyncio.TimeoutError:
            pass
        return {"state": pg.state, "allocations": pg.allocations, "bundles": pg.bundles}

    async def rpc_remove_placement_group(self, conn, pg_id: PlacementGroupID):
        pg = self.placement_groups.pop(pg_id, None)
        self.store.delete("pgs", pg_id)
        if pg is None:
            return False
        for bundle_index, nid in enumerate(pg.allocations):
            if nid is None:
                continue
            node = self.nodes.get(nid)
            if node is not None and node.alive:
                try:
                    await node.conn.call("cancel_bundle", pg.pg_id, bundle_index)
                except Exception:
                    pass  # node died: its bundles are already released
        return True

    async def rpc_list_objects(self, conn, limit: int = 1000):
        out = []
        for oid, entry in self.object_dir.items():
            owner = entry.get("owner") or {}
            owner_worker = owner.get("worker_id")
            owner_node = owner.get("node_id")
            out.append({
                "object_id": oid.hex(),
                "size": entry["size"],
                "num_locations": len(entry["locations"]),
                "owner_worker_id": owner_worker.hex() if owner_worker else None,
                "owner_node_id": owner_node.hex() if owner_node else None,
            })
            if len(out) >= limit:
                break
        return out

    async def rpc_list_placement_groups(self, conn):
        return [
            {"pg_id": pg.pg_id, "state": pg.state, "strategy": pg.strategy, "name": pg.name}
            for pg in self.placement_groups.values()
        ]

    # ---------------- task events (observability) ----------------

    async def rpc_report_task_events(self, conn, events: list):
        """Task events persist in chunk-sized store records (a GCS restart
        keeps the timeline; reference round-2 gap: events were memory-only).
        Trimming drops whole chunks from memory AND the store, so the log
        cannot grow unboundedly."""
        self.task_events.extend(events)
        self._index_task_events(events)
        self._export_events("task", events)
        self._task_events_total += len(events)
        self._task_event_seq += 1
        seq = self._task_event_seq
        self.store.put("task_events", seq, events)
        self._task_event_chunks.append((seq, len(events)))
        max_events = CONFIG.gcs_max_task_events
        excess = len(self.task_events) - max_events
        while excess > 0 and self._task_event_chunks:
            old_seq, count = self._task_event_chunks[0]
            if count > excess:
                break  # only whole chunks are dropped; a little slack is fine
            self._task_event_chunks.popleft()
            self.store.delete("task_events", old_seq)
            for e in self.task_events[:count]:
                self._unindex_task_event(e)
            del self.task_events[:count]
            excess -= count
        return True

    def _index_task_events(self, events: list):
        """Per-task index (references into the retained log): get_task and
        task_id-filtered listings serve straight from it instead of scanning
        the retention window."""
        idx = getattr(self, "_task_event_index", None)
        if idx is None:
            idx = self._task_event_index = {}
            for e in self.task_events[:-len(events) or None]:
                tid = e.get("task_id")
                if tid is not None:
                    idx.setdefault(tid, []).append(e)
        for e in events:
            tid = e.get("task_id")
            if tid is not None:
                idx.setdefault(tid, []).append(e)

    def _unindex_task_event(self, e: dict):
        idx = getattr(self, "_task_event_index", None)
        tid = e.get("task_id")
        if idx is None or tid is None:
            return
        lst = idx.get(tid)
        if lst:
            # Trims drop the oldest events log-wide; within one task's list
            # that is always the head.
            if lst[0] is e:
                lst.pop(0)
            else:  # restored-from-store objects: fall back to equality
                try:
                    lst.remove(e)
                except ValueError:
                    pass
            if not lst:
                del idx[tid]

    @staticmethod
    def _event_pred(filters):
        """The state API's filter predicates, evaluated server-side
        (reference: GcsTaskManager filters, gcs_task_manager.h — the query
        is pushed down so `ray_tpu list tasks -f k=v` never ships the whole
        retention window). Shared with the client via state_filters so both
        sides always compare identically."""
        from ray_tpu._private.state_filters import build_predicate

        return build_predicate(filters)

    async def rpc_list_task_events(self, conn, limit: int = 1000,
                                   filters=None, offset: int = 0,
                                   task_id=None):
        if task_id is not None:
            if getattr(self, "_task_event_index", None) is None:
                self._index_task_events([])
            rows = list(self._task_event_index.get(task_id, ()))
            if filters:
                match = self._event_pred(filters)
                rows = [e for e in rows if match(e)]
            return rows[offset:offset + limit] if limit else rows[offset:]
        if not filters and not offset:
            return self.task_events[-limit:] if limit else list(self.task_events)
        # Streamed filter scan with early exit: collect offset+limit matches
        # in log order and stop — matching pages never require materializing
        # (or shipping) the whole retention window.
        match = self._event_pred(filters or ())
        out = []
        want = offset + limit if limit else None
        for e in self.task_events:
            if match(e):
                out.append(e)
                if want is not None and len(out) >= want:
                    break
        return out[offset:]

    async def rpc_task_event_stats(self, conn):
        """Cheap counters for samplers (no event payloads cross the wire)."""
        return {"total": self._task_events_total, "retained": len(self.task_events)}

    async def rpc_list_dag_op_events(self, conn, prefix: str):
        """Latest compiled-DAG per-op profile event per id, filtered server-side
        (shipping the whole retained event log per profile call is 100k dicts)."""
        latest: dict[str, dict] = {}
        for e in self.task_events:
            tid = str(e.get("task_id", ""))
            if e.get("dag_op") and tid.startswith(prefix):
                latest[tid] = e  # log order: the last occurrence is newest
        return list(latest.values())

    # ---------------- replication-plane surface (single-candidate answers)

    def _repl_view(self) -> dict:
        """A lone GCS answers the replicated-mode surface so clients can use
        ONE probe/redirect path regardless of `gcs_replicas` (with one
        candidate there is nobody else to be primary)."""
        return {
            "role": "primary", "epoch": 0, "seq": 0, "promised": 0,
            "candidate_id": 0, "replicas": 1, "primary": None,
            "failovers": 0, "lag": {},
        }

    async def rpc_repl_status(self, conn):
        view = self._repl_view()
        if hasattr(self.store, "stats_view"):
            view["store"] = self.store.stats_view()
        return view

    async def rpc_store_stats(self, conn):
        store = (self.store.stats_view()
                 if hasattr(self.store, "stats_view") else {})
        return {"store": store, "repl": self._repl_view()}

    async def rpc_cluster_resources(self, conn):
        total: dict[str, float] = {}
        avail: dict[str, float] = {}
        for node in self.nodes.values():
            if not node.alive:
                continue
            for r, amt in node.resources_total.items():
                total[r] = total.get(r, 0) + amt
            for r, amt in node.resources_available.items():
                avail[r] = avail.get(r, 0) + amt
        return {"total": total, "available": avail}
