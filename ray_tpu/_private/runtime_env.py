"""Runtime environments: per-task/per-actor env vars, working_dir, py_modules.

Design parity: reference `python/ray/_private/runtime_env/` — the per-lease
environment prepared before a worker runs user code. Here the common plugins are
applied in-process: `env_vars`, `working_dir` (chdir + sys.path), `py_modules`
(sys.path additions). Paths must be visible on the executing node (shared
filesystem or same machine); package-installing plugins (pip/uv/conda) are a later
round — they need the reference's per-env virtualenv cache keyed into the worker
pool.

Isolation model: actors own their worker process, so their env applies permanently.
Plain tasks share a threaded worker, so process-global mutations (os.environ, cwd,
sys.path) are guarded by a reader/writer lock — a task WITH a runtime_env runs
exclusively on its worker; tasks without one run concurrently as before. Modules
imported from a task's py_modules/working_dir are evicted from sys.modules on
restore so later tasks can't silently pick up stale code.
"""

from __future__ import annotations

import contextlib
import os
import sys
import threading
from typing import Any, Dict, Optional

_SUPPORTED = {"env_vars", "working_dir", "py_modules"}


def validate(runtime_env: Optional[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    if not runtime_env:
        return None
    unknown = set(runtime_env) - _SUPPORTED
    if unknown:
        raise ValueError(
            f"unsupported runtime_env keys {sorted(unknown)}; "
            f"supported: {sorted(_SUPPORTED)}"
        )
    env_vars = runtime_env.get("env_vars") or {}
    if not all(isinstance(k, str) and isinstance(v, str) for k, v in env_vars.items()):
        raise ValueError("runtime_env env_vars must be str -> str")
    wd = runtime_env.get("working_dir")
    if wd is not None and not isinstance(wd, (str, os.PathLike)):
        raise ValueError(f"runtime_env working_dir must be a path, got {type(wd).__name__}")
    mods = runtime_env.get("py_modules")
    if mods is not None:
        if isinstance(mods, (str, os.PathLike)) or not all(
            isinstance(m, (str, os.PathLike)) for m in mods
        ):
            raise ValueError("runtime_env py_modules must be a LIST of paths")
    return dict(runtime_env)


class _RWLock:
    """Many concurrent env-free tasks OR one env-carrying task per process."""

    def __init__(self):
        self._cond = threading.Condition()
        self._readers = 0
        self._writer = False

    @contextlib.contextmanager
    def shared(self):
        with self._cond:
            while self._writer:
                self._cond.wait()
            self._readers += 1
        try:
            yield
        finally:
            with self._cond:
                self._readers -= 1
                self._cond.notify_all()

    @contextlib.contextmanager
    def exclusive(self):
        with self._cond:
            while self._writer or self._readers:
                self._cond.wait()
            self._writer = True
        try:
            yield
        finally:
            with self._cond:
                self._writer = False
                self._cond.notify_all()


_lock = _RWLock()


def _env_paths(runtime_env: Dict[str, Any]) -> list:
    paths = []
    wd = runtime_env.get("working_dir")
    if wd:
        paths.append(os.path.abspath(os.path.expanduser(str(wd))))
    for m in runtime_env.get("py_modules") or []:
        paths.append(os.path.abspath(os.path.expanduser(str(m))))
    return paths


def _apply(runtime_env: Dict[str, Any], saved_env: Optional[Dict[str, Optional[str]]]):
    """Apply the env; when saved_env is a dict, record prior values for restore."""
    for k, v in (runtime_env.get("env_vars") or {}).items():
        if saved_env is not None:
            saved_env[k] = os.environ.get(k)
        os.environ[k] = v
    wd = runtime_env.get("working_dir")
    if wd:
        wd = os.path.abspath(os.path.expanduser(str(wd)))
        os.chdir(wd)
        if wd not in sys.path:
            sys.path.insert(0, wd)
    for mod_path in runtime_env.get("py_modules") or []:
        mod_path = os.path.abspath(os.path.expanduser(str(mod_path)))
        if mod_path not in sys.path:
            sys.path.insert(0, mod_path)


def apply_permanent(runtime_env: Optional[Dict[str, Any]]):
    """Actor path: the actor owns its worker process, so mutate it directly."""
    if not runtime_env:
        return
    _apply(runtime_env, saved_env=None)


@contextlib.contextmanager
def applied(runtime_env: Optional[Dict[str, Any]]):
    """Task path: apply exclusively around one execution, then restore.

    The rw-lock keeps concurrent env-free tasks from observing (or clobbering)
    another task's env; env-free tasks take the shared side and stay concurrent.
    """
    if not runtime_env:
        with _lock.shared():
            yield
        return
    with _lock.exclusive():
        saved_env: Dict[str, Optional[str]] = {}
        saved_cwd = os.getcwd()
        saved_path = list(sys.path)
        saved_modules = set(sys.modules)
        env_paths = _env_paths(runtime_env)
        try:
            _apply(runtime_env, saved_env)
            yield
        finally:
            for k, old in saved_env.items():
                if old is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = old
            try:
                os.chdir(saved_cwd)
            except OSError:
                pass
            sys.path[:] = saved_path
            # Evict modules this task imported from ITS paths: a later task with a
            # different py_modules version must not silently get this one's code.
            for name in set(sys.modules) - saved_modules:
                mod_file = getattr(sys.modules.get(name), "__file__", None) or ""
                if any(mod_file.startswith(p + os.sep) or mod_file == p
                       for p in env_paths):
                    sys.modules.pop(name, None)
