"""Runtime environments: per-task/per-actor env vars, working_dir, py_modules.

Design parity: reference `python/ray/_private/runtime_env/` — the per-lease
environment prepared before a worker runs user code. Here the common plugins are
applied in-process: `env_vars`, `working_dir` (chdir + sys.path), `py_modules`
(sys.path additions). Paths must be visible on the executing node (shared
filesystem or same machine); package-installing plugins (pip/uv/conda) are a later
round — they need the reference's per-env virtualenv cache keyed into the worker
pool.

Isolation model: actors own their worker process, so their env applies permanently.
Plain tasks share a threaded worker, so process-global mutations (os.environ, cwd,
sys.path) are guarded by a reader/writer lock — a task WITH a runtime_env runs
exclusively on its worker; tasks without one run concurrently as before. Modules
imported from a task's py_modules/working_dir are evicted from sys.modules on
restore so later tasks can't silently pick up stale code.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import os
import subprocess
import sys
import threading
from typing import Any, Dict, Optional

_SUPPORTED = {"env_vars", "working_dir", "py_modules", "pip", "uv", "conda",
              "image_uri"}


def _normalize_pip(spec) -> Dict[str, Any]:
    """Accept ["pkg", ...] or {"packages": [...], "find_links": path}."""
    if isinstance(spec, (list, tuple)):
        spec = {"packages": list(spec)}
    if not isinstance(spec, dict) or not isinstance(spec.get("packages"), list):
        raise ValueError(
            'runtime_env pip/uv must be a list of requirements or {"packages": [...]}'
        )
    out = {"packages": [str(p) for p in spec["packages"]]}
    if spec.get("find_links"):
        out["find_links"] = os.path.abspath(os.path.expanduser(str(spec["find_links"])))
    return out


def validate(runtime_env: Optional[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    if not runtime_env:
        return None
    unknown = set(runtime_env) - _SUPPORTED
    if unknown:
        raise ValueError(
            f"unsupported runtime_env keys {sorted(unknown)}; "
            f"supported: {sorted(_SUPPORTED)}"
        )
    runtime_env = dict(runtime_env)
    # "uv" is an alias for "pip" (same venv mechanism; uv used when available).
    if "uv" in runtime_env:
        if "pip" in runtime_env:
            raise ValueError("pass either pip or uv, not both")
        runtime_env["pip"] = runtime_env.pop("uv")
    if "pip" in runtime_env:
        runtime_env["pip"] = _normalize_pip(runtime_env["pip"])
    if "conda" in runtime_env:
        conda = runtime_env["conda"]
        if not (isinstance(conda, str)
                or (isinstance(conda, dict) and isinstance(
                    conda.get("dependencies"), list))):
            raise ValueError(
                'runtime_env conda must be an env name or {"dependencies": [...]}'
            )
        if "pip" in runtime_env:
            raise ValueError("pass either pip or conda, not both")
    if "image_uri" in runtime_env:
        if not isinstance(runtime_env["image_uri"], str):
            raise ValueError("runtime_env image_uri must be a string")
        if "pip" in runtime_env or "conda" in runtime_env:
            # The image defines the interpreter environment wholesale
            # (reference image_uri.py: container excludes pip/conda).
            raise ValueError("image_uri cannot be combined with pip/conda")
    env_vars = runtime_env.get("env_vars") or {}
    if not all(isinstance(k, str) and isinstance(v, str) for k, v in env_vars.items()):
        raise ValueError("runtime_env env_vars must be str -> str")
    wd = runtime_env.get("working_dir")
    if wd is not None and not isinstance(wd, (str, os.PathLike)):
        raise ValueError(f"runtime_env working_dir must be a path, got {type(wd).__name__}")
    mods = runtime_env.get("py_modules")
    if mods is not None:
        if isinstance(mods, (str, os.PathLike)) or not all(
            isinstance(m, (str, os.PathLike)) for m in mods
        ):
            raise ValueError("runtime_env py_modules must be a LIST of paths")
    return dict(runtime_env)


class _RWLock:
    """Many concurrent env-free tasks OR one env-carrying task per process."""

    def __init__(self):
        self._cond = threading.Condition()
        self._readers = 0
        self._writer = False

    @contextlib.contextmanager
    def shared(self):
        with self._cond:
            while self._writer:
                self._cond.wait()
            self._readers += 1
        try:
            yield
        finally:
            with self._cond:
                self._readers -= 1
                self._cond.notify_all()

    @contextlib.contextmanager
    def exclusive(self):
        with self._cond:
            while self._writer or self._readers:
                self._cond.wait()
            self._writer = True
        try:
            yield
        finally:
            with self._cond:
                self._writer = False
                self._cond.notify_all()


_lock = _RWLock()


def _env_paths(runtime_env: Dict[str, Any]) -> list:
    paths = []
    wd = runtime_env.get("working_dir")
    if wd:
        paths.append(os.path.abspath(os.path.expanduser(str(wd))))
    for m in runtime_env.get("py_modules") or []:
        paths.append(os.path.abspath(os.path.expanduser(str(m))))
    return paths


def _apply(runtime_env: Dict[str, Any], saved_env: Optional[Dict[str, Optional[str]]]):
    """Apply the env; when saved_env is a dict, record prior values for restore."""
    for k, v in (runtime_env.get("env_vars") or {}).items():
        if saved_env is not None:
            saved_env[k] = os.environ.get(k)
        os.environ[k] = v
    wd = runtime_env.get("working_dir")
    if wd:
        wd = os.path.abspath(os.path.expanduser(str(wd)))
        os.chdir(wd)
        if wd not in sys.path:
            sys.path.insert(0, wd)
    for mod_path in runtime_env.get("py_modules") or []:
        mod_path = os.path.abspath(os.path.expanduser(str(mod_path)))
        if mod_path not in sys.path:
            sys.path.insert(0, mod_path)


def apply_permanent(runtime_env: Optional[Dict[str, Any]]):
    """Actor path: the actor owns its worker process, so mutate it directly."""
    if not runtime_env:
        return
    _apply(runtime_env, saved_env=None)


@contextlib.contextmanager
def applied(runtime_env: Optional[Dict[str, Any]]):
    """Task path: apply exclusively around one execution, then restore.

    The rw-lock keeps concurrent env-free tasks from observing (or clobbering)
    another task's env; env-free tasks take the shared side and stay concurrent.
    """
    if not runtime_env:
        with _lock.shared():
            yield
        return
    with _lock.exclusive():
        saved_env: Dict[str, Optional[str]] = {}
        saved_cwd = os.getcwd()
        saved_path = list(sys.path)
        saved_modules = set(sys.modules)
        env_paths = _env_paths(runtime_env)
        try:
            _apply(runtime_env, saved_env)
            yield
        finally:
            for k, old in saved_env.items():
                if old is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = old
            try:
                os.chdir(saved_cwd)
            except OSError:
                pass
            sys.path[:] = saved_path
            # Evict modules this task imported from ITS paths: a later task with a
            # different py_modules version must not silently get this one's code.
            for name in set(sys.modules) - saved_modules:
                mod_file = getattr(sys.modules.get(name), "__file__", None) or ""
                if any(mod_file.startswith(p + os.sep) or mod_file == p
                       for p in env_paths):
                    sys.modules.pop(name, None)


# -- pip/uv virtualenv plugin ----------------------------------------------
# Reference: python/ray/_private/runtime_env/pip.py + uv.py — the per-node
# runtime-env agent materializes a virtualenv per unique pip spec and the worker
# pool launches (and caches) workers inside it (worker_pool.h runtime-env-keyed
# pools). Installs run OFFLINE (--no-index [+ --find-links]) — this framework
# targets air-gapped TPU pods; point find_links at a local wheel house.


def env_key(runtime_env: Optional[Dict[str, Any]]) -> Optional[str]:
    """Stable key for the parts of a runtime_env that require a DEDICATED worker
    process (a different interpreter); None means any vanilla worker can serve
    it (env_vars/working_dir/py_modules apply in-process)."""
    if not runtime_env:
        return None
    dedicated = {k: runtime_env[k] for k in ("pip", "conda", "image_uri")
                 if k in runtime_env}
    if not dedicated:
        return None
    blob = json.dumps(dedicated, sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


def ensure_pip_env(runtime_env: Dict[str, Any], cache_root: str) -> str:
    """Materialize (or reuse) the venv for a pip spec; returns its python path.

    Venvs are cached by spec hash under `cache_root` (reference: uri_cache.py),
    created with --system-site-packages so the baked-in jax/numpy stack stays
    visible beneath the env's own packages.
    """
    key = env_key(runtime_env)
    spec = runtime_env["pip"]
    final = os.path.join(cache_root, f"venv_{key}")
    final_python = os.path.join(final, "bin", "python")
    stamp_name = ".ready"
    if os.path.exists(os.path.join(final, stamp_name)):
        return final_python
    os.makedirs(cache_root, exist_ok=True)
    # Cross-process safety (several raylets can share one cache root): build in
    # a private tmp dir, then atomically rename into place; the loser of the
    # rename race discards its build and uses the winner's.
    path = final + f".build{os.getpid()}"
    python = os.path.join(path, "bin", "python")
    try:
        subprocess.run(
            [sys.executable, "-m", "venv", "--system-site-packages", path],
            check=True, capture_output=True, timeout=120,
        )
        # When the parent interpreter is ITSELF a venv (the common container
        # layout), --system-site-packages exposes the base python's site dir,
        # not the parent venv's — link the parent's site-packages explicitly so
        # the baked-in jax/numpy stack stays importable beneath the new env.
        import sysconfig

        parent_purelib = sysconfig.get_paths()["purelib"]
        venv_purelib = subprocess.run(
            [python, "-c",
             "import sysconfig; print(sysconfig.get_paths()['purelib'])"],
            check=True, capture_output=True, timeout=60, text=True,
        ).stdout.strip()
        with open(os.path.join(venv_purelib, "_ray_tpu_parent.pth"), "w") as f:
            f.write(parent_purelib + "\n")
        if spec["packages"]:
            import shutil

            uv = shutil.which("uv")
            if uv:
                cmd = [uv, "pip", "install", "--python", python, "--no-index"]
            else:
                cmd = [python, "-m", "pip", "install", "--no-index", "--quiet",
                       "--no-build-isolation"]
            if spec.get("find_links"):
                cmd += ["--find-links", spec["find_links"]]
            cmd += spec["packages"]
            proc = subprocess.run(cmd, capture_output=True, timeout=600)
            if proc.returncode != 0:
                raise RuntimeError(
                    f"pip env install failed:\n{proc.stderr.decode(errors='replace')[-2000:]}"
                )
        with open(os.path.join(path, stamp_name), "w") as f:
            f.write(json.dumps(spec))
        try:
            os.rename(path, final)
        except OSError:
            # Another process installed the same env first; keep theirs. The
            # renamed venv keeps working because only `<venv>/bin/python -m` is
            # ever invoked (console-script shebangs bake the build path, unused).
            import shutil

            shutil.rmtree(path, ignore_errors=True)
            if not os.path.exists(os.path.join(final, stamp_name)):
                raise
        return final_python
    except Exception:
        import shutil

        shutil.rmtree(path, ignore_errors=True)
        raise


def ensure_conda_env(runtime_env: Dict[str, Any], cache_root: str,
                     conda_exe: Optional[str] = None) -> str:
    """Resolve (named env) or materialize (spec dict) a conda env; returns its
    python path. Parity: reference `python/ray/_private/runtime_env/conda.py` —
    named envs resolve against the local conda install, spec dicts build cached
    envs keyed by content hash."""
    import shutil

    conda_exe = conda_exe or shutil.which("conda") or shutil.which("mamba") \
        or shutil.which("micromamba")
    if conda_exe is None:
        raise RuntimeError(
            "runtime_env conda requires a conda/mamba install on every node"
        )
    spec = runtime_env["conda"]
    if isinstance(spec, str):
        # Named env: ask conda where its envs live.
        proc = subprocess.run([conda_exe, "info", "--base"],
                              capture_output=True, timeout=60, text=True)
        if proc.returncode != 0:
            raise RuntimeError(f"conda info --base failed: {proc.stderr[-500:]}")
        base = proc.stdout.strip()
        python = os.path.join(base, "envs", spec, "bin", "python")
        if not os.path.exists(python):
            raise RuntimeError(f"conda env {spec!r} not found under {base}/envs")
        return python
    key = env_key({"conda": spec})
    final = os.path.join(cache_root, f"conda_{key}")
    python = os.path.join(final, "bin", "python")
    if os.path.exists(os.path.join(final, ".ready")):
        return python
    os.makedirs(cache_root, exist_ok=True)
    build = final + f".build{os.getpid()}"
    yml = build + ".yml"
    try:
        import json as _json

        with open(yml, "w") as f:
            # environment.yml is YAML, but flow-style JSON is valid YAML 1.2 —
            # no yaml dependency needed to emit {"dependencies": [...]}.
            f.write(_json.dumps({"dependencies": spec["dependencies"]}))
        proc = subprocess.run(
            [conda_exe, "env", "create", "-y", "-p", build, "-f", yml],
            capture_output=True, timeout=1800, text=True,
        )
        if proc.returncode != 0:
            raise RuntimeError(f"conda env create failed: {proc.stderr[-2000:]}")
        with open(os.path.join(build, ".ready"), "w") as f:
            f.write(key or "")
        try:
            os.rename(build, final)
        except OSError:
            import shutil as _sh

            _sh.rmtree(build, ignore_errors=True)
            if not os.path.exists(os.path.join(final, ".ready")):
                raise
        return python
    finally:
        try:
            os.remove(yml)
        except OSError:
            pass


def container_command(runtime_env: Dict[str, Any], *, session_dir: str,
                      env: Dict[str, str], engine: Optional[str] = None) -> list:
    """Build the host command that launches a worker inside the runtime_env's
    container image. Parity: reference `runtime_env/image_uri.py` — the image
    must contain ray_tpu; host networking + IPC so the worker reaches the
    raylet's ports and the shared-memory object store exactly like a native
    worker; the session dir is mounted for runtime-env artifacts."""
    import shutil

    engine = engine or shutil.which("podman") or shutil.which("docker")
    if engine is None:
        raise RuntimeError(
            "runtime_env image_uri requires podman or docker on every node"
        )
    image = runtime_env["image_uri"]
    for prefix in ("docker://",):
        if image.startswith(prefix):
            image = image[len(prefix):]
    cmd = [engine, "run", "--rm", "--network=host", "--ipc=host",
           "-v", f"{session_dir}:{session_dir}"]
    for k, v in env.items():
        cmd += ["--env", f"{k}={v}"]
    cmd += [image, "python3", "-m", "ray_tpu._private.default_worker"]
    return cmd
