"""Persistent storage behind the GCS tables.

Design parity: reference `src/ray/gcs/store_client/` — the GCS keeps all cluster tables
behind a `StoreClient` so the control plane can restart and re-learn its state
(`redis_store_client.h:126` vs `in_memory_store_client.h:32`; restart recovery loads
tables via `gcs_init_data.cc`). Here the durable backend is an append-only pickle log
per store directory (this framework has no Redis dependency): every mutation appends an
(op, table, key, value) record; load() replays the log; compaction rewrites it as one
snapshot record per live key once the log grows past a threshold.
"""

from __future__ import annotations

import os
import pickle
import threading
from typing import Any, Iterator


class InMemoryStoreClient:
    """Table storage with no durability (reference in_memory_store_client.h:32)."""

    def __init__(self):
        self._tables: dict[str, dict[Any, Any]] = {}

    @property
    def persistent(self) -> bool:
        return False

    def put(self, table: str, key, value):
        self._tables.setdefault(table, {})[key] = value

    def get(self, table: str, key, default=None):
        return self._tables.get(table, {}).get(key, default)

    def delete(self, table: str, key):
        self._tables.get(table, {}).pop(key, None)

    def keys(self, table: str) -> list:
        return list(self._tables.get(table, {}))

    def items(self, table: str) -> Iterator[tuple[Any, Any]]:
        return iter(list(self._tables.get(table, {}).items()))

    def load(self):
        pass

    def close(self):
        pass


class FileStoreClient(InMemoryStoreClient):
    """Append-only-log storage; survives GCS process restarts.

    Records are pickle-framed (op, table, key, value) tuples. Writes flush to
    the OS on every append (a crash of the GCS process loses nothing), and a
    group-commit thread fsyncs the log every few milliseconds by default — one
    disk sync amortizes every append in the window, so host crashes lose at
    most that window (reference `redis_store_client.h:126` semantics with AOF
    between everysec and always). `RAY_TPU_GCS_STORE_FSYNC` tunes it:
    "1"/"always" = fsync per append, "0"/"off" = flush only (fastest, host
    crash can lose the OS-buffered tail), unset/"group" = group commit.
    """

    @property
    def _COMPACT_THRESHOLD(self) -> int:
        from ray_tpu._private.config import CONFIG

        return CONFIG.gcs_store_compact_threshold

    def __init__(self, store_dir: str):
        super().__init__()
        self._dir = store_dir
        os.makedirs(store_dir, exist_ok=True)
        self._path = os.path.join(store_dir, "gcs_tables.log")
        self._lock = threading.Lock()
        self._log = None
        self._appends_since_compact = 0
        mode = os.environ.get("RAY_TPU_GCS_STORE_FSYNC", "group").lower()
        if mode in ("1", "true", "on", "always"):
            self._fsync_mode = "always"
        elif mode in ("0", "false", "off"):
            self._fsync_mode = "off"
        else:
            self._fsync_mode = "group"
        self._fsync = self._fsync_mode == "always"
        self._dirty = threading.Event()  # appends since last group fsync
        self._closing = False
        # Plain counters, cheap enough for the append path; exported as
        # gcs_store_* metrics only from report paths (rpc_store_stats ->
        # util.state.control_plane_stats) — never flushed from here.
        self._stats = {"appends": 0, "append_seconds": 0.0, "compactions": 0}
        self._syncer: threading.Thread | None = None
        if self._fsync_mode == "group":
            self._syncer = threading.Thread(
                target=self._group_sync_loop, name="gcs-store-fsync", daemon=True
            )
            self._syncer.start()

    def _group_sync_loop(self, interval_s: float | None = None):
        if interval_s is None:
            from ray_tpu._private.config import CONFIG

            interval_s = CONFIG.gcs_store_fsync_window_s
        while not self._closing:
            self._dirty.wait()
            if self._closing:
                return
            self._dirty.clear()
            # Collect a window of appends, then one fsync covers them all.
            import time as _time

            _time.sleep(interval_s)
            # Sync OUTSIDE the lock on a dup'd fd: an fsync can take tens of
            # ms on a loaded disk, and holding the lock would stall every
            # append (the GCS event loop) for the duration.
            fd = None
            with self._lock:
                if self._log is not None:
                    try:
                        fd = os.dup(self._log.fileno())
                    except (OSError, ValueError):
                        fd = None
            if fd is not None:
                try:
                    os.fsync(fd)
                except OSError:
                    pass
                finally:
                    os.close(fd)

    @property
    def persistent(self) -> bool:
        return True

    def load(self):
        """Replay the log into memory, then open it for appending. A torn tail
        record (crash mid-append) is truncated away so later appends are not
        stranded behind unreadable bytes on the next load. Idempotent: a
        second load (e.g. a warm-standby store promoted into a GcsService)
        keeps the already-replayed tables."""
        if self._log is not None:
            return
        good_offset = 0
        existed = os.path.exists(self._path)
        if existed:
            with open(self._path, "rb") as f:
                while True:
                    try:
                        op, table, key, value = pickle.load(f)
                        good_offset = f.tell()
                    except EOFError:
                        break
                    except Exception:
                        break  # torn tail record from a crash mid-append
                    if op == "put":
                        super().put(table, key, value)
                    elif op == "del":
                        super().delete(table, key)
            if good_offset < os.path.getsize(self._path):
                with open(self._path, "r+b") as f:
                    f.truncate(good_offset)
        self._log = open(self._path, "ab")
        if not existed and self._fsync_mode != "off":
            # The file CREATION must be durable too: a host crash right after
            # cluster start could otherwise strand a directory entry pointing
            # at nothing, and the first fsynced appends with it (the same
            # rename-durability rule _compact_locked already follows).
            self._fsync_dir()

    def _fsync_dir(self):
        dir_fd = os.open(self._dir, os.O_RDONLY)
        try:
            os.fsync(dir_fd)
        finally:
            os.close(dir_fd)

    def _append(self, record):
        if self._log is None:
            return
        import time as _time

        t0 = _time.perf_counter()
        with self._lock:
            pickle.dump(record, self._log, protocol=5)
            self._log.flush()
            if self._fsync:
                os.fsync(self._log.fileno())
            self._appends_since_compact += 1
            if self._appends_since_compact >= self._COMPACT_THRESHOLD:
                self._compact_locked()
            self._stats["appends"] += 1
            self._stats["append_seconds"] += _time.perf_counter() - t0
        if self._fsync_mode == "group":
            self._dirty.set()

    def _compact_locked(self):
        tmp = self._path + ".compact"
        with open(tmp, "wb") as f:
            for table, kv in self._tables.items():
                for key, value in kv.items():
                    pickle.dump(("put", table, key, value), f, protocol=5)
            f.flush()
            os.fsync(f.fileno())
        self._log.close()
        os.replace(tmp, self._path)
        if self._fsync_mode != "off":
            # The rename itself must be durable, or a host crash can strand the
            # directory pointing at the pre-compaction inode — losing the
            # snapshot and every fsynced append after it.
            self._fsync_dir()
        self._log = open(self._path, "ab")
        self._appends_since_compact = 0
        self._stats["compactions"] += 1

    def put(self, table: str, key, value):
        super().put(table, key, value)
        self._append(("put", table, key, value))

    def delete(self, table: str, key):
        super().delete(table, key)
        self._append(("del", table, key, None))

    def stats_view(self) -> dict:
        """Cheap snapshot of the append/compaction counters plus the current
        log size, for the store-stats report path."""
        try:
            log_bytes = os.path.getsize(self._path)
        except OSError:
            log_bytes = 0
        with self._lock:
            view = dict(self._stats)
        view["log_bytes"] = log_bytes
        return view

    def close(self):
        self._closing = True
        self._dirty.set()  # unblock the group-sync thread
        if self._syncer is not None:
            # Join BEFORE closing the log: _group_sync_loop fsyncs a dup'd fd
            # taken under the lock, but close() racing the window between dup
            # and fsync could recycle the fd number onto an unrelated file.
            # Bounded join — a syncer mid-fsync on a loaded disk finishes its
            # last window; past the bound we proceed (daemon thread, and the
            # explicit fsync below covers the tail).
            self._syncer.join(timeout=5.0)
            self._syncer = None
        with self._lock:
            if self._log is not None:
                try:
                    if self._fsync_mode != "off":
                        os.fsync(self._log.fileno())
                except OSError:
                    pass
                self._log.close()
                self._log = None
