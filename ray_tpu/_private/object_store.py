"""Per-node shared-memory object store.

Design parity: reference plasma store (`src/ray/object_manager/plasma/` — dlmalloc arena
over mmap/shm, LRU eviction, create/seal lifecycle, fd-passing to clients).

Two backends behind one API:
- **Native (default)**: one C++ mmap arena per node (`_native/shmstore.cpp` —
  boundary-tag allocator with coalescing, open-addressing index, LRU eviction, robust
  process-shared mutex); workers attach the arena once and read payloads zero-copy at
  offsets. This is the plasma-shaped path: one mapping, allocator-managed placement.
- **Pure-Python fallback** (`RAY_TPU_NATIVE_STORE=0` or no toolchain): one POSIX shm
  segment per object, kernel-managed.

Both speak the same name protocol: `info()/create()` return an opaque "location name"
the reader side resolves (`@arena:offset:size` for native, a segment name otherwise),
so the raylet/worker wire format is backend-agnostic.
"""

from __future__ import annotations

import hashlib
import os
import shutil
import threading
import time
from collections import OrderedDict
from multiprocessing import shared_memory

from ray_tpu._private.ids import ObjectID
from ray_tpu.exceptions import ObjectStoreFullError

_PREFIX = "rtpu_"


def _native_key(object_id: ObjectID) -> bytes:
    """ObjectIDs are longer than the native index's 16-byte keys; a keyed blake2b
    digest keeps collisions negligible."""
    return hashlib.blake2b(object_id.binary(), digest_size=16).digest()


def _native_enabled() -> bool:
    return os.environ.get("RAY_TPU_NATIVE_STORE", "1") != "0"


class _QuietSharedMemory(shared_memory.SharedMemory):
    """SharedMemory whose close/finalizer tolerates exported buffers.

    Zero-copy readers hand out memoryviews into the mapping (numpy arrays deserialized
    from the store alias it); closing with exports alive raises BufferError. We swallow
    it — the fd is reclaimed by the kernel at process exit, which is the plasma behavior
    (clients keep objects mapped until release)."""

    def close(self):
        try:
            super().close()
        except BufferError:
            pass

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def _untrack(shm: shared_memory.SharedMemory):
    """Detach from the resource tracker: segment lifetime is managed by the store,
    not by whichever process happened to touch it (3.12 lacks track=False)."""
    try:
        from multiprocessing import resource_tracker

        resource_tracker.unregister(shm._name, "shared_memory")  # noqa: SLF001
    except Exception:
        pass


class _Entry:
    __slots__ = ("shm", "size", "sealed", "created_at", "freed")

    def __init__(self, shm, size):
        self.shm = shm
        self.size = size
        self.sealed = False
        self.freed = False
        self.created_at = time.monotonic()


def SharedObjectStore(capacity_bytes: int):
    """Backend-selecting factory (the raylet's store construction point)."""
    if _native_enabled():
        try:
            return NativeSharedObjectStore(capacity_bytes)
        except Exception:
            pass
    return PySharedObjectStore(capacity_bytes)


class NativeSharedObjectStore:
    """C++ arena backend. Location names: '@<arena>:<offset>:<size>:<key>' for
    in-arena objects, '#spill:<path>:<size>' for objects spilled to disk.

    Spilling (plasma parity: local_object_manager spill orchestration): when an
    allocation cannot fit even after evicting freed objects, sealed unpinned
    objects are copied out to files in LRU order and evicted, and reads serve
    them from the file via mmap."""

    def __init__(self, capacity_bytes: int, spill_dir: str | None = None):
        from ray_tpu._native.shmstore import NativeStoreServer

        from ray_tpu._private.config import CONFIG

        self.capacity = capacity_bytes
        self._arena_name = f"rtpu_arena_{os.getpid()}_{os.urandom(4).hex()}"
        self._srv = NativeStoreServer(
            self._arena_name, capacity_bytes,
            pretouch=min(capacity_bytes, CONFIG.store_pretouch_bytes),
        )
        spill_root = os.path.join(
            os.environ.get("TMPDIR", "/tmp"), "ray_tpu", "spill"
        )
        self._spill_dir = spill_dir or os.path.join(spill_root, self._arena_name)
        self._sweep_stale_spill_dirs(spill_root)
        self._spilled: dict[bytes, tuple[str, int]] = {}  # key -> (path, size)
        self.num_spilled = 0
        self.spilled_bytes = 0
        # Unsealed objects: the native index only serves sealed lookups, but
        # create()/seal()/put_bytes() need the placement before sealing.
        self._unsealed: dict[ObjectID, tuple[int, int]] = {}
        self._lock = threading.Lock()

    # -- spilling ----------------------------------------------------------
    def _spill_one(self) -> bool:
        """Copy the LRU sealed, unpinned object to disk and evict it. Disk IO
        happens without self._lock; only the bookkeeping mutation takes it."""
        for key in self._srv.list_spillable(64):
            with self._lock:
                if key in self._spilled:
                    continue
            found = self._srv.lookup(key)
            if found is None:
                continue
            off, size = found
            if not self._srv.pin(key):
                continue
            try:
                os.makedirs(self._spill_dir, exist_ok=True)
                path = os.path.join(self._spill_dir, key.hex())
                with open(path, "wb") as f:
                    f.write(self._srv.read(off, size))
            finally:
                self._srv.release(key)
            with self._lock:
                self._spilled[key] = (path, size)
                self.num_spilled += 1
                self.spilled_bytes += size
            self._srv.free(key, eager=True)
            return True
        return False

    @staticmethod
    def _sweep_stale_spill_dirs(spill_root: str):
        """Best-effort cleanup of spill dirs left by crashed stores (their embedded
        pid is gone). Prevents /tmp filling up across repeated crashes."""
        try:
            for name in os.listdir(spill_root):
                parts = name.split("_")  # rtpu_arena_<pid>_<rand>
                if len(parts) < 4 or not parts[2].isdigit():
                    continue
                pid = int(parts[2])
                try:
                    os.kill(pid, 0)
                    continue  # owner alive
                except ProcessLookupError:
                    pass
                except PermissionError:
                    continue
                shutil.rmtree(os.path.join(spill_root, name), ignore_errors=True)
        except OSError:
            pass

    def _name_of(self, offset: int, size: int, key: bytes) -> str:
        # The key rides in the name so readers can pin the object against
        # eviction-recycling while zero-copy views alias the arena.
        return f"@{self._arena_name}:{offset}:{size}:{key.hex()}"

    def create(self, object_id: ObjectID, size: int) -> str:
        key = _native_key(object_id)
        with self._lock:
            if object_id in self._unsealed:
                off, sz = self._unsealed[object_id]
                return self._name_of(off, sz, key)
        found = self._srv.lookup(key)
        if found is not None:
            return self._name_of(*found, key)
        # Allocation + spilling run OUTSIDE self._lock: the C++ arena has its own
        # process-shared mutex, and a multi-second disk spill must not block every
        # other store call on this node.
        while True:
            try:
                off = self._srv.alloc(key, size)
            except FileExistsError:
                found = self._srv.lookup(key)
                if found is not None:
                    return self._name_of(*found, key)
                raise
            if off is not None:
                break
            # Full even after evicting freed entries: spill sealed LRU
            # objects to disk until the allocation fits.
            if not self._spill_one():
                raise ObjectStoreFullError(
                    f"object of {size} bytes does not fit: "
                    f"{self._srv.used}/{self.capacity} used, "
                    f"{self.num_spilled} objects already spilled"
                )
        with self._lock:
            self._unsealed[object_id] = (off, size)
        return self._name_of(off, size, key)

    def put_bytes(self, object_id: ObjectID, data: bytes) -> str:
        name = self.create(object_id, len(data))
        with self._lock:
            off, _sz = self._unsealed.get(object_id, (None, None))
        if off is not None:
            self._srv.write(off, data)
            self.seal(object_id)
        return name

    def seal(self, object_id: ObjectID):
        with self._lock:
            if object_id not in self._unsealed:
                # already sealed (idempotent) or unknown
                if self._srv.lookup(_native_key(object_id)) is not None:
                    return
                raise KeyError(f"seal of unknown object {object_id}")
            self._unsealed.pop(object_id)
        self._srv.seal(_native_key(object_id))

    def contains(self, object_id: ObjectID) -> bool:
        key = _native_key(object_id)
        return self._srv.lookup(key) is not None or key in self._spilled

    def info(self, object_id: ObjectID):
        key = _native_key(object_id)
        found = self._srv.lookup(key)
        if found is not None:
            return (self._name_of(*found, key), found[1])
        spilled = self._spilled.get(key)
        if spilled is not None:
            path, size = spilled
            return (f"#spill:{path}:{size}", size)
        return None

    def read_bytes(self, object_id: ObjectID, offset: int = 0, length: int | None = None) -> bytes:
        key = _native_key(object_id)
        found = self._srv.lookup(key)
        if found is None:
            spilled = self._spilled.get(key)
            if spilled is not None:
                path, size = spilled
                end = size if length is None else min(offset + length, size)
                with open(path, "rb") as f:
                    f.seek(offset)
                    return f.read(end - offset)
            raise KeyError(f"object {object_id} not sealed/present")
        off, size = found
        end = size if length is None else min(offset + length, size)
        # Pin across the copy: another process's alloc must not recycle the block
        # mid-memcpy.
        self._srv.pin(key)
        try:
            return bytes(self._srv.read(off + offset, end - offset))
        finally:
            self._srv.release(key)

    def free(self, object_id: ObjectID, eager: bool = False):
        key = _native_key(object_id)
        with self._lock:
            self._unsealed.pop(object_id, None)
            spilled = self._spilled.pop(key, None)
        if spilled is not None:
            try:
                os.remove(spilled[0])
            except OSError:
                pass
            self.spilled_bytes -= spilled[1]
        self._srv.free(key, eager=eager)

    @property
    def used(self) -> int:
        return self._srv.used

    def stats(self):
        return {
            "num_objects": self._srv.num_objects,
            "used_bytes": self._srv.used,
            "capacity_bytes": self.capacity,
            "num_evictions": self._srv.num_evictions,
            "num_spilled": self.num_spilled,
            "spilled_bytes": max(0, self.spilled_bytes),
            "backend": "native",
        }

    def destroy(self):
        self._srv.destroy()
        self._spilled.clear()
        shutil.rmtree(self._spill_dir, ignore_errors=True)


class PySharedObjectStore:
    """Pure-Python fallback: one shm segment per object (server side)."""

    def __init__(self, capacity_bytes: int):
        self.capacity = capacity_bytes
        self.used = 0
        self._entries: OrderedDict[ObjectID, _Entry] = OrderedDict()
        self._lock = threading.Lock()

    def create(self, object_id: ObjectID, size: int) -> str:
        """Allocate a segment; returns the shm name for the writer to map."""
        with self._lock:
            if object_id in self._entries:
                entry = self._entries[object_id]
                return entry.shm.name
            self._ensure_capacity(size)
            # Full hex: the return-index lives in the trailing bytes, so truncation
            # would collide every put from one task.
            name = _PREFIX + object_id.hex()
            try:
                shm = _QuietSharedMemory(name=name, create=True, size=max(size, 1))
            except FileExistsError:
                old = _QuietSharedMemory(name=name)
                _untrack(old)
                old.close()
                old.unlink()
                shm = _QuietSharedMemory(name=name, create=True, size=max(size, 1))
            _untrack(shm)
            self._entries[object_id] = _Entry(shm, size)
            self.used += size
            return shm.name

    def put_bytes(self, object_id: ObjectID, data: bytes) -> str:
        name = self.create(object_id, len(data))
        entry = self._entries[object_id]
        entry.shm.buf[: len(data)] = data
        self.seal(object_id)
        return name

    def seal(self, object_id: ObjectID):
        with self._lock:
            entry = self._entries.get(object_id)
            if entry is None:
                raise KeyError(f"seal of unknown object {object_id}")
            entry.sealed = True
            self._entries.move_to_end(object_id)

    def contains(self, object_id: ObjectID) -> bool:
        with self._lock:
            e = self._entries.get(object_id)
            return e is not None and e.sealed

    def info(self, object_id: ObjectID):
        with self._lock:
            e = self._entries.get(object_id)
            if e is None or not e.sealed:
                return None
            self._entries.move_to_end(object_id)
            return (e.shm.name, e.size)

    def read_bytes(self, object_id: ObjectID, offset: int = 0, length: int | None = None) -> bytes:
        """Copy out a range (used for node-to-node transfer chunks)."""
        with self._lock:
            e = self._entries.get(object_id)
            if e is None or not e.sealed:
                raise KeyError(f"object {object_id} not sealed/present")
            end = e.size if length is None else min(offset + length, e.size)
            return bytes(e.shm.buf[offset:end])

    def free(self, object_id: ObjectID, eager: bool = False):
        """Mark freed; eager=True unlinks immediately, else the entry stays as LRU cache."""
        with self._lock:
            e = self._entries.get(object_id)
            if e is None:
                return
            e.freed = True
            if eager:
                self._evict_locked(object_id)

    def _evict_locked(self, object_id: ObjectID):
        e = self._entries.pop(object_id, None)
        if e is None:
            return
        self.used -= e.size
        try:
            e.shm.close()
            e.shm.unlink()
        except Exception:
            pass

    def _ensure_capacity(self, size: int):
        if self.used + size <= self.capacity:
            return
        # LRU-evict freed entries first (reference: eviction_policy.h LRU over releasable).
        for oid in [o for o, e in self._entries.items() if e.freed and e.sealed]:
            self._evict_locked(oid)
            if self.used + size <= self.capacity:
                return
        if self.used + size > self.capacity:
            raise ObjectStoreFullError(
                f"object of {size} bytes does not fit: {self.used}/{self.capacity} used"
            )

    def stats(self):
        with self._lock:
            return {
                "num_objects": len(self._entries),
                "used_bytes": self.used,
                "capacity_bytes": self.capacity,
            }

    def destroy(self):
        with self._lock:
            for oid in list(self._entries):
                self._evict_locked(oid)


class LocalObjectReader:
    """Client side: resolves location names from either backend.

    Native names ('@arena:offset:size') attach the node's arena ONCE and slice the
    mapping; per-object names map their own segment. Both cached per process."""

    def __init__(self):
        self._maps: dict[str, shared_memory.SharedMemory] = {}
        self._arenas: dict[str, object] = {}
        self._lock = threading.Lock()

    def _arena(self, name: str):
        client = self._arenas.get(name)
        if client is None:
            from ray_tpu._native.shmstore import NativeStoreClient

            client = NativeStoreClient(name)
            self._arenas[name] = client
        return client

    @staticmethod
    def _parse(shm_name: str):
        arena, off, size, key = shm_name[1:].rsplit(":", 3)
        return arena, int(off), int(size), bytes.fromhex(key)

    def read(self, shm_name: str, size: int) -> memoryview:
        with self._lock:
            if shm_name.startswith("#spill:"):
                import mmap

                rest = shm_name[len("#spill:"):]
                path, _, sz = rest.rpartition(":")
                with open(path, "rb") as f:
                    mapped = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
                # The mmap stays alive via the returned memoryview; page cache
                # makes repeated spilled reads cheap.
                return memoryview(mapped)[: min(size, int(sz))]
            if shm_name.startswith("@"):
                arena, off, sz, key = self._parse(shm_name)
                # Pinned view: the arena can't recycle this payload while any
                # deserialized alias of the returned buffer is alive. KeyError =
                # evicted/spilled since resolve; caller re-resolves.
                return self._arena(arena).read_pinned(key, off, min(size, sz))
            shm = self._maps.get(shm_name)
            if shm is None:
                shm = _QuietSharedMemory(name=shm_name)
                _untrack(shm)
                self._maps[shm_name] = shm
            return shm.buf[:size]

    def write_view(self, shm_name: str, size: int) -> memoryview:
        """WRITABLE raw view of a freshly-allocated (unsealed) object, for the
        put path to fill in place. Distinct from read(): no pin is taken (an
        unsealed allocation is never recycled under the writer) and no
        read-copy fallback may substitute — the caller's writes must land in
        the shared segment itself (read_pinned degrades to a copy on
        Python < 3.12, which would silently discard writes)."""
        with self._lock:
            if shm_name.startswith("@"):
                arena, off, sz, _key = self._parse(shm_name)
                return self._arena(arena).read(off, min(size, sz))
            shm = self._maps.get(shm_name)
            if shm is None:
                shm = _QuietSharedMemory(name=shm_name)
                _untrack(shm)
                self._maps[shm_name] = shm
            return shm.buf[:size]

    def write(self, shm_name: str, data: bytes):
        with self._lock:
            if shm_name.startswith("@"):
                arena, off, sz, _key = self._parse(shm_name)
                if len(data) > sz:
                    raise ValueError(
                        f"write of {len(data)} bytes exceeds the {sz}-byte "
                        f"allocation at {shm_name}"
                    )
                self._arena(arena).write(off, data)
                return
            shm = self._maps.get(shm_name)
            if shm is None:
                shm = _QuietSharedMemory(name=shm_name)
                _untrack(shm)
                self._maps[shm_name] = shm
        shm.buf[: len(data)] = data

    def release(self, shm_name: str):
        if shm_name.startswith("@"):
            return  # arena mapping is shared; nothing per-object to unmap
        with self._lock:
            shm = self._maps.pop(shm_name, None)
        if shm is not None:
            try:
                shm.close()
            except Exception:
                pass

    def close(self):
        with self._lock:
            for shm in self._maps.values():
                try:
                    shm.close()
                except Exception:
                    pass
            self._maps.clear()
            for client in self._arenas.values():
                try:
                    client.close()
                except Exception:
                    pass
            self._arenas.clear()
