"""Per-node shared-memory object store.

Design parity: reference plasma store (`src/ray/object_manager/plasma/` — dlmalloc arena
over mmap/shm, LRU eviction, create/seal lifecycle, fd-passing to clients). Here each
sealed object lives in its own POSIX shm segment created by the raylet process; workers on
the same node map the segment by name for zero-copy reads (the kernel plays the role of
the reference's dlmalloc arena; a C++ slab allocator can replace per-object segments
without changing this API). Lifecycle is the same create → write → seal → (map readers)
→ free, with capacity accounting and LRU eviction of freed-but-cached entries.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from multiprocessing import shared_memory

from ray_tpu._private.ids import ObjectID
from ray_tpu.exceptions import ObjectStoreFullError

_PREFIX = "rtpu_"


class _QuietSharedMemory(shared_memory.SharedMemory):
    """SharedMemory whose close/finalizer tolerates exported buffers.

    Zero-copy readers hand out memoryviews into the mapping (numpy arrays deserialized
    from the store alias it); closing with exports alive raises BufferError. We swallow
    it — the fd is reclaimed by the kernel at process exit, which is the plasma behavior
    (clients keep objects mapped until release)."""

    def close(self):
        try:
            super().close()
        except BufferError:
            pass

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def _untrack(shm: shared_memory.SharedMemory):
    """Detach from the resource tracker: segment lifetime is managed by the store,
    not by whichever process happened to touch it (3.12 lacks track=False)."""
    try:
        from multiprocessing import resource_tracker

        resource_tracker.unregister(shm._name, "shared_memory")  # noqa: SLF001
    except Exception:
        pass


class _Entry:
    __slots__ = ("shm", "size", "sealed", "created_at", "freed")

    def __init__(self, shm, size):
        self.shm = shm
        self.size = size
        self.sealed = False
        self.freed = False
        self.created_at = time.monotonic()


class SharedObjectStore:
    """Server side (runs in the raylet process)."""

    def __init__(self, capacity_bytes: int):
        self.capacity = capacity_bytes
        self.used = 0
        self._entries: OrderedDict[ObjectID, _Entry] = OrderedDict()
        self._lock = threading.Lock()

    def create(self, object_id: ObjectID, size: int) -> str:
        """Allocate a segment; returns the shm name for the writer to map."""
        with self._lock:
            if object_id in self._entries:
                entry = self._entries[object_id]
                return entry.shm.name
            self._ensure_capacity(size)
            # Full hex: the return-index lives in the trailing bytes, so truncation
            # would collide every put from one task.
            name = _PREFIX + object_id.hex()
            try:
                shm = _QuietSharedMemory(name=name, create=True, size=max(size, 1))
            except FileExistsError:
                old = _QuietSharedMemory(name=name)
                _untrack(old)
                old.close()
                old.unlink()
                shm = _QuietSharedMemory(name=name, create=True, size=max(size, 1))
            _untrack(shm)
            self._entries[object_id] = _Entry(shm, size)
            self.used += size
            return shm.name

    def put_bytes(self, object_id: ObjectID, data: bytes) -> str:
        name = self.create(object_id, len(data))
        entry = self._entries[object_id]
        entry.shm.buf[: len(data)] = data
        self.seal(object_id)
        return name

    def seal(self, object_id: ObjectID):
        with self._lock:
            entry = self._entries.get(object_id)
            if entry is None:
                raise KeyError(f"seal of unknown object {object_id}")
            entry.sealed = True
            self._entries.move_to_end(object_id)

    def contains(self, object_id: ObjectID) -> bool:
        with self._lock:
            e = self._entries.get(object_id)
            return e is not None and e.sealed

    def info(self, object_id: ObjectID):
        with self._lock:
            e = self._entries.get(object_id)
            if e is None or not e.sealed:
                return None
            self._entries.move_to_end(object_id)
            return (e.shm.name, e.size)

    def read_bytes(self, object_id: ObjectID, offset: int = 0, length: int | None = None) -> bytes:
        """Copy out a range (used for node-to-node transfer chunks)."""
        with self._lock:
            e = self._entries.get(object_id)
            if e is None or not e.sealed:
                raise KeyError(f"object {object_id} not sealed/present")
            end = e.size if length is None else min(offset + length, e.size)
            return bytes(e.shm.buf[offset:end])

    def free(self, object_id: ObjectID, eager: bool = False):
        """Mark freed; eager=True unlinks immediately, else the entry stays as LRU cache."""
        with self._lock:
            e = self._entries.get(object_id)
            if e is None:
                return
            e.freed = True
            if eager:
                self._evict_locked(object_id)

    def _evict_locked(self, object_id: ObjectID):
        e = self._entries.pop(object_id, None)
        if e is None:
            return
        self.used -= e.size
        try:
            e.shm.close()
            e.shm.unlink()
        except Exception:
            pass

    def _ensure_capacity(self, size: int):
        if self.used + size <= self.capacity:
            return
        # LRU-evict freed entries first (reference: eviction_policy.h LRU over releasable).
        for oid in [o for o, e in self._entries.items() if e.freed and e.sealed]:
            self._evict_locked(oid)
            if self.used + size <= self.capacity:
                return
        if self.used + size > self.capacity:
            raise ObjectStoreFullError(
                f"object of {size} bytes does not fit: {self.used}/{self.capacity} used"
            )

    def stats(self):
        with self._lock:
            return {
                "num_objects": len(self._entries),
                "used_bytes": self.used,
                "capacity_bytes": self.capacity,
            }

    def destroy(self):
        with self._lock:
            for oid in list(self._entries):
                self._evict_locked(oid)


class LocalObjectReader:
    """Client side: maps sealed segments by name, caches mappings per process."""

    def __init__(self):
        self._maps: dict[str, shared_memory.SharedMemory] = {}
        self._lock = threading.Lock()

    def read(self, shm_name: str, size: int) -> memoryview:
        with self._lock:
            shm = self._maps.get(shm_name)
            if shm is None:
                shm = _QuietSharedMemory(name=shm_name)
                _untrack(shm)
                self._maps[shm_name] = shm
            return shm.buf[:size]

    def write(self, shm_name: str, data: bytes):
        with self._lock:
            shm = self._maps.get(shm_name)
            if shm is None:
                shm = _QuietSharedMemory(name=shm_name)
                _untrack(shm)
                self._maps[shm_name] = shm
        shm.buf[: len(data)] = data

    def release(self, shm_name: str):
        with self._lock:
            shm = self._maps.pop(shm_name, None)
        if shm is not None:
            try:
                shm.close()
            except Exception:
                pass

    def close(self):
        with self._lock:
            for shm in self._maps.values():
                try:
                    shm.close()
                except Exception:
                    pass
            self._maps.clear()
