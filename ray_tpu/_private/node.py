"""Driver-side node/process management: start and stop the cluster daemons.

Design parity: reference `python/ray/_private/node.py` + `services.py` (Node starts
gcs_server, raylet, dashboard, ... via start_ray_process). Here a node is one
raylet_main process (head also hosts the GCS inside it).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time
import uuid


class NodeProcess:
    def __init__(self, proc: subprocess.Popen, info: dict, ready_file: str,
                 gcs_proc: subprocess.Popen | None = None,
                 gcs_store_dir: str | None = None,
                 session_dir: str | None = None):
        self.proc = proc
        self.info = info
        self.ready_file = ready_file
        self.gcs_proc = gcs_proc  # head only: the separate GCS server process
        self.gcs_store_dir = gcs_store_dir
        self.session_dir = session_dir

    @property
    def node_id_hex(self) -> str:
        return self.info["node_id"]

    @property
    def raylet_port(self) -> int:
        return self.info["raylet_port"]

    @property
    def gcs_port(self) -> int | None:
        return self.info.get("gcs_port")

    def terminate(self):
        try:
            self.proc.terminate()
            self.proc.wait(timeout=5)
        except Exception:
            try:
                self.proc.kill()
            except Exception:
                pass
        if self.gcs_proc is not None:
            try:
                self.gcs_proc.terminate()
                self.gcs_proc.wait(timeout=5)
            except Exception:
                try:
                    self.gcs_proc.kill()
                except Exception:
                    pass

    def kill_gcs(self):
        """Crash the GCS process (head nodes only); raylets keep running."""
        if self.gcs_proc is None:
            raise RuntimeError("this node does not host the GCS")
        self.gcs_proc.kill()
        self.gcs_proc.wait(timeout=5)

    def restart_gcs(self, timeout: float = 90.0):
        """Start a fresh GCS on the same port over the same persistent store
        (reference: gcs_server restart with a Redis backend)."""
        if self.gcs_port is None:
            raise RuntimeError("this node does not host the GCS")
        if self.gcs_proc is not None and self.gcs_proc.poll() is None:
            self.kill_gcs()
        self.gcs_proc = _start_gcs_process(
            self.session_dir, self.gcs_store_dir, port=self.gcs_port, timeout=timeout
        )


def _package_pythonpath(existing: str | None) -> str:
    """Ensure spawned daemons can import ray_tpu even when the driver added it to
    sys.path manually (the -m child does not inherit sys.path)."""
    import ray_tpu

    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(ray_tpu.__file__)))
    parts = [pkg_root] + ([existing] if existing else [])
    return os.pathsep.join(parts)


def make_session_dir() -> str:
    base = os.path.join(tempfile.gettempdir(), "ray_tpu")
    session = os.path.join(base, f"session_{time.strftime('%Y%m%d-%H%M%S')}_{uuid.uuid4().hex[:8]}")
    os.makedirs(os.path.join(session, "logs"), exist_ok=True)
    return session


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _start_gcs_process(session_dir: str, store_dir: str, port: int,
                       timeout: float = 90.0) -> subprocess.Popen:
    """Spawn the standalone GCS server (reference: gcs_server binary) and wait for
    it to bind. The fixed port lets raylets and drivers reconnect to a restarted
    GCS at the same address."""
    ready_file = os.path.join(session_dir, f"gcs_ready_{uuid.uuid4().hex[:8]}.json")
    cmd = [
        sys.executable, "-m", "ray_tpu._private.gcs_main",
        "--port", str(port),
        "--store-dir", store_dir,
        "--ready-file", ready_file,
    ]
    log_path = os.path.join(session_dir, "logs", f"gcs-{uuid.uuid4().hex[:8]}.log")
    out = open(log_path, "wb")
    env = dict(os.environ)
    env["PYTHONPATH"] = _package_pythonpath(env.get("PYTHONPATH"))
    proc = subprocess.Popen(cmd, stdout=out, stderr=subprocess.STDOUT, env=env)
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if os.path.exists(ready_file):
            os.remove(ready_file)
            return proc
        if proc.poll() is not None:
            with open(log_path, "rb") as f:
                tail = f.read()[-4000:].decode(errors="replace")
            raise RuntimeError(f"gcs process exited during startup:\n{tail}")
        time.sleep(0.05)
    proc.terminate()
    raise TimeoutError("gcs did not become ready in time")


def start_node(
    *,
    head: bool,
    gcs_addr: tuple[str, int] | None,
    resources: dict,
    labels: dict | None = None,
    session_dir: str,
    object_store_bytes: int = 0,
    worker_env: dict | None = None,
    timeout: float = 90.0,
) -> NodeProcess:
    ready_file = os.path.join(
        session_dir, f"node_ready_{uuid.uuid4().hex[:8]}.json"
    )
    gcs_proc = None
    gcs_store_dir = None
    if head:
        # The GCS runs as its own process (reference: gcs_server binary) so it can
        # crash and restart independently of the raylet; a pre-picked port lets the
        # raylet spawn concurrently and retry-connect while the GCS boots.
        gcs_store_dir = os.path.join(session_dir, "gcs_store")
        gcs_addr = ("127.0.0.1", _free_port())
    cmd = [
        sys.executable,
        "-m",
        "ray_tpu._private.raylet_main",
        "--resources",
        json.dumps(resources),
        "--labels",
        json.dumps(labels or {}),
        "--worker-env",
        json.dumps(worker_env or {}),
        "--session-dir",
        session_dir,
        "--object-store-bytes",
        str(object_store_bytes),
        "--ready-file",
        ready_file,
        "--gcs-host",
        gcs_addr[0],
        "--gcs-port",
        str(gcs_addr[1]),
    ]
    if head:
        cmd.append("--head")
    log_path = os.path.join(session_dir, "logs", f"raylet-{uuid.uuid4().hex[:8]}.log")
    out = open(log_path, "wb")
    env = dict(os.environ)
    env["PYTHONPATH"] = _package_pythonpath(env.get("PYTHONPATH"))
    proc = subprocess.Popen(cmd, stdout=out, stderr=subprocess.STDOUT, env=env)
    if head:
        try:
            gcs_proc = _start_gcs_process(
                session_dir, gcs_store_dir, port=gcs_addr[1], timeout=timeout
            )
        except Exception:
            proc.terminate()
            raise
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if os.path.exists(ready_file):
            with open(ready_file) as f:
                info = json.load(f)
            return NodeProcess(proc, info, ready_file, gcs_proc=gcs_proc,
                               gcs_store_dir=gcs_store_dir, session_dir=session_dir)
        if proc.poll() is not None:
            with open(log_path, "rb") as f:
                tail = f.read()[-4000:].decode(errors="replace")
            if gcs_proc is not None:
                gcs_proc.terminate()
            raise RuntimeError(f"node process exited during startup:\n{tail}")
        time.sleep(0.05)
    proc.terminate()
    if gcs_proc is not None:
        gcs_proc.terminate()
    raise TimeoutError("node did not become ready in time")
