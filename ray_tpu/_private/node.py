"""Driver-side node/process management: start and stop the cluster daemons.

Design parity: reference `python/ray/_private/node.py` + `services.py` (Node starts
gcs_server, raylet, dashboard, ... via start_ray_process). Here a node is one
raylet_main process (head also hosts the GCS inside it).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time
import uuid


class NodeProcess:
    def __init__(self, proc: subprocess.Popen, info: dict, ready_file: str):
        self.proc = proc
        self.info = info
        self.ready_file = ready_file

    @property
    def node_id_hex(self) -> str:
        return self.info["node_id"]

    @property
    def raylet_port(self) -> int:
        return self.info["raylet_port"]

    @property
    def gcs_port(self) -> int | None:
        return self.info.get("gcs_port")

    def terminate(self):
        try:
            self.proc.terminate()
            self.proc.wait(timeout=5)
        except Exception:
            try:
                self.proc.kill()
            except Exception:
                pass


def _package_pythonpath(existing: str | None) -> str:
    """Ensure spawned daemons can import ray_tpu even when the driver added it to
    sys.path manually (the -m child does not inherit sys.path)."""
    import ray_tpu

    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(ray_tpu.__file__)))
    parts = [pkg_root] + ([existing] if existing else [])
    return os.pathsep.join(parts)


def make_session_dir() -> str:
    base = os.path.join(tempfile.gettempdir(), "ray_tpu")
    session = os.path.join(base, f"session_{time.strftime('%Y%m%d-%H%M%S')}_{uuid.uuid4().hex[:8]}")
    os.makedirs(os.path.join(session, "logs"), exist_ok=True)
    return session


def start_node(
    *,
    head: bool,
    gcs_addr: tuple[str, int] | None,
    resources: dict,
    labels: dict | None = None,
    session_dir: str,
    object_store_bytes: int = 0,
    worker_env: dict | None = None,
    timeout: float = 30.0,
) -> NodeProcess:
    ready_file = os.path.join(
        session_dir, f"node_ready_{uuid.uuid4().hex[:8]}.json"
    )
    cmd = [
        sys.executable,
        "-m",
        "ray_tpu._private.raylet_main",
        "--resources",
        json.dumps(resources),
        "--labels",
        json.dumps(labels or {}),
        "--worker-env",
        json.dumps(worker_env or {}),
        "--session-dir",
        session_dir,
        "--object-store-bytes",
        str(object_store_bytes),
        "--ready-file",
        ready_file,
    ]
    if head:
        cmd.append("--head")
    else:
        cmd += ["--gcs-host", gcs_addr[0], "--gcs-port", str(gcs_addr[1])]
    log_path = os.path.join(session_dir, "logs", f"raylet-{uuid.uuid4().hex[:8]}.log")
    out = open(log_path, "wb")
    env = dict(os.environ)
    env["PYTHONPATH"] = _package_pythonpath(env.get("PYTHONPATH"))
    proc = subprocess.Popen(cmd, stdout=out, stderr=subprocess.STDOUT, env=env)
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if os.path.exists(ready_file):
            with open(ready_file) as f:
                info = json.load(f)
            return NodeProcess(proc, info, ready_file)
        if proc.poll() is not None:
            with open(log_path, "rb") as f:
                tail = f.read()[-4000:].decode(errors="replace")
            raise RuntimeError(f"node process exited during startup:\n{tail}")
        time.sleep(0.05)
    proc.terminate()
    raise TimeoutError("node did not become ready in time")
