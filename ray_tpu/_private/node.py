"""Driver-side node/process management: start and stop the cluster daemons.

Design parity: reference `python/ray/_private/node.py` + `services.py` (Node starts
gcs_server, raylet, dashboard, ... via start_ray_process). Here a node is one
raylet_main process (head also hosts the GCS inside it).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time
import uuid


class NodeProcess:
    def __init__(self, proc: subprocess.Popen, info: dict, ready_file: str,
                 gcs_procs: list | None = None,
                 gcs_peers: list | None = None,
                 gcs_store_dirs: list | None = None,
                 session_dir: str | None = None):
        self.proc = proc
        self.info = info
        self.ready_file = ready_file
        # Head only: the separate GCS candidate processes (one with
        # gcs_replicas=1, the quorum-HA ensemble otherwise), their fixed
        # (host, port) addresses, and their per-candidate store dirs.
        self.gcs_procs: list = list(gcs_procs or [])
        self.gcs_peers: list = list(gcs_peers or [])
        self.gcs_store_dirs: list = list(gcs_store_dirs or [])
        self.session_dir = session_dir

    @property
    def gcs_proc(self):
        """The sole GCS process in single-candidate mode (back-compat)."""
        return self.gcs_procs[0] if self.gcs_procs else None

    @property
    def gcs_store_dir(self):
        return self.gcs_store_dirs[0] if self.gcs_store_dirs else None

    @property
    def node_id_hex(self) -> str:
        return self.info["node_id"]

    @property
    def raylet_port(self) -> int:
        return self.info["raylet_port"]

    @property
    def gcs_port(self) -> int | None:
        if self.gcs_peers:
            return self.gcs_peers[0][1]
        return self.info.get("gcs_port")

    @property
    def gcs_ports(self) -> list:
        if self.gcs_peers:
            return [p for _h, p in self.gcs_peers]
        port = self.info.get("gcs_port")
        return [port] if port else []

    @property
    def gcs_addrs(self) -> list:
        return (list(self.gcs_peers)
                or [("127.0.0.1", p) for p in self.gcs_ports])

    def terminate(self):
        try:
            self.proc.terminate()
            self.proc.wait(timeout=5)
        except Exception:
            try:
                self.proc.kill()
            except Exception:
                pass
        for gp in self.gcs_procs:
            try:
                gp.terminate()
                gp.wait(timeout=5)
            except Exception:
                try:
                    gp.kill()
                except Exception:
                    pass

    def kill_gcs(self):
        """Crash every GCS candidate process (head nodes only) — a full
        control-plane outage; raylets keep running."""
        if not self.gcs_procs:
            raise RuntimeError("this node does not host the GCS")
        for gp in self.gcs_procs:
            if gp.poll() is None:
                gp.kill()
        for gp in self.gcs_procs:
            try:
                gp.wait(timeout=5)
            except Exception:
                pass

    def restart_gcs(self, timeout: float = 90.0):
        """Restart every dead GCS candidate on its original port over its
        persistent store (reference: gcs_server restart with a Redis
        backend)."""
        if not self.gcs_ports:
            raise RuntimeError("this node does not host the GCS")
        for i in range(len(self.gcs_procs)):
            if self.gcs_procs[i].poll() is not None:
                self.restart_gcs_candidate(i, timeout=timeout)

    # ---------------------------------------------- quorum-HA chaos helpers

    def gcs_candidate_status(self, index: int, timeout: float = 2.0):
        from ray_tpu._private.gcs_replication import probe_status

        return probe_status(self.gcs_addrs[index], timeout=timeout)

    def gcs_primary_index(self, timeout: float = 30.0) -> int:
        """Index of the candidate currently reporting role=primary."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            for i in range(len(self.gcs_addrs)):
                st = self.gcs_candidate_status(i)
                if st and st.get("role") == "primary":
                    return i
            time.sleep(0.1)
        raise TimeoutError("no GCS candidate became primary in time")

    def kill_gcs_candidate(self, index: int):
        """SIGKILL one candidate (the chaos path for primary kills)."""
        proc = self.gcs_procs[index]
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=5)

    def restart_gcs_candidate(self, index: int, timeout: float = 90.0):
        if self.gcs_procs[index].poll() is None:
            self.kill_gcs_candidate(index)
        self.gcs_procs[index] = _start_gcs_process(
            self.session_dir, self.gcs_store_dirs[index],
            port=self.gcs_ports[index], timeout=timeout,
            candidate_id=index,
            peers=self.gcs_peers if len(self.gcs_peers) > 1 else None,
        )


def _package_pythonpath(existing: str | None) -> str:
    """Ensure spawned daemons can import ray_tpu even when the driver added it to
    sys.path manually (the -m child does not inherit sys.path)."""
    import ray_tpu

    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(ray_tpu.__file__)))
    parts = [pkg_root] + ([existing] if existing else [])
    return os.pathsep.join(parts)


def make_session_dir() -> str:
    base = os.path.join(tempfile.gettempdir(), "ray_tpu")
    session = os.path.join(base, f"session_{time.strftime('%Y%m%d-%H%M%S')}_{uuid.uuid4().hex[:8]}")
    os.makedirs(os.path.join(session, "logs"), exist_ok=True)
    return session


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _start_gcs_process(session_dir: str, store_dir: str, port: int,
                       timeout: float = 90.0, candidate_id: int = 0,
                       peers: list | None = None) -> subprocess.Popen:
    """Spawn the standalone GCS server (reference: gcs_server binary) and wait for
    it to bind. The fixed port lets raylets and drivers reconnect to a restarted
    GCS at the same address. `peers` (all candidate addresses, self included)
    switches the process into quorum-HA candidate mode."""
    ready_file = os.path.join(session_dir, f"gcs_ready_{uuid.uuid4().hex[:8]}.json")
    cmd = [
        sys.executable, "-m", "ray_tpu._private.gcs_main",
        "--port", str(port),
        "--store-dir", store_dir,
        "--ready-file", ready_file,
    ]
    if peers and len(peers) > 1:
        from ray_tpu._private.gcs_replication import format_addrs

        cmd += ["--candidate-id", str(candidate_id),
                "--peers", format_addrs(peers)]
    log_path = os.path.join(session_dir, "logs", f"gcs-{uuid.uuid4().hex[:8]}.log")
    out = open(log_path, "wb")
    env = dict(os.environ)
    env["PYTHONPATH"] = _package_pythonpath(env.get("PYTHONPATH"))
    proc = subprocess.Popen(cmd, stdout=out, stderr=subprocess.STDOUT, env=env)
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if os.path.exists(ready_file):
            os.remove(ready_file)
            return proc
        if proc.poll() is not None:
            with open(log_path, "rb") as f:
                tail = f.read()[-4000:].decode(errors="replace")
            raise RuntimeError(f"gcs process exited during startup:\n{tail}")
        time.sleep(0.05)
    proc.terminate()
    raise TimeoutError("gcs did not become ready in time")


def start_node(
    *,
    head: bool,
    gcs_addr: tuple[str, int] | None,
    resources: dict,
    labels: dict | None = None,
    session_dir: str,
    object_store_bytes: int = 0,
    worker_env: dict | None = None,
    timeout: float = 90.0,
) -> NodeProcess:
    from ray_tpu._private.gcs_replication import format_addrs, parse_addrs

    ready_file = os.path.join(
        session_dir, f"node_ready_{uuid.uuid4().hex[:8]}.json"
    )
    gcs_procs: list = []
    gcs_peers: list = []
    gcs_store_dirs: list = []
    if head:
        # The GCS runs as its own process (reference: gcs_server binary) so it can
        # crash and restart independently of the raylet; pre-picked ports let the
        # raylet spawn concurrently and retry-connect while the GCS boots. With
        # gcs_replicas > 1 the head spawns that many candidate processes, each
        # over its OWN store dir (a replica sharing a disk with the primary
        # would defeat the whole point), and every client gets the full
        # candidate address list.
        from ray_tpu._private.config import CONFIG

        replicas = max(1, int(CONFIG.gcs_replicas))
        gcs_peers = [("127.0.0.1", _free_port()) for _ in range(replicas)]
        if replicas == 1:
            gcs_store_dirs = [os.path.join(session_dir, "gcs_store")]
        else:
            gcs_store_dirs = [
                os.path.join(session_dir, f"gcs_store_{i}")
                for i in range(replicas)
            ]
        gcs_addr = gcs_peers
    else:
        gcs_addr = parse_addrs(gcs_addr)
    cmd = [
        sys.executable,
        "-m",
        "ray_tpu._private.raylet_main",
        "--resources",
        json.dumps(resources),
        "--labels",
        json.dumps(labels or {}),
        "--worker-env",
        json.dumps(worker_env or {}),
        "--session-dir",
        session_dir,
        "--object-store-bytes",
        str(object_store_bytes),
        "--ready-file",
        ready_file,
        "--gcs-addrs",
        format_addrs(gcs_addr),
    ]
    if head:
        cmd.append("--head")
    log_path = os.path.join(session_dir, "logs", f"raylet-{uuid.uuid4().hex[:8]}.log")
    out = open(log_path, "wb")
    env = dict(os.environ)
    env["PYTHONPATH"] = _package_pythonpath(env.get("PYTHONPATH"))
    proc = subprocess.Popen(cmd, stdout=out, stderr=subprocess.STDOUT, env=env)

    def _kill_gcs_procs():
        for gp in gcs_procs:
            try:
                gp.terminate()
            except Exception:
                pass

    if head:
        try:
            for i, (_h, port) in enumerate(gcs_peers):
                gcs_procs.append(_start_gcs_process(
                    session_dir, gcs_store_dirs[i], port=port,
                    timeout=timeout, candidate_id=i,
                    peers=gcs_peers if len(gcs_peers) > 1 else None,
                ))
        except Exception:
            proc.terminate()
            _kill_gcs_procs()
            raise
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if os.path.exists(ready_file):
            with open(ready_file) as f:
                info = json.load(f)
            return NodeProcess(proc, info, ready_file, gcs_procs=gcs_procs,
                               gcs_peers=gcs_peers,
                               gcs_store_dirs=gcs_store_dirs,
                               session_dir=session_dir)
        if proc.poll() is not None:
            with open(log_path, "rb") as f:
                tail = f.read()[-4000:].decode(errors="replace")
            _kill_gcs_procs()
            raise RuntimeError(f"node process exited during startup:\n{tail}")
        time.sleep(0.05)
    proc.terminate()
    _kill_gcs_procs()
    raise TimeoutError("node did not become ready in time")
