"""ObjectRef: a handle to a (possibly pending) remote object.

Design parity: reference `python/ray/includes/object_ref.pxi` + ownership model of
`src/ray/core_worker/reference_counter.h` — every ref carries its owner's address so any
holder can locate the object; local refcounts are maintained per process and the owner
frees the object when all known references are gone.
"""

from __future__ import annotations

from ray_tpu._private.ids import ObjectID


class ObjectRef:
    __slots__ = ("id", "owner", "_worker", "__weakref__")

    def __init__(self, object_id: ObjectID, owner: dict | None = None, _register: bool = True):
        self.id = object_id
        self.owner = owner  # {"node_id": NodeID, "worker_id": WorkerID} | None
        self._worker = None
        if _register:
            from ray_tpu._private.worker import global_worker_or_none

            w = global_worker_or_none()
            if w is not None:
                self._worker = w
                # Passing the owner lets the counter detect borrowed refs (owner is
                # another worker) and report the borrow so the owner keeps the
                # object alive until every borrower's last ref dies.
                w.reference_counter.add_local_ref(self.id, owner)

    def binary(self) -> bytes:
        return self.id.binary()

    def hex(self) -> str:
        return self.id.hex()

    def task_id(self):
        return self.id.task_id()

    def future(self):
        """Return a concurrent.futures.Future resolving to the object's value."""
        from ray_tpu._private.worker import global_worker

        return global_worker().as_future(self)

    def __await__(self):
        import asyncio

        async def _get():
            from ray_tpu._private.worker import global_worker

            return await asyncio.wrap_future(global_worker().as_future(self))

        return _get().__await__()

    def __hash__(self):
        return hash(self.id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other.id == self.id

    def __repr__(self):
        return f"ObjectRef({self.id.hex()})"

    def __reduce__(self):
        # Crossing a process boundary: the receiver registers a borrowed
        # reference. When a task's results are being packaged, the executor
        # captures every serialized ref so the reply can carry a sequenced
        # borrow handoff to the caller (see ReferenceCounter docstring).
        from ray_tpu._private.worker import global_worker_or_none

        w = global_worker_or_none()
        if w is not None:
            w._note_serialized_ref(self.id, self.owner)
        return (_deserialize_ref, (self.id.binary(), self.owner))

    def __del__(self):
        # Finalizers can run via GC inside runtime critical sections (same
        # thread, lock already held): never lock here — defer the release.
        w = self._worker
        if w is not None:
            try:
                w.reference_counter.defer_remove(self.id)
            except Exception:
                pass


def _deserialize_ref(binary: bytes, owner):
    return ObjectRef(ObjectID(binary), owner)
