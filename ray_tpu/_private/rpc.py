"""Internal asyncio RPC layer.

Design parity: reference `src/ray/rpc/` (gRPC server/client helpers + ClientCallManager)
and the asio io-service threading model of the C++ core worker. Here the transport is
length-prefixed pickled frames over TCP/unix sockets, with a *symmetric* peer protocol:
either side of a connection can issue requests, which is how the raylet pushes tasks to
workers over the same connection the worker registered on (reference: separate gRPC
services in both directions).

Every process runs one IO thread with an asyncio loop (`IoLoop`), mirroring the reference
core worker's dedicated io_service thread; blocking public APIs bridge in via
run_coroutine_threadsafe.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import pickle
import struct
import threading
import traceback
from typing import Any, Callable

_LEN_FMT = "<Q"
_LEN_SIZE = 8

_REQUEST = 0
_RESPONSE = 1
_ONEWAY = 2
_HELLO = 3

# Wire-protocol version (reference role: the protobuf schema version baked
# into src/ray/protobuf — cross-version clusters fail there by schema
# incompatibility; here both peers announce a version in their FIRST frame
# and a mismatch fails every call on the connection with a crisp error
# instead of a pickle decode crash deep in a handler). Unknown frame kinds
# are skipped by the receive loop, so future minor additions (new frame
# types) pass through old readers; bump this number for changes old code
# cannot safely ignore.
#
# Detection starts at v1: builds that PREDATE the handshake never send a
# HELLO and silently skip ours (their recv loop drops unknown frame kinds),
# so against such a peer the mismatch cannot be proven — the first
# _REQUEST/_RESPONSE arriving before any HELLO is the tell, and the receive
# loop logs a "legacy peer" warning naming the likely cause so the ensuing
# pickle/handler errors aren't a dead end.
PROTOCOL_VERSION = 1

logger = logging.getLogger(__name__)


class RpcError(Exception):
    pass


class ConnectionLost(RpcError):
    pass


class NotPrimaryError(ConnectionLost):
    """Raised by a replicated-GCS candidate that is not the current primary
    (docs/fault_tolerance.md). Subclasses ConnectionLost deliberately: to a
    client, "this endpoint cannot serve GCS calls" is the same retryable
    condition as a dropped connection, so every existing reconnect/backoff
    path handles it. Carries the current primary's (host, port) when the
    candidate knows it, letting clients redirect instead of scanning."""

    def __init__(self, primary=None):
        self.primary = tuple(primary) if primary else None
        super().__init__(
            f"not the GCS primary (primary hint: {self.primary})"
        )

    def __reduce__(self):  # travels pickled inside RPC error replies
        return (NotPrimaryError, (self.primary,))


class RemoteError(RpcError):
    def __init__(self, method: str, tb: str):
        self.method = method
        self.remote_traceback = tb
        super().__init__(f"remote call {method!r} failed:\n{tb}")

    def __reduce__(self):  # travels pickled inside RPC error replies
        return (RemoteError, (self.method, self.remote_traceback))


async def _read_frame(reader: asyncio.StreamReader) -> Any:
    header = await reader.readexactly(_LEN_SIZE)
    (length,) = struct.unpack(_LEN_FMT, header)
    payload = await reader.readexactly(length)
    return pickle.loads(payload)


def _frame(msg: Any) -> bytes:
    payload = pickle.dumps(msg, protocol=5)
    return struct.pack(_LEN_FMT, len(payload)) + payload


class Connection:
    """A symmetric RPC peer. `handler` is an object whose `rpc_<method>` coroutines serve
    inbound requests; outbound requests go through `call`/`notify`."""

    def __init__(self, reader, writer, handler: Any = None, name: str = "?",
                 _protocol_version: int | None = None):
        self._reader = reader
        self._writer = writer
        self.handler = handler
        self.name = name
        # Instance-scoped so tests can impersonate another version; real
        # processes always announce the module constant.
        self._protocol_version = (
            PROTOCOL_VERSION if _protocol_version is None else _protocol_version
        )
        self._protocol_error_msg: str | None = None
        self._mid = itertools.count(1)
        self._pending: dict[int, asyncio.Future] = {}
        self._closed = False
        self._close_callbacks: list[Callable] = []
        self._writer_lock = asyncio.Lock()
        self._recv_task: asyncio.Task | None = None
        self.peer_protocol: int | None = None  # set by the peer's HELLO
        self._legacy_warned = False
        from ray_tpu.devtools import leaksan as _leaksan

        _leaksan.track("rpc_conn", self, detail=f"conn {name}")

    def start(self):
        loop = asyncio.get_running_loop()
        # Announce our wire version SYNCHRONOUSLY before any other frame can
        # be written: writer.write appends to an ordered buffer, so this is
        # guaranteed to be the first frame on the wire (a fire-and-forget
        # task could lose the race to an immediate call(), be GC'd before
        # running, or leak an unretrieved exception).
        self._writer.write(_frame((_HELLO, self._protocol_version, {})))
        self._recv_task = loop.create_task(self._recv_loop())
        return self

    def on_close(self, cb: Callable):
        self._close_callbacks.append(cb)

    @property
    def closed(self) -> bool:
        return self._closed

    async def _send(self, msg):
        async with self._writer_lock:
            self._writer.write(_frame(msg))
            await self._writer.drain()

    def _closed_error(self) -> RpcError:
        """Fresh instance per raise: a shared exception object accumulates
        tracebacks across unrelated callers."""
        if self._protocol_error_msg:
            return RpcError(self._protocol_error_msg)
        return ConnectionLost(f"connection {self.name} is closed")

    async def call(self, method: str, *args, timeout: float | None = None, **kwargs):
        if self._closed:
            raise self._closed_error()
        mid = next(self._mid)
        fut = asyncio.get_running_loop().create_future()
        self._pending[mid] = fut
        await self._send((_REQUEST, mid, method, args, kwargs))
        try:
            return await (asyncio.wait_for(fut, timeout) if timeout else fut)
        finally:
            self._pending.pop(mid, None)

    async def notify(self, method: str, *args, **kwargs):
        if self._closed:
            raise self._closed_error()
        await self._send((_ONEWAY, 0, method, args, kwargs))

    async def _recv_loop(self):
        try:
            while True:
                msg = await _read_frame(self._reader)
                kind = msg[0]
                if (
                    kind in (_REQUEST, _RESPONSE, _ONEWAY)
                    and self.peer_protocol is None
                    and not self._legacy_warned
                ):
                    # Pre-handshake builds never send a HELLO (their recv
                    # loop silently skips ours), so a request/response
                    # arriving first is the only cross-version tell we get.
                    self._legacy_warned = True
                    logger.warning(
                        "peer on %s sent traffic before any HELLO frame: "
                        "likely a legacy ray_tpu build that predates the "
                        "wire-protocol handshake (this process speaks v%s). "
                        "If calls fail with pickle/handler errors, upgrade "
                        "the peer — mixed-version clusters are unsupported.",
                        self.name, self._protocol_version,
                    )
                if kind == _RESPONSE:
                    _, mid, ok, value = msg
                    fut = self._pending.get(mid)
                    if fut is not None and not fut.done():
                        if ok:
                            fut.set_result(value)
                        else:
                            fut.set_exception(
                                value
                                if isinstance(value, Exception)
                                else RemoteError(str(mid), str(value))
                            )
                elif kind == _REQUEST:
                    asyncio.get_running_loop().create_task(self._dispatch(msg))
                elif kind == _ONEWAY:
                    asyncio.get_running_loop().create_task(self._dispatch(msg, oneway=True))
                elif kind == _HELLO:
                    self.peer_protocol = msg[1]
                    if msg[1] != self._protocol_version:
                        self._protocol_error_msg = (
                            f"wire-protocol mismatch on {self.name}: peer "
                            f"speaks v{msg[1]}, this process v"
                            f"{self._protocol_version} — every ray_tpu "
                            "process in a cluster must run the same version"
                        )
                        # Best-effort flush of our own (already-buffered)
                        # HELLO so the peer can derive the same diagnosis.
                        try:
                            await self._writer.drain()
                        except Exception:
                            pass  # peer hung up first; it already has our HELLO or never will
                        break  # -> _shutdown fails pending calls with it
                # Unknown kinds: skipped (forward compatibility within a
                # protocol version).
        except (asyncio.IncompleteReadError, ConnectionResetError, BrokenPipeError, OSError):
            pass
        except asyncio.CancelledError:
            return
        finally:
            await self._shutdown()

    async def _dispatch(self, msg, oneway: bool = False):
        _, mid, method, args, kwargs = msg
        try:
            fn = getattr(self.handler, "rpc_" + method, None)
            if fn is None:
                raise RpcError(f"{type(self.handler).__name__} has no method {method!r}")
            result = fn(self, *args, **kwargs)
            if asyncio.iscoroutine(result):
                result = await result
            if not oneway:
                await self._send((_RESPONSE, mid, True, result))
        except Exception as e:  # noqa: BLE001 - must report any handler failure to caller
            if oneway:
                traceback.print_exc()
                return
            try:
                pickle.dumps(e)
                payload: Any = e
            except Exception:
                payload = RemoteError(method, traceback.format_exc())
            try:
                await self._send((_RESPONSE, mid, False, payload))
            except Exception:
                pass  # connection died before the error reply; caller sees ConnectionLost

    async def _shutdown(self):
        if self._closed:
            return
        self._closed = True
        from ray_tpu.devtools import leaksan as _leaksan

        _leaksan.untrack("rpc_conn", self)
        for fut in self._pending.values():
            if not fut.done():
                # Fresh instance per future (shared exception objects chain
                # tracebacks across unrelated awaiters).
                if self._protocol_error_msg:
                    fut.set_exception(RpcError(self._protocol_error_msg))
                else:
                    fut.set_exception(
                        ConnectionLost(f"connection {self.name} lost")
                    )
        self._pending.clear()
        try:
            self._writer.close()
        except Exception:
            pass
        for cb in self._close_callbacks:
            try:
                res = cb(self)
                if asyncio.iscoroutine(res):
                    await res
            except Exception:
                traceback.print_exc()

    async def close(self):
        if self._recv_task is not None:
            self._recv_task.cancel()
        await self._shutdown()


class RpcServer:
    """Accepts connections; each gets a Connection served by `handler_factory(conn)`."""

    def __init__(self, handler_factory: Callable[[Connection], Any]):
        self._handler_factory = handler_factory
        self._server: asyncio.AbstractServer | None = None
        self.connections: set[Connection] = set()
        self.port: int | None = None

    async def start(self, host: str = "127.0.0.1", port: int = 0):
        self._server = await asyncio.start_server(self._on_client, host, port)
        self.port = self._server.sockets[0].getsockname()[1]
        return self

    async def _on_client(self, reader, writer):
        conn = Connection(reader, writer, name="server-peer")
        conn.handler = self._handler_factory(conn)
        self.connections.add(conn)
        conn.on_close(lambda c: self.connections.discard(c))
        conn.start()

    async def close(self):
        if self._server is not None:
            self._server.close()
        for conn in list(self.connections):
            await conn.close()


async def connect(
    host: str, port: int, handler: Any = None, name: str = "client",
    timeout: float = 10.0, via: tuple | None = None,
    _protocol_version: int | None = None,
) -> Connection:
    """Open a peer connection. `via=(proxy_host, proxy_port, client_id)` tunnels
    through a client proxy (util/client/proxier.py): the first frame on the wire
    is a routing envelope naming the real (host, port) target; everything after
    is the normal symmetric protocol, relayed by the proxy."""
    if via is not None:
        proxy_host, proxy_port, client_id = via[0], via[1], via[2]
        token = via[3] if len(via) > 3 else None
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(proxy_host, proxy_port), timeout
        )
        # The envelope is JSON, not pickle: the proxy terminates untrusted
        # connections and must never unpickle pre-auth client bytes.
        import json as _json

        env = {"route": [host, int(port)], "client_id": client_id}
        if token:
            env["token"] = token
        payload = _json.dumps(env).encode()
        writer.write(struct.pack(_LEN_FMT, len(payload)) + payload)
        await writer.drain()
    else:
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(host, port), timeout
        )
    return Connection(reader, writer, handler, name=name,
                      _protocol_version=_protocol_version).start()


class IoLoop:
    """A dedicated asyncio loop thread (parity: core worker io_service thread)."""

    def __init__(self, name: str = "ray-tpu-io"):
        self.loop = asyncio.new_event_loop()
        self._thread = threading.Thread(target=self._run, name=name, daemon=True)
        self._started = threading.Event()
        self._thread.start()
        self._started.wait()

    def _run(self):
        asyncio.set_event_loop(self.loop)
        self.loop.call_soon(self._started.set)
        self.loop.run_forever()

    def run(self, coro, timeout: float | None = None):
        """Run a coroutine on the io thread and block for its result."""
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        return fut.result(timeout)

    def spawn(self, coro):
        """Fire-and-forget a coroutine on the io thread."""
        return asyncio.run_coroutine_threadsafe(coro, self.loop)

    def stop(self):
        def _stop():
            tasks = [t for t in asyncio.all_tasks(self.loop) if t is not asyncio.current_task()]
            for task in tasks:
                task.cancel()

            async def _drain():
                await asyncio.gather(*tasks, return_exceptions=True)
                self.loop.stop()

            self.loop.create_task(_drain())

        self.loop.call_soon_threadsafe(_stop)
        self._thread.join(timeout=2)
