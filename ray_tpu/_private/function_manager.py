"""Function/actor-class table backed by the GCS KV store.

Design parity: reference `python/ray/_private/function_manager.py` + GCS function table
(`src/ray/gcs/gcs_function_manager.h`): functions and actor classes are cloudpickled once
by the exporting driver, stored under a content hash, and lazily fetched + cached by
executing workers.
"""

from __future__ import annotations

import hashlib
import threading

import cloudpickle

_NS = "fn"


class FunctionManager:
    def __init__(self, worker):
        self._worker = worker
        self._cache: dict[bytes, object] = {}
        self._exported: set[bytes] = set()
        self._lock = threading.Lock()

    def export(self, obj) -> bytes:
        """Pickle and upload; returns the content-hash key."""
        blob = cloudpickle.dumps(obj)
        key = hashlib.sha1(blob).digest()
        with self._lock:
            if key in self._exported:
                return key
        self._worker.gcs_kv_put(_NS, key, blob, overwrite=False)
        with self._lock:
            self._exported.add(key)
            self._cache[key] = obj
        return key

    def load(self, key: bytes):
        with self._lock:
            if key in self._cache:
                return self._cache[key]
        blob = self._worker.gcs_kv_get(_NS, key)
        if blob is None:
            raise RuntimeError(f"function {key.hex()[:12]} not found in GCS")
        obj = cloudpickle.loads(blob)
        with self._lock:
            self._cache[key] = obj
        return obj
