"""Shared state-API filter predicates.

One implementation serves both sides: the client (`ray_tpu.util.state`
filtering nodes/actors it already fetched) and the GCS (pushing task-event
filters down to the server) — so tasks vs actors/nodes can never drift to
different comparison semantics. Parity: reference
python/ray/util/state/common.py predicate set (=/!= plus comparisons).
"""

from __future__ import annotations

from typing import Any, Callable, Iterable

OPS = {
    "=": lambda a, b: a == b,
    "==": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
}


def coerce_pair(a: Any, b: Any):
    """Compare numerically when both sides parse as numbers, else as strings
    (entity fields arrive as heterogeneous python values)."""
    try:
        return float(a), float(b)
    except (TypeError, ValueError):
        return str(a), str(b)


def build_predicate(filters: Iterable) -> Callable[[dict], bool]:
    """Compile (key, op, value) triples into one row predicate; raises
    ValueError on an unknown operator."""
    compiled = []
    for key, op, value in filters:
        if op not in OPS:
            raise ValueError(
                f"unsupported filter op {op!r}; one of {sorted(OPS)}"
            )
        compiled.append((key, OPS[op], value))

    def match(row: dict) -> bool:
        return all(pred(*coerce_pair(row.get(key), value))
                   for key, pred, value in compiled)

    return match
