"""Chaos/fault-injection test utilities.

Design parity: reference `python/ray/_private/test_utils.py` — the ResourceKiller
hierarchy (`RayletKiller` :1479, `WorkerKillerActor` :1591,
`get_and_run_resource_killer` :1665) used by chaos and long-running release tests to
randomly kill nodes/workers while a workload runs.
"""

from __future__ import annotations

import random
import threading
import time
from typing import List, Optional

import ray_tpu


class ResourceKiller:
    """Periodically kill one target until stopped. Subclasses choose targets."""

    def __init__(self, interval_s: float = 1.0, max_to_kill: int = 3,
                 seed: Optional[int] = None):
        self._interval = interval_s
        self._max = max_to_kill
        self._rng = random.Random(seed)
        self.killed: List = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _targets(self) -> list:
        raise NotImplementedError

    def _kill(self, target):
        raise NotImplementedError

    def run(self):
        if self._thread is None:
            self._thread = threading.Thread(target=self._loop, daemon=True)
            self._thread.start()

    def _loop(self):
        while not self._stop.is_set() and len(self.killed) < self._max:
            self._stop.wait(self._interval)
            if self._stop.is_set():
                return
            targets = [t for t in self._targets() if t not in self.killed]
            if not targets:
                continue
            target = self._rng.choice(targets)
            try:
                self._kill(target)
                self.killed.append(target)
            except Exception:
                pass

    def stop(self) -> list:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        return list(self.killed)


class NodeKiller(ResourceKiller):
    """Kills random non-head worker NODES of a cluster_utils.Cluster
    (RayletKiller/EC2InstanceTerminator role)."""

    def __init__(self, cluster, **kwargs):
        super().__init__(**kwargs)
        self._cluster = cluster

    def _targets(self) -> list:
        return list(self._cluster.worker_nodes)

    def _kill(self, node):
        self._cluster.remove_node(node)


class ActorKiller(ResourceKiller):
    """Kills random live actors matching a class-name filter (WorkerKillerActor role)."""

    def __init__(self, class_name: Optional[str] = None, **kwargs):
        super().__init__(**kwargs)
        self._class_name = class_name

    def _targets(self) -> list:
        from ray_tpu.util import state

        out = []
        for a in state.list_actors():
            if a.get("state") != "ALIVE":
                continue
            if self._class_name and a.get("class_name") != self._class_name:
                continue
            out.append(a["actor_id"])
        return out

    def _kill(self, actor_id):
        from ray_tpu.actor import ActorHandle

        # Chaos simulates a CRASH: no_restart=False lets max_restarts kick in
        # (no_restart=True is a permanent administrative kill).
        ray_tpu.kill(ActorHandle(actor_id, [], ""), no_restart=False)


def get_and_run_resource_killer(killer_cls, interval_s: float = 1.0, **kwargs):
    """Parity: test_utils.get_and_run_resource_killer — construct + start."""
    killer = killer_cls(interval_s=interval_s, **kwargs)
    killer.run()
    return killer
