"""Binary IDs for the ray_tpu runtime.

Design parity: reference `src/ray/common/id.h` (TaskID/ObjectID/ActorID/NodeID/JobID with
binary+hex forms). We keep the same conceptual family but a simpler layout: every ID is a
fixed-size random byte string; ObjectIDs embed the producing TaskID plus a return index so
lineage can be recovered from the ID alone (reference: ObjectID = TaskID + index).
"""

from __future__ import annotations

import os
import struct
import threading

_UNIQUE_LEN = 16  # bytes of entropy for standalone ids
_TASK_LEN = 16
_OBJECT_LEN = _TASK_LEN + 4  # task id + big-endian return index

# Per-process id generator state (see BaseID.from_random). Re-seeded after
# fork so spawned workers never share a sequence.
_gen_seed = os.urandom(24)
_gen_counter = 0
_gen_lock = threading.Lock()
_gen_pid = os.getpid()


def _reseed_if_forked():
    global _gen_seed, _gen_counter, _gen_pid
    if os.getpid() != _gen_pid:
        _gen_seed = os.urandom(24)
        _gen_counter = 0
        _gen_pid = os.getpid()


class BaseID:
    __slots__ = ("_bytes",)
    SIZE = _UNIQUE_LEN

    def __init__(self, binary: bytes):
        if len(binary) != self.SIZE:
            raise ValueError(
                f"{type(self).__name__} requires {self.SIZE} bytes, got {len(binary)}"
            )
        self._bytes = bytes(binary)

    @classmethod
    def from_random(cls):
        # One urandom seed per process, then counter-added (mod 2^(8*SIZE)):
        # uniqueness holds (full-width per-process entropy x monotonic counter)
        # and hot submit loops skip ~26µs of kernel entropy per task. Small IDs
        # (JobID) keep true randomness — the counter would dominate their width.
        if cls.SIZE < 16:
            return cls(os.urandom(cls.SIZE))
        with _gen_lock:
            global _gen_counter
            _reseed_if_forked()
            _gen_counter += 1
            n = _gen_counter
        width = cls.SIZE
        base = int.from_bytes(_gen_seed[:width].ljust(width, b"\0"), "big")
        return cls(((base + n) % (1 << (8 * width))).to_bytes(width, "big"))

    @classmethod
    def from_hex(cls, hex_str: str):
        return cls(bytes.fromhex(hex_str))

    @classmethod
    def nil(cls):
        return cls(b"\x00" * cls.SIZE)

    def is_nil(self) -> bool:
        return self._bytes == b"\x00" * self.SIZE

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    def __hash__(self):
        return hash((type(self).__name__, self._bytes))

    def __eq__(self, other):
        return type(other) is type(self) and other._bytes == self._bytes

    def __lt__(self, other):
        return self._bytes < other._bytes

    def __repr__(self):
        return f"{type(self).__name__}({self.hex()[:16]}…)" if len(
            self._bytes
        ) > 8 else f"{type(self).__name__}({self.hex()})"

    def __reduce__(self):
        return (type(self), (self._bytes,))


class JobID(BaseID):
    SIZE = 4

    @classmethod
    def from_int(cls, value: int):
        return cls(struct.pack(">I", value))

    def int(self) -> int:
        return struct.unpack(">I", self._bytes)[0]


class NodeID(BaseID):
    SIZE = _UNIQUE_LEN


class WorkerID(BaseID):
    SIZE = _UNIQUE_LEN


class ActorID(BaseID):
    SIZE = _UNIQUE_LEN


class PlacementGroupID(BaseID):
    SIZE = _UNIQUE_LEN


class TaskID(BaseID):
    SIZE = _TASK_LEN


class ObjectID(BaseID):
    SIZE = _OBJECT_LEN

    @classmethod
    def from_task(cls, task_id: TaskID, index: int) -> "ObjectID":
        return cls(task_id.binary() + struct.pack(">I", index))

    def task_id(self) -> TaskID:
        return TaskID(self._bytes[:_TASK_LEN])

    def index(self) -> int:
        return struct.unpack(">I", self._bytes[_TASK_LEN:])[0]


class _Counter:
    """Monotonic counter for per-process sequence numbers."""

    def __init__(self):
        self._value = 0
        self._lock = threading.Lock()

    def next(self) -> int:
        with self._lock:
            self._value += 1
            return self._value
