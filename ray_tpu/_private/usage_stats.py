"""Usage statistics: opt-out, local-only usage recording.

Design parity: reference `python/ray/_common/usage/usage_lib.py` — an opt-out
recorder of coarse cluster/library usage. Divergence by design: this framework
targets air-gapped TPU pods, so nothing is ever transmitted; records land in a
local JSON file under the session dir (the reference POSTs to a collector URL).
Disable with RAY_TPU_USAGE_STATS_ENABLED=0.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional

_lock = threading.Lock()
_state = {
    "schema_version": 1,
    "session_start": None,
    "libraries_used": [],
    "features_used": [],
    "cluster": {},
}
_path: Optional[str] = None


def enabled() -> bool:
    return os.environ.get("RAY_TPU_USAGE_STATS_ENABLED", "1").lower() not in (
        "0", "false", "no", "off",
    )


def start_session(session_dir: str, cluster_meta: dict):
    global _path
    if not enabled():
        return
    with _lock:
        _path = os.path.join(session_dir, "usage_stats.json")
        _state["session_start"] = time.time()
        _state["cluster"] = dict(cluster_meta)
    _flush()


def record_library_usage(name: str):
    """Called by library entry points (train/tune/serve/data/rllib/llm)."""
    if not enabled():
        return
    with _lock:
        if name not in _state["libraries_used"]:
            _state["libraries_used"].append(name)
    _flush()


def record_feature(name: str):
    if not enabled():
        return
    with _lock:
        if name not in _state["features_used"]:
            _state["features_used"].append(name)
    _flush()


def _flush():
    with _lock:
        path = _path
        if path is None:
            return
        blob = json.dumps(_state, indent=2)
    try:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(blob)
        os.replace(tmp, path)
    except OSError:
        pass


def read(session_dir: str) -> Optional[dict]:
    try:
        with open(os.path.join(session_dir, "usage_stats.json")) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None
