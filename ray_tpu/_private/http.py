"""Minimal dependency-free HTTP/1.1 helpers shared by the serve proxy and the
dashboard (one parser, one response writer — not two hand-rolled copies)."""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, field
from typing import Dict, Optional
from urllib.parse import parse_qsl, urlsplit

_REASONS = {
    200: "OK",
    400: "Bad Request",
    404: "Not Found",
    500: "Internal Server Error",
}


@dataclass
class HttpRequest:
    method: str = "GET"
    path: str = "/"
    query: Dict[str, str] = field(default_factory=dict)
    headers: Dict[str, str] = field(default_factory=dict)
    body: bytes = b""


async def read_http_request(reader: asyncio.StreamReader) -> Optional[HttpRequest]:
    line = await reader.readline()
    if not line:
        return None
    method, target, _version = line.decode().split(" ", 2)
    headers: Dict[str, str] = {}
    while True:
        hline = await reader.readline()
        if hline in (b"\r\n", b"\n", b""):
            break
        k, _, v = hline.decode().partition(":")
        headers[k.strip().lower()] = v.strip()
    body = b""
    length = int(headers.get("content-length", "0") or 0)
    if length:
        body = await reader.readexactly(length)
    split = urlsplit(target)
    return HttpRequest(
        method=method.upper(),
        path=split.path,
        query=dict(parse_qsl(split.query)),
        headers=headers,
        body=body,
    )


async def write_http_response(writer: asyncio.StreamWriter, status: int,
                              body: bytes, content_type: str):
    reason = _REASONS.get(status, "OK")
    writer.write(
        f"HTTP/1.1 {status} {reason}\r\n"
        f"Content-Type: {content_type}\r\n"
        f"Content-Length: {len(body)}\r\n"
        f"Connection: close\r\n\r\n".encode()
        + body
    )
    await writer.drain()


async def write_http_chunked(writer: asyncio.StreamWriter, status: int,
                             content_type: str, chunks):
    """Stream a chunked-transfer response; `chunks` is an async iterator of bytes."""
    reason = _REASONS.get(status, "OK")
    writer.write(
        f"HTTP/1.1 {status} {reason}\r\n"
        f"Content-Type: {content_type}\r\n"
        f"Transfer-Encoding: chunked\r\n"
        f"Connection: close\r\n\r\n".encode()
    )
    await writer.drain()
    async for chunk in chunks:
        if not chunk:
            continue
        writer.write(f"{len(chunk):X}\r\n".encode() + chunk + b"\r\n")
        await writer.drain()
    writer.write(b"0\r\n\r\n")
    await writer.drain()
