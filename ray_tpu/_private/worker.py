"""CoreWorker: the in-process runtime embedded in every driver and worker process.

Design parity: reference `src/ray/core_worker/core_worker.h` (SubmitTask :856, CreateActor
:881, SubmitActorTask :938, Put :483, Get :659) + `python/ray/_private/worker.py`. Holds
the in-process memory store (reference: store_provider/memory_store), the reference counter
(reference_counter.h), the function manager, dependency-gated task submission (reference:
DependencyResolver in task_submission/), and the task execution loop with per-caller
ordered actor queues (task_execution/ actor scheduling queues).
"""

from __future__ import annotations

import asyncio
import os
import sys
import threading
import time
import traceback
from collections import deque
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Optional

from ray_tpu._private import rpc, serialization
from ray_tpu._private.config import CONFIG, bind_host_for, get_node_ip
from ray_tpu._private.function_manager import FunctionManager
from ray_tpu._private.ids import ActorID, NodeID, ObjectID, TaskID, WorkerID, _Counter
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.object_store import LocalObjectReader
from ray_tpu.exceptions import (
    GetTimeoutError,
    ObjectLostError,
    RayTpuError,
    RayTpuTaskError,
)

_global_worker: Optional["CoreWorker"] = None
_global_lock = threading.Lock()
_MISS = object()  # local-arena fast-path miss sentinel

# Starting per-worker pipeline depth for the lease fast path
# (CONFIG.lease_pipeline_min_depth). Shallow by default so a burst queues
# work and acquires more workers (parallelism); lease denials ramp the depth
# toward CONFIG.lease_worker_slots (throughput via large coalesced frames
# once the node is saturated). 2, not 1: one task executing + one parked
# keeps the worker from going idle during the result/refill round trip.
def _lease_depth_min() -> int:
    return max(1, CONFIG.lease_pipeline_min_depth)


def _addr_key(addr: dict) -> tuple:
    """Hashable identity of a worker address (borrower bookkeeping)."""
    return (addr["node_id"].hex(), addr["worker_id"].hex())


def global_worker() -> "CoreWorker":
    if _global_worker is None:
        raise RuntimeError("ray_tpu.init() has not been called")
    return _global_worker


def global_worker_or_none() -> Optional["CoreWorker"]:
    return _global_worker


def set_global_worker(worker: Optional["CoreWorker"]):
    global _global_worker
    with _global_lock:
        _global_worker = worker


class _Record:
    __slots__ = ("data", "error", "in_plasma", "resolved", "event", "callbacks")

    def __init__(self):
        self.data: bytes | None = None
        self.error = False
        self.in_plasma = False
        self.resolved = False
        self.event = threading.Event()
        self.callbacks: list = []


class MemoryStore:
    """In-process store for inline objects and pending futures (memory_store.h parity)."""

    def __init__(self):
        self._records: dict[ObjectID, _Record] = {}
        self._lock = threading.Lock()

    def create_pending(self, object_id: ObjectID) -> _Record:
        with self._lock:
            rec = self._records.get(object_id)
            if rec is None:
                rec = _Record()
                self._records[object_id] = rec
            return rec

    def get(self, object_id: ObjectID) -> _Record | None:
        with self._lock:
            return self._records.get(object_id)

    def resolve(self, object_id: ObjectID, data: bytes | None, error: bool,
                in_plasma: bool) -> bool:
        """Resolve an existing record. Returns False if the record was already freed
        (all refs dropped before the result arrived) — caller should discard/free."""
        with self._lock:
            rec = self._records.get(object_id)
            if rec is None:
                return False
            if rec.resolved and not rec.error and error:
                # First success wins: a late failure report (e.g. delegated-task
                # recovery racing a completion that already landed) must not
                # clobber a delivered result.
                return True
            rec.data = data
            rec.error = error
            rec.in_plasma = in_plasma
            rec.resolved = True
            callbacks = rec.callbacks
            rec.callbacks = []
        rec.event.set()
        for cb in callbacks:
            try:
                cb(object_id, rec)
            except Exception:
                traceback.print_exc()
        return True

    def add_done_callback(self, object_id: ObjectID, cb) -> bool:
        """Returns True if registered (pending), False if already resolved."""
        with self._lock:
            rec = self._records.get(object_id)
            if rec is None:
                rec = _Record()
                self._records[object_id] = rec
            if rec.resolved:
                return False
            rec.callbacks.append(cb)
            return True

    def pop(self, object_id: ObjectID):
        with self._lock:
            self._records.pop(object_id, None)


class ReferenceCounter:
    """Distributed reference counts with a sequenced borrowing protocol.

    Reference: `src/ray/core_worker/reference_counter.h` — the owner frees an
    object cluster-wide only when (a) its own local count is zero AND (b) every
    registered borrower has released.

    Borrow registration is SEQUENCED through the task protocol, never a bare
    fire-and-forget racing the owner's release:

    - **Task args**: while a task executes, its borrowed arg refs are protected
      by the caller's arg pins, so the executor defers registration entirely
      (a per-task borrow sink). Refs still held at completion ride the reply's
      `borrows` list; the caller records the executor as a borrower BEFORE it
      releases those pins (same message, strict order). The executor's later
      release routes to the caller (its borrow parent), forming the reference's
      borrower tree rather than a flat owner-centric count.
    - **Result refs**: refs serialized into a task's results are captured at
      pickle time; the executor pre-registers the caller as a sub-borrower
      before replying and the reply's `result_refs` pre-seed the caller's
      parent table, so the caller's first local ref never emits a racing +1
      and its release routes back to the executor.
    - Refs that arrive outside the task protocol (inside a put object) keep
      the legacy immediate report as a best-effort fallback.

    Borrower counts are keyed per borrower address; an audit loop drops
    borrowers whose process died without releasing (raylet/GCS death signals +
    direct pings), so crashes reconcile instead of leaking the object.
    """

    def __init__(self, worker: "CoreWorker"):
        self._counts: dict[ObjectID, int] = {}
        self._owned: set[ObjectID] = set()
        # id -> {borrower_key: count}; for owned ids these are direct borrowers,
        # for borrowed ids they are sub-borrowers this process handed refs to.
        self._borrows: dict[ObjectID, dict[str, int]] = {}
        self._borrowed_owner: dict[ObjectID, dict] = {}  # borrowed id -> PARENT address
        self._pending_free: set[ObjectID] = set()  # local zero, waiting on borrowers
        # Borrowed ids whose local count hit zero while sub-borrowers remain:
        # the upstream release is deferred until they drain.
        self._pending_upstream: set[ObjectID] = set()
        # Borrowed ids registered via the sequenced paths that have not yet
        # taken a local ref (pre-seeded by result_refs): the first local ref
        # must not emit the legacy racing report.
        self._preregistered: set[ObjectID] = set()
        # Ids first borrowed inside the currently-executing task (deferred).
        self._task_deferred: set[ObjectID] = set()
        # borrowed id -> the object's TRUE owner (never re-parented). Used to
        # mirror sub-borrower registrations to the owner so an INTERMEDIATE
        # borrower's crash cannot free an object a live grandchild holds
        # (reference: transitive borrower propagation,
        # src/ray/core_worker/reference_counter.h:43).
        self._true_owner: dict[ObjectID, dict] = {}
        self._lock = threading.Lock()
        self._worker = worker
        # GC-safety: __del__ may fire via garbage collection INSIDE a section
        # that already holds one of this runtime's locks (same thread), so
        # finalizers must never lock. They append to this deque (GIL-atomic)
        # and the release runs later from drain_deferred() on a normal API path.
        self._deferred: deque = deque()

    def defer_remove(self, object_id: ObjectID):
        """Finalizer-safe ref release: enqueue only; no locks, no RPC."""
        self._deferred.append(("ref", object_id))

    def defer_actor_pin_release(self, actor_id):
        self._deferred.append(("actor_pins", actor_id))

    def drain_deferred(self):
        """Apply releases queued by finalizers. Called from non-finalizer paths
        (put/get/submit/...) and the periodic flush loop, never from __del__."""
        while True:
            try:
                kind, ident = self._deferred.popleft()
            except IndexError:
                return
            if kind == "ref":
                self.remove_local_ref(ident)
            else:
                self._worker.release_actor_arg_pins(ident)

    def add_owned(self, object_id: ObjectID):
        with self._lock:
            self._owned.add(object_id)

    def add_local_ref(self, object_id: ObjectID, owner: dict | None = None):
        if owner is not None:
            self.record_true_owner(object_id, owner)
        report_to = None
        materialized = False
        with self._lock:
            n = self._counts.get(object_id, 0)
            self._counts[object_id] = n + 1
            self._pending_free.discard(object_id)  # re-acquired before borrowers drained
            self._pending_upstream.discard(object_id)
            if (
                n == 0
                and owner is not None
                and object_id not in self._owned
                and owner.get("worker_id") is not None
                and owner["worker_id"] != self._worker.worker_id
            ):
                if object_id in self._preregistered:
                    # Sequenced handoff (result_refs): parent already seeded,
                    # parent already counted us — no report. The materialized
                    # note runs after this lock drops (lock order: never take
                    # _embedded_lock under rc._lock — _settle_embedded_on_free
                    # holds them in the opposite order).
                    self._preregistered.discard(object_id)
                    materialized = True
                elif object_id not in self._borrowed_owner:
                    sink = self._worker._task_borrow_sink()
                    if sink is not None:
                        # Executing a task: the caller's arg pins protect the
                        # object until completion; registration (if the ref
                        # survives the task) rides the reply, sequenced.
                        sink[object_id] = owner
                        self._borrowed_owner[object_id] = owner
                        self._task_deferred.add(object_id)
                    else:
                        # Outside the task protocol (ref inside a put object):
                        # legacy immediate report, best effort.
                        self._borrowed_owner[object_id] = owner
                        report_to = owner
        if materialized:
            self._worker._note_embedded_materialized(object_id)
        if report_to is not None:
            self._worker._report_borrow(object_id, report_to, +1)

    def remove_local_ref(self, object_id: ObjectID):
        free = False
        report_to = None
        with self._lock:
            n = self._counts.get(object_id, 0) - 1
            if n > 0:
                self._counts[object_id] = n
            else:
                self._counts.pop(object_id, None)
                if object_id in self._task_deferred:
                    if self._borrow_total_locked(object_id) > 0:
                        # A sub-borrower registered with us mid-task (we handed
                        # the ref onward): we must stay in the chain — the
                        # reply handoff re-parents us to the caller and lists
                        # the id in `borrows`.
                        self._task_deferred.discard(object_id)
                        self._pending_upstream.add(object_id)
                    else:
                        # Dropped before the task finished: registration never
                        # happened anywhere, so nothing to report.
                        self._task_deferred.discard(object_id)
                        self._borrowed_owner.pop(object_id, None)
                        sink = self._worker._task_borrow_sink()
                        if sink is not None:
                            sink.pop(object_id, None)
                elif object_id in self._borrowed_owner:
                    if self._borrow_total_locked(object_id) > 0:
                        # Sub-borrowers still hold refs we handed out: the
                        # upstream release waits for them.
                        self._pending_upstream.add(object_id)
                    else:
                        report_to = self._borrowed_owner.pop(object_id)
                        self._true_owner.pop(object_id, None)
                elif object_id in self._owned:
                    if self._borrow_total_locked(object_id) > 0:
                        self._pending_free.add(object_id)
                    else:
                        self._owned.discard(object_id)
                        free = True
        if report_to is not None:
            self._worker._report_borrow(object_id, report_to, -1)
        if free:
            self._worker._free_owned_object(object_id)

    def _borrow_total_locked(self, object_id: ObjectID) -> int:
        # Negative entries are pending releases whose registration is still in
        # flight (see _apply_borrow): they hold nothing alive.
        return sum(v for v in self._borrows.get(object_id, {}).values() if v > 0)

    def add_sub_borrow(self, object_id: ObjectID, borrower_key: str):
        """Count a downstream borrower BEFORE the message that informs it is
        sent (the sequencing that makes the handoff race-free)."""
        with self._lock:
            per = self._borrows.setdefault(object_id, {})
            per[borrower_key] = per.get(borrower_key, 0) + 1
            mirror = self._mirror_target_locked(object_id)
        if mirror is not None:
            self._worker._report_borrow(object_id, mirror, +1, borrower_key)

    def _mirror_target_locked(self, object_id: ObjectID) -> dict | None:
        """The true owner to mirror a sub-borrower count to — None when this
        process IS the owner (its table is already authoritative)."""
        if object_id in self._owned:
            return None
        return self._true_owner.get(object_id)

    def record_true_owner(self, object_id: ObjectID, owner: dict | None):
        if owner is None or owner.get("worker_id") == self._worker.worker_id:
            return
        with self._lock:
            if object_id not in self._owned:
                self._true_owner.setdefault(object_id, owner)

    def pre_register_borrow(self, object_id: ObjectID, parent: dict):
        """Caller side of a result-ref handoff: seed the parent so the first
        local ref neither re-reports nor routes its release to the raw owner."""
        with self._lock:
            if (
                object_id in self._owned
                or object_id in self._borrowed_owner
                or parent.get("worker_id") == self._worker.worker_id
            ):
                return False
            self._borrowed_owner[object_id] = parent
            self._preregistered.add(object_id)
            return True

    def settle_unmaterialized(self, object_id: ObjectID) -> dict | None:
        """A reply's embedded ref was never deserialized and its containing
        result is gone: undo the pre-registration; returns the parent to
        release to (the executor pre-counted us)."""
        with self._lock:
            if object_id not in self._preregistered:
                return None
            self._preregistered.discard(object_id)
            return self._borrowed_owner.pop(object_id, None)

    def promote_task_borrows(self, kept: dict, parent: dict):
        """Executor side at task completion: arg borrows that survived the task
        re-parent to the caller (whose reply-side registration is sequenced
        ahead of its pin release)."""
        with self._lock:
            for object_id in kept:
                if object_id in self._task_deferred:
                    self._task_deferred.discard(object_id)
                    self._borrowed_owner[object_id] = parent
                elif (
                    object_id in self._pending_upstream
                    and object_id in self._borrowed_owner
                ):
                    # Held only by sub-borrowers now: re-route the eventual
                    # upstream release to the caller, who counts us via the
                    # reply's `borrows` list.
                    self._borrowed_owner[object_id] = parent

    def promote_captured(self, object_ids, parent: dict) -> list:
        """Deferred arg borrows captured into a task's results: re-parent to
        the caller immediately (their only local ref may die with the frame)
        and return those promoted, for the reply's `borrows` list."""
        promoted = []
        with self._lock:
            for object_id in object_ids:
                if object_id in self._task_deferred:
                    self._task_deferred.discard(object_id)
                    self._borrowed_owner[object_id] = parent
                    promoted.append(object_id)
        return promoted

    def update_borrow(self, object_id: ObjectID, delta: int,
                      borrower_key: str = "?"):
        """Parent side: a borrower registered (+1) or released (-1)."""
        self._apply_borrow(object_id, delta, borrower_key)

    def drop_borrow_entry(self, object_id: ObjectID, borrower_key: str):
        """Audit verdict: a live borrower no longer holds this id (its release
        was lost to a crashed parent): reconcile just that entry."""
        self._apply_borrow(object_id, None, borrower_key)

    def drop_borrower(self, borrower_key: str):
        """A borrower process died without releasing: reconcile its counts."""
        with self._lock:
            stale = [
                oid for oid, per in self._borrows.items() if borrower_key in per
            ]
        for oid in stale:
            self._apply_borrow(oid, None, borrower_key)

    def _apply_borrow(self, object_id: ObjectID, delta, borrower_key: str):
        free = False
        report_to = None
        mirror_to = None
        mirror_delta = 0
        with self._lock:
            per = self._borrows.setdefault(object_id, {})
            # Mirror every sub-borrower count change to the TRUE owner (no-op
            # when we are the owner): the owner's table then lists every
            # transitive borrower, so this process crashing cannot strand a
            # live grandchild's count. Mirrors land via the same routed
            # borrow_update; negative-entry tolerance absorbs reorders.
            mirror_to = self._mirror_target_locked(object_id)
            if mirror_to is not None:
                if delta is None:
                    mirror_delta = -max(per.get(borrower_key, 0), 0)
                else:
                    mirror_delta = delta
            if delta is None:
                per.pop(borrower_key, None)  # borrower died: drop all its refs
            else:
                # A release may arrive BEFORE its matching registration when the
                # two ride different channels (reply-borne +1 vs raylet-routed
                # -1): keep the negative entry as a pending release so the late
                # +1 nets to zero instead of resurrecting a count nobody will
                # ever release.
                n = per.get(borrower_key, 0) + delta
                if n == 0:
                    per.pop(borrower_key, None)
                else:
                    per[borrower_key] = n
            if not any(v > 0 for v in per.values()):
                if not per:
                    self._borrows.pop(object_id, None)
                if (
                    object_id in self._pending_free
                    and self._counts.get(object_id, 0) <= 0
                    and object_id in self._owned
                ):
                    self._pending_free.discard(object_id)
                    self._owned.discard(object_id)
                    free = True
                elif (
                    object_id in self._pending_upstream
                    and self._counts.get(object_id, 0) <= 0
                ):
                    self._pending_upstream.discard(object_id)
                    report_to = self._borrowed_owner.pop(object_id, None)
                    self._true_owner.pop(object_id, None)
        if mirror_to is not None and mirror_delta:
            self._worker._report_borrow(object_id, mirror_to, mirror_delta,
                                        borrower_key)
        if report_to is not None:
            self._worker._report_borrow(object_id, report_to, -1)
        if free:
            self._worker._free_owned_object(object_id)

    def borrower_snapshot(self) -> dict[str, list[ObjectID]]:
        """borrower_key -> ids it holds (for the crash-audit loop)."""
        with self._lock:
            out: dict[str, list[ObjectID]] = {}
            for oid, per in self._borrows.items():
                for key in per:
                    out.setdefault(key, []).append(oid)
            return out

    def num_refs(self, object_id: ObjectID) -> int:
        with self._lock:
            return self._counts.get(object_id, 0)

    def num_borrows(self, object_id: ObjectID) -> int:
        with self._lock:
            return self._borrow_total_locked(object_id)


class _LogDeduplicator:
    """Collapse identical log lines spamming from many workers (reference:
    python/ray/_private/ray_logging LogDeduplicator — the '[repeated Nx across
    cluster]' behavior). Lines are keyed with digits masked so counters and
    pids don't defeat the match; the first occurrence prints immediately, later
    ones within the window are counted and summarized when the window expires.
    Disabled via RAY_TPU_LOG_DEDUP=0 (every line passes through verbatim)."""

    @property
    def WINDOW_S(self) -> float:
        return CONFIG.log_dedup_window_s

    def __init__(self):
        import re

        self._mask = re.compile(r"\d+")
        self._seen: dict[str, dict] = {}
        self.enabled = os.environ.get("RAY_TPU_LOG_DEDUP", "1") not in (
            "0", "false", "off"
        )

    def ingest(self, prefix: str, pid, lines) -> str:
        if not self.enabled:
            return "".join(f"{prefix} {ln}\n" for ln in lines)
        now = time.monotonic()
        out = []
        out.append(self.flush_expired(now))
        for ln in lines:
            key = self._mask.sub("#", ln)
            entry = self._seen.get(key)
            # flush_expired above evicted every stale entry, so a hit here is
            # always inside the window.
            if entry is not None:
                entry["count"] += 1
                entry["pids"].add(pid)
                continue
            self._seen[key] = {
                "first_t": now, "count": 0, "line": ln, "prefix": prefix,
                "pids": {pid},
            }
            out.append(f"{prefix} {ln}\n")
        return "".join(out)

    def flush_expired(self, now: float | None = None) -> str:
        now = time.monotonic() if now is None else now
        out = []
        for key in list(self._seen):
            entry = self._seen[key]
            if now - entry["first_t"] >= self.WINDOW_S:
                del self._seen[key]
                if entry["count"]:
                    out.append(self._summary(entry))
        return "".join(out)

    @staticmethod
    def _summary(entry) -> str:
        n, pids = entry["count"], len(entry["pids"])
        return (
            f"{entry['prefix']} {entry['line']} "
            f"[repeated {n}x across {pids} process(es); set RAY_TPU_LOG_DEDUP=0 "
            f"to disable deduplication]\n"
        )


class _StreamState:
    """Owner-side state of one streaming-generator task (ObjectRefStream parity,
    reference task_manager.h). Items can arrive out of order (RPC dispatch is
    concurrent per message), so they buffer by index and emit in order."""

    def __init__(self):
        self.items: dict[int, "ObjectRef"] = {}
        self.total: int | None = None  # set at end-of-stream
        self.abort_error: Exception | None = None  # producer died, retries exhausted
        self.cond = threading.Condition()


class ObjectRefGenerator:
    """Iterator over the ObjectRefs yielded by a streaming task.

    Reference: `ObjectRefGenerator` / streaming generators
    (`num_returns="streaming"`). Each __next__ returns the next item's ObjectRef
    as soon as the executor has produced it — consumption overlaps production.
    A mid-stream exception in the generator body becomes a final error ref whose
    get() raises, followed by StopIteration.
    """

    def __init__(self, task_id: TaskID, worker: "CoreWorker"):
        self._task_id = task_id
        self._worker = worker
        self._consumed = 0

    def __iter__(self):
        return self

    def __next__(self):
        return self._next(timeout=None)

    def _next(self, timeout: float | None):
        st = self._worker._streams.get(self._task_id)
        if st is None:
            raise StopIteration
        deadline = None if timeout is None else time.monotonic() + timeout
        with st.cond:
            while True:
                if self._consumed in st.items:
                    ref = st.items.pop(self._consumed)
                    self._consumed += 1
                    return ref
                if st.total is not None and self._consumed >= st.total:
                    self._worker._streams.pop(self._task_id, None)
                    raise StopIteration
                if st.abort_error is not None:
                    self._worker._streams.pop(self._task_id, None)
                    raise st.abort_error
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise GetTimeoutError(
                        f"no stream item within timeout for task {self._task_id.hex()}"
                    )
                st.cond.wait(0.2 if remaining is None else min(0.2, remaining))

    def __aiter__(self):
        return self

    async def __anext__(self):
        # StopIteration cannot cross an executor Future (Python converts it to
        # RuntimeError); end-of-stream travels as a sentinel instead.
        done = object()

        def step():
            try:
                return self.__next__()
            except StopIteration:
                return done

        item = await asyncio.get_running_loop().run_in_executor(None, step)
        if item is done:
            raise StopAsyncIteration
        return item

    def __del__(self):
        try:
            from ray_tpu.devtools import distsan

            # Local dict cleanup only: the finalizer tag asserts (under
            # RAY_TPU_DISTSAN=1) that no control-plane call sneaks in here.
            with distsan.finalizer("stream-iterator"):
                self._worker._streams.pop(self._task_id, None)
        except Exception:
            pass


class _ActorRuntime:
    """Execution state when this worker hosts an actor.

    Concurrency groups (reference: core_worker/task_execution/
    concurrency_group_manager.cc): each named group gets its OWN thread pool
    (sync actors) or semaphore (async actors) sized to its declared limit, so
    a method bound to one group cannot starve another — the default pool
    keeps max_concurrency for unbound methods. Dispatch releases tasks in
    per-caller seq order but never blocks on execution, so in-group ordering
    holds while groups stay independent. `out_of_order` skips seq gating
    entirely (reference: out_of_order_actor_submit_queue.cc)."""

    def __init__(self, instance, max_concurrency: int, is_async: bool,
                 concurrency_groups: dict | None = None,
                 method_groups: dict | None = None,
                 out_of_order: bool = False):
        self.instance = instance
        self.max_concurrency = max_concurrency
        self.is_async = is_async
        self.out_of_order = out_of_order
        self.concurrency_groups = dict(concurrency_groups or {})
        self.method_groups = dict(method_groups or {})
        self.expected_seq: dict[bytes, int] = {}
        self.buffered: dict[tuple[bytes, int], dict] = {}
        self.executor = ThreadPoolExecutor(max_workers=max_concurrency)
        self.group_executors: dict[str, ThreadPoolExecutor] = {}
        if not is_async:
            for gname, limit in self.concurrency_groups.items():
                self.group_executors[gname] = ThreadPoolExecutor(
                    max_workers=max(1, int(limit)),
                    thread_name_prefix=f"actor-cg-{gname}",
                )
        self.async_loop: asyncio.AbstractEventLoop | None = None
        self.semaphore: asyncio.Semaphore | None = None
        self.group_semaphores: dict[str, asyncio.Semaphore] = {}
        if is_async:
            self.async_loop = asyncio.new_event_loop()
            t = threading.Thread(target=self._run_loop, daemon=True, name="actor-asyncio")
            t.start()

    def group_of(self, spec) -> str | None:
        """Resolve a call's concurrency group: per-call override first, then
        the class-declared method binding. None = default pool."""
        return spec.get("concurrency_group") or self.method_groups.get(
            spec["method_name"]
        )

    def _run_loop(self):
        asyncio.set_event_loop(self.async_loop)
        self.semaphore = asyncio.Semaphore(self.max_concurrency)
        for gname, limit in self.concurrency_groups.items():
            self.group_semaphores[gname] = asyncio.Semaphore(max(1, int(limit)))
        self.async_loop.run_forever()


class CoreWorker:
    def __init__(
        self,
        mode: str,  # "driver" | "worker"
        raylet_addr: tuple[str, int],
        gcs_addr: tuple[str, int],
        worker_id: WorkerID | None = None,
        job_id=None,
        remote_data_plane: bool = False,
        proxy: tuple | None = None,
    ):
        self.mode = mode
        # Thin-client mode (reference: Ray Client, util/client/): this process
        # runs no local raylet, so plasma traffic rides RPC (put_bytes /
        # read_chunk) to a remote raylet instead of shared memory.
        self.remote_data_plane = remote_data_plane
        # (host, port, client_id) of a client proxy (util/client/proxier.py):
        # every control-plane dial tunnels through it (reference: proxier's
        # per-client routing of the Ray Client data channel).
        self.proxy = proxy
        self.session_token = os.urandom(8).hex()  # distinguishes init/shutdown cycles
        self.worker_id = worker_id or WorkerID.from_random()
        self.node_id: NodeID | None = None
        self.node_ip: str = "127.0.0.1"
        self._direct_bind_host: str = "127.0.0.1"
        self._store_arena: str | None = None
        self._store_ops: list[tuple] = []
        self._store_ops_lock = threading.Lock()
        self._store_ops_flushing = False
        self._result_queues: dict[int, tuple] = {}  # id(conn) -> (conn, [payloads])
        self._result_sending: set[int] = set()
        self._result_lock = threading.Lock()
        # Sequenced borrow handoffs embedded in task replies (see
        # ReferenceCounter docstring): task_id -> {refs, returns, src}.
        self._reply_embedded: dict = {}
        self._embedded_materialized: set[ObjectID] = set()
        self._embedded_lock = threading.Lock()
        # put object id -> refs embedded in its payload, pinned until the put
        # object is freed (contained-in protection; see put()).
        self._put_embedded_pins: dict[ObjectID, list[ObjectID]] = {}
        self._log_dedup = _LogDeduplicator()
        # Owned ids with an attached resource (e.g. a device-object HBM pin):
        # the hook runs when the id's last reference dies cluster-wide.
        self._owned_free_hooks: dict[ObjectID, Any] = {}
        self.job_id = job_id
        self.io = rpc.IoLoop(name=f"rtpu-io-{mode}")
        self.raylet: rpc.Connection | None = None
        self.gcs: rpc.Connection | None = None
        self.raylet_addr = raylet_addr
        # All GCS candidate addresses (one entry in the classic single-GCS
        # shape); gcs_addr tracks the CURRENT primary this worker talks to.
        from ray_tpu._private.gcs_replication import parse_addrs

        self.gcs_addrs: list[tuple[str, int]] = parse_addrs(gcs_addr)
        self.gcs_addr = self.gcs_addrs[0]
        self.memory_store = MemoryStore()
        self.reference_counter = ReferenceCounter(self)
        self.functions = FunctionManager(self)
        self.reader = LocalObjectReader()
        self._default_task_id = TaskID.from_random()  # driver "task" identity
        self._pending_promoted: dict[TaskID, list[ObjectID]] = {}
        self._put_counter = _Counter()
        self._task_counter = _Counter()
        # Lineage for reconstruction: owned return-object id -> shared entry
        # {"spec", "live": set of ids, "promoted": pinned arg ids} (task_manager.h:177).
        self._lineage: dict[ObjectID, dict] = {}
        self._lineage_lock = threading.Lock()
        self._reconstructing: set[ObjectID] = set()
        self._recon_attempts: dict[ObjectID, int] = {}
        self._actor_seq: dict[ActorID, _Counter] = {}
        self._actor_arg_pins: dict[ActorID, list[ObjectID]] = {}
        # Direct actor-call path (reference: ActorTaskSubmitter pushes method
        # calls straight to the actor process, no raylet per call,
        # task_submission/actor_task_submitter.h:67). Per-actor: cached direct
        # connection, in-flight specs (failed on conn loss), and a seq-ordered
        # send queue (deps may resolve out of order; sends must not).
        self._direct_server: rpc.RpcServer | None = None
        self._direct_actor: dict[ActorID, Any] = {}  # conn | None(=use raylet)
        self._direct_inflight: dict[ActorID, dict] = {}  # aid -> {task_id: spec}
        self._direct_send: dict[ActorID, dict] = {}  # aid -> {"next": int, "ready": {}}
        self._direct_lock = threading.Lock()
        # Cached worker leases for normal tasks (reference: lease caching +
        # PushNormalTask, normal_task_submitter.h:81,220): per resource shape,
        # leased workers that execute pushed tasks back-to-back with no raylet
        # hop per task.
        self._leases: dict[tuple, dict] = {}  # shape -> {"workers", "queue", ...}
        self._lease_inflight: dict[TaskID, tuple] = {}  # task_id -> (shape, wid)
        self._lease_oom: dict[WorkerID, str] = {}  # OOM causes from the raylet
        self._lease_lock = threading.Lock()
        self._streams: dict[TaskID, _StreamState] = {}  # owner side of streaming tasks
        self._task_executor = ThreadPoolExecutor(max_workers=4, thread_name_prefix="rtpu-exec")
        # Owner-pushed lease tasks run on ONE thread: the owner pipelines up to
        # lease_worker_slots specs ahead so the wire never idles, but execution
        # stays sequential per worker — a lease holds one resource slot
        # (reference: a core worker executes one task at a time).
        self._lease_executor = ThreadPoolExecutor(max_workers=1, thread_name_prefix="rtpu-lease")
        self._future_pool = ThreadPoolExecutor(max_workers=8, thread_name_prefix="rtpu-fut")
        self.actor_runtime: _ActorRuntime | None = None
        self.actor_id: ActorID | None = None
        self._connected = False
        self._gcs_reconnect_counter = None  # lazy util.metrics Counter
        self._task_events: list[dict] = []
        self._events_lock = threading.Lock()
        self._tls = threading.local()

    @property
    def current_task_id(self) -> TaskID:
        """The task identity of the calling thread (thread-local inside executors:
        concurrent tasks must stamp their own ObjectIDs for lineage to hold)."""
        return getattr(self._tls, "task_id", None) or self._default_task_id

    # ------------------------------------------------------------------ connect

    def connect(self):
        self.raylet = self.io.run(
            rpc.connect(*self.raylet_addr, handler=self, name=f"{self.mode}->raylet",
                        via=self.proxy)
        )
        self.gcs = self._connect_gcs_primary(deadline_s=60.0)
        direct_port = None
        if not self.remote_data_plane:
            # Direct-call server: peers (owners of actor calls / leased tasks,
            # cross-node channel readers) reach this process without a raylet
            # hop on the hot path. Drivers host one too: they are the writer
            # side of a compiled DAG's input channel. Bound on all interfaces
            # when this node advertises a routable IP, so remote-node peers can
            # actually dial the direct_addr the raylet publishes for us.
            bind = bind_host_for(get_node_ip(self.gcs_addr[0]))
            self._direct_server = self.io.run(
                rpc.RpcServer(lambda conn: self).start(host=bind)
            )
            direct_port = self._direct_server.port
            self._direct_bind_host = bind
        reply = self.io.run(
            self.raylet.call(
                "register_worker", self.worker_id, self.mode, os.getpid(), direct_port,
                self._direct_bind_host,
            )
        )
        self.node_id = reply["node_id"]
        # Native-store direct data plane: with the arena name in hand, put/get
        # run entirely in shared memory (alloc/write/seal and lookup/read under
        # the arena's process-shared mutex) — no raylet RPC on the hot path.
        # Thin clients live on another host: the arena is unreachable for them.
        if not self.remote_data_plane:
            self._store_arena = reply.get("store_arena")
        node_ip = reply.get("node_ip", "127.0.0.1")
        # The IP peers may dial this worker's direct server on. Loopback when we
        # bound loopback-only, whatever the node advertises (compiled DAG driver
        # channels publish this).
        self.node_ip = (
            node_ip if self._direct_bind_host in ("0.0.0.0", node_ip) else "127.0.0.1"
        )
        if self.mode == "worker":
            self.raylet.on_close(lambda c: os._exit(0))
        elif os.environ.get("RAY_TPU_LOG_TO_DRIVER", "1") not in ("0", "false"):
            # Drivers see worker stdout/stderr live (reference: log_monitor.py
            # tails per-worker files and streams them to the driver).
            self.io.run(self.gcs.call("subscribe", "worker_logs"))
        if self.job_id is None:
            self.job_id = self.io.run(self.gcs.call("next_job_id"))
        self._connected = True
        self.io.spawn(self._event_flush_loop())
        self.io.spawn(self._borrow_audit_loop())
        return self

    def disconnect(self):
        self._connected = False
        try:
            self._drain_store_ops_sync()
        except Exception:
            pass
        try:
            for conn in list(self._direct_actor.values()):
                if conn is not None and not conn.closed:
                    self.io.run(conn.close())
            with self._lease_lock:
                lease_conns = [
                    w["conn"] for st in self._leases.values()
                    for w in st["workers"].values()
                ]
                self._leases.clear()
            for conn in lease_conns:
                if not conn.closed:
                    self.io.run(conn.close())
            if self.raylet is not None:
                self.io.run(self.raylet.close())
            if self.gcs is not None:
                self.io.run(self.gcs.close())
        except Exception:
            pass
        self.io.stop()
        self.reader.close()

    # ------------------------------------------------------------------ kv helpers

    def gcs_kv_put(self, ns: str, key: bytes, value: bytes, overwrite=True):
        return self.gcs_call("kv_put", ns, key, value, overwrite)

    def gcs_kv_get(self, ns: str, key: bytes):
        return self.gcs_call("kv_get", ns, key)

    def _connect_gcs_primary(self, deadline_s: float,
                             hint: tuple | None = None) -> rpc.Connection:
        """Dial GCS candidates until the current PRIMARY answers.

        A non-primary candidate (warm standby under quorum HA,
        docs/fault_tolerance.md) reports its role via `repl_status` and hints
        the primary's address; the probe follows hints first and otherwise
        walks the candidate list with exponential backoff + full jitter (a
        restarted/promoted GCS sees a spread-out thundering herd, not a
        synchronized stampede). Raises ConnectionLost past the deadline."""
        import random as _random

        deadline = time.monotonic() + deadline_s
        backoff = 0.05
        i = 0
        while True:
            addr = tuple(hint) if hint else self.gcs_addrs[i % len(self.gcs_addrs)]
            hint = None
            i += 1
            conn = None
            try:
                conn = self.io.run(
                    rpc.connect(*addr, handler=self,
                                name=f"{self.mode}->gcs", via=self.proxy)
                )
                st = self.io.run(conn.call("repl_status", timeout=5.0))
                if st.get("role") == "primary":
                    self.gcs_addr = addr
                    return conn
                hint = st.get("primary")
                self.io.run(conn.close())
            except (OSError, rpc.RpcError):
                if conn is not None:
                    try:
                        self.io.run(conn.close())
                    except Exception:
                        pass
            if time.monotonic() > deadline:
                raise rpc.ConnectionLost(
                    f"no GCS primary reachable at {self.gcs_addrs}"
                )
            if not hint:
                # Full jitter on the exponential step; never sleep past the
                # deadline (the final attempt should still get its shot).
                pause = backoff * (0.5 + _random.random())
                pause = min(pause, max(0.0, deadline - time.monotonic()))
                time.sleep(pause)
                backoff = min(backoff * 2.0, 2.0)

    def gcs_call(self, method: str, *args, timeout: float | None = None,
                 deadline_s: float | None = None):
        """GCS request with transparent reconnect + failover: the control
        plane may restart — or fail over to another head candidate — under us
        (reference: GCS clients buffer and retry during GCS downtime).

        ConnectionLost covers both a dead socket and a NOT_PRIMARY redirect
        (`rpc.NotPrimaryError` subclasses it, carrying the new primary's
        address); either way the call re-resolves the primary through
        `_connect_gcs_primary` and retries, up to a total deadline
        (`deadline_s`, default CONFIG.gcs_rpc_timeout_s), after which
        ConnectionLost surfaces to the caller."""
        from ray_tpu.devtools import distsan

        distsan.note_gcs_call(method)  # records if a hot/finalizer tag is active
        deadline = time.monotonic() + (
            deadline_s if deadline_s is not None else CONFIG.gcs_rpc_timeout_s
        )
        reconnects = 0
        while True:
            try:
                result = self.io.run(self.gcs.call(method, *args), timeout)
                if reconnects:
                    self._note_gcs_reconnects(reconnects)
                return result
            except rpc.ConnectionLost as e:
                if not self._connected or time.monotonic() > deadline:
                    raise
                hint = getattr(e, "primary", None)
                old = self.gcs
                if old is not None and not old.closed:
                    # A NOT_PRIMARY answer leaves the socket open; drop it so
                    # in-flight direct users fail fast onto the new conn.
                    try:
                        self.io.run(old.close())
                    except Exception:
                        pass
                self.gcs = self._connect_gcs_primary(
                    deadline_s=max(0.05, deadline - time.monotonic()),
                    hint=hint,
                )
                reconnects += 1

    def _note_gcs_reconnects(self, n: int):
        """Count successful GCS reconnections (`gcs_reconnect_total`). Called
        only after the re-issued request succeeded, so the nested KV flush
        inside the counter rides a healthy connection, never a retry loop."""
        try:
            if self._gcs_reconnect_counter is None:
                from ray_tpu.util.metrics import Counter

                self._gcs_reconnect_counter = Counter(
                    "gcs_reconnect_total",
                    "GCS client reconnections that recovered an in-flight call",
                )
            self._gcs_reconnect_counter.inc(n)  # raylint: disable=RL901 (rare reconnect event, not a data path; the nested flush rides the just-recovered connection — see docstring)
        except Exception:
            pass  # observability must never break the recovered call

    def raylet_call(self, method: str, *args, timeout: float | None = None):
        return self.io.run(self.raylet.call(method, *args), timeout)

    # ------------------------------------------------------------------ events

    def _record_event(self, **fields):
        fields["time"] = time.time()
        fields["worker_id"] = self.worker_id.hex()  # per-worker timeline lanes
        with self._events_lock:
            self._task_events.append(fields)
            if len(self._task_events) > CONFIG.event_buffer_size:
                del self._task_events[: len(self._task_events) // 2]

    async def _event_flush_loop(self):
        while self._connected:
            await asyncio.sleep(CONFIG.metrics_report_interval_s)
            # Backstop drain: refs dropped by GC with no later API activity.
            self.reference_counter.drain_deferred()
            # Dedup summaries for lines whose repeat window closed quietly.
            try:
                pending = self._log_dedup.flush_expired()
                if pending:
                    sys.stderr.write(pending)
                    sys.stderr.flush()
            except Exception:
                pass  # stderr may be closed at interpreter teardown; drop the summary
            with self._events_lock:
                batch, self._task_events = self._task_events, []
            if batch:
                try:
                    await self.gcs.call("report_task_events", batch)
                except rpc.RpcError:
                    pass

    # ------------------------------------------------------------------ put / get / wait

    def _owner_address(self) -> dict:
        return {"node_id": self.node_id, "worker_id": self.worker_id}

    def put_inline_owned(self, data: bytes, free_hook=None) -> ObjectRef:
        """Register a small owned object resolving to pre-serialized bytes,
        with an optional hook that runs when its last reference dies
        cluster-wide (device objects pin HBM behind these)."""
        self.reference_counter.drain_deferred()
        object_id = ObjectID.from_task(
            self.current_task_id, 0x50000000 + self._put_counter.next()
        )
        self.reference_counter.add_owned(object_id)
        self.memory_store.create_pending(object_id)
        self.memory_store.resolve(object_id, data, False, False)
        if free_hook is not None:
            self._owned_free_hooks[object_id] = free_hook
        return ObjectRef(object_id, self._owner_address())

    def put(self, value: Any) -> ObjectRef:
        self.reference_counter.drain_deferred()
        object_id = ObjectID.from_task(self.current_task_id, 0x40000000 + self._put_counter.next())
        # Capture refs embedded in the payload and pin them for the put
        # object's lifetime: the putter holds live refs at serialization time,
        # so the pin is sequenced (no fire-and-forget racing the owner's
        # free). Released in _free_owned_object when the put object dies —
        # the "contained_in" protection of the reference's reference_counter.
        prev_cap = getattr(self._tls, "ref_capture", None)
        self._tls.ref_capture = cap = []
        try:
            self._put_to_plasma(object_id, value, self._owner_address())
        finally:
            self._tls.ref_capture = prev_cap
        if cap:
            pins = []
            for eid, eowner in cap:
                self.reference_counter.add_local_ref(eid, eowner)
                pins.append(eid)
            self._put_embedded_pins[object_id] = pins
        self.reference_counter.add_owned(object_id)
        rec = self.memory_store.create_pending(object_id)
        rec.in_plasma = True
        rec.resolved = True
        rec.event.set()
        return ObjectRef(object_id, self._owner_address())

    def _put_to_plasma(self, object_id: ObjectID, value: Any, owner: dict):
        pickled, raw_buffers, total = serialization.serialized_size(value)
        self._write_plasma(object_id, pickled, raw_buffers, total, owner)

    def _write_plasma(self, object_id: ObjectID, pickled, raw_buffers, total: int,
                      owner: dict):
        """The single plasma write path: shared memory locally, RPC bytes for
        thin clients."""
        if self.remote_data_plane:
            self.raylet_call(
                "store_put_bytes", object_id,
                bytes(serialization.assemble(pickled, raw_buffers)), owner,
            )
            return
        if self._store_arena is not None and self._put_direct(
            object_id, pickled, raw_buffers, total, owner
        ):
            return
        shm_name = self.raylet_call("store_create", object_id, total)
        buf = self.reader.write_view(shm_name, total)
        serialization.write_parts(buf, pickled, raw_buffers)
        self.raylet_call("store_seal", object_id, total, owner)

    def _put_direct(self, object_id: ObjectID, pickled, raw_buffers, total: int,
                    owner: dict) -> bool:
        """Allocate, write, and seal straight in the shared arena; the raylet
        only learns about the sealed object via an async notify (location
        tracking + GCS directory). Falls back to the RPC path (returns False)
        when the arena is full — the raylet's create() spills LRU objects to
        disk, which only it can orchestrate.

        Reference: plasma clients memcpy into store-allocated buffers
        (`object_buffer_pool.h:32`); here even create/seal skip the socket."""
        from ray_tpu._private.object_store import _native_key

        key = _native_key(object_id)
        try:
            arena = self.reader._arena(self._store_arena)
        except Exception:
            self._store_arena = None  # arena gone (store restarted): RPC path
            return False
        try:
            off = arena.alloc(key, total)
        except FileExistsError:
            # Same id re-put (retry/reconstruction): if sealed it's already
            # readable — re-notify bookkeeping; otherwise another writer is
            # mid-put and the RPC path serializes against it.
            if arena.lookup(key) is None:
                return False
            self._notify_sealed(object_id, total, owner)
            return True
        except KeyError:
            return False
        if off is None:
            return False
        buf = arena.read(off, total)
        serialization.write_parts(buf, pickled, raw_buffers)
        arena.seal(key)
        self._notify_sealed(object_id, total, owner)
        return True

    def _notify_sealed(self, object_id: ObjectID, total: int, owner: dict):
        # Fire-and-forget: the arena itself is the source of truth for local
        # resolution; the notify only feeds the raylet's location bookkeeping
        # and the GCS object directory (cross-node discovery).
        self._queue_store_op(("sealed", object_id, total, owner))

    def _queue_store_op(self, op: tuple):
        """Batch store bookkeeping notifies (sealed/free): one IO-thread wakeup
        and one frame per window instead of per object. Order is preserved —
        seal-then-free of the same id must apply in order at the raylet."""
        with self._store_ops_lock:
            self._store_ops.append(op)
            if self._store_ops_flushing:
                return
            self._store_ops_flushing = True
        self.io.spawn(self._flush_store_ops())

    async def _flush_store_ops(self):
        await asyncio.sleep(CONFIG.object_report_flush_s / 2)
        with self._store_ops_lock:
            ops, self._store_ops = self._store_ops, []
            self._store_ops_flushing = False
        if ops and self.raylet is not None and not self.raylet.closed:
            try:
                await self.raylet.notify("store_ops_batch", ops)
            except Exception:
                pass  # raylet restart: unacked ops re-enter _store_ops via retry paths

    def _drain_store_ops_sync(self):
        """Flush pending store ops before disconnect so frees/seals aren't lost."""
        with self._store_ops_lock:
            ops, self._store_ops = self._store_ops, []
        if ops and self.raylet is not None and not self.raylet.closed:
            try:
                self.io.run(self.raylet.notify("store_ops_batch", ops))
            except Exception:
                pass

    def _get_direct(self, object_id: ObjectID):
        """Zero-RPC read of a locally-sealed object, or _MISS. The pinned view
        keeps the payload alive while any deserialized alias exists."""
        from ray_tpu._private.object_store import _native_key

        key = _native_key(object_id)
        try:
            arena = self.reader._arena(self._store_arena)
        except Exception:
            self._store_arena = None  # arena unopenable: stop trying per-get
            return _MISS
        try:
            found = arena.lookup(key)
            if found is None:
                return _MISS
            off, size = found
            buf = arena.read_pinned(key, off, size)
        except Exception:
            return _MISS  # evicted/spilled mid-read: resolve path re-locates
        return serialization.loads(buf)

    def _read_remote_object(self, object_id: ObjectID, size: int) -> bytes:
        """Thin-client read: stream the object over RPC in store-chunk units."""
        chunks = []
        offset = 0
        step = CONFIG.object_store_min_chunk_bytes
        while offset < size:
            data = self.raylet_call(
                "read_chunk", object_id, offset, min(step, size - offset)
            )
            if not data:
                raise ObjectLostError(object_id, "remote read returned no data")
            chunks.append(data)
            offset += len(data)
        return b"".join(chunks)

    def get(self, refs: list[ObjectRef], timeout: float | None = None) -> list[Any]:
        self.reference_counter.drain_deferred()
        deadline = None if timeout is None else time.monotonic() + timeout
        out = []
        for ref in refs:
            out.append(self._get_one(ref, deadline))
        return out

    @staticmethod
    def _decode_inline(rec: _Record):
        """Deserialize a resolved inline record, raising task errors in caller context."""
        value = serialization.loads(rec.data)
        if rec.error:
            raise value.as_instanceof_cause() if isinstance(value, RayTpuTaskError) else value
        return value

    def _get_one(self, ref: ObjectRef, deadline: float | None):
        rec = self.memory_store.get(ref.id)
        if rec is not None and not rec.resolved:
            remaining = None if deadline is None else max(0.0, deadline - time.monotonic())
            if not rec.event.wait(remaining):
                raise GetTimeoutError(f"get() timed out waiting for {ref}")
        rec = self.memory_store.get(ref.id)
        if rec is not None and rec.resolved and not rec.in_plasma:
            return self._decode_inline(rec)
        # Local-arena fast path: a direct (pinning) lookup in shared memory
        # skips the resolve RPC entirely when the object lives on this node.
        if self._store_arena is not None:
            value = self._get_direct(ref.id)
            if value is not _MISS:
                if isinstance(value, RayTpuTaskError):
                    raise value.as_instanceof_cause()
                if isinstance(value, RayTpuError):
                    raise value
                return value
        # Plasma or borrowed: resolve via the raylet. "lost" (known object, zero live
        # copies) triggers lineage reconstruction: the owner re-runs the producing
        # task and the loop waits for the fresh copy to be sealed.
        hard_deadline = time.monotonic() + 300.0 if deadline is None else deadline
        recon_next = 0.0  # owner requests dedupe internally; borrowers back off
        while True:
            remaining = max(0.0, hard_deadline - time.monotonic())
            reply = self.raylet_call("resolve_object", ref.id, ref.owner, remaining, 0)
            if reply.get("error") == "lost":
                # A rebuild may already have routed an (inline) error result back.
                rec = self.memory_store.get(ref.id)
                if rec is not None and rec.resolved and not rec.in_plasma:
                    return self._decode_inline(rec)
                now = time.monotonic()
                if now >= hard_deadline:
                    raise GetTimeoutError(f"get() timed out waiting for {ref}")
                if now >= recon_next:
                    if not self._try_reconstruct(ref):
                        raise ObjectLostError(
                            ref.id,
                            f"{ref} was lost (all copies died) and could not be "
                            "reconstructed from lineage",
                        )
                    recon_next = now + 2.0
                time.sleep(0.1)
                continue
            break
        if reply.get("error"):
            if reply["error"] == "timeout":
                raise GetTimeoutError(f"get() timed out waiting for {ref}")
            raise ObjectLostError(ref.id, f"failed to resolve {ref}: {reply['error']}")
        if "inline" in reply:
            data = reply["inline"]
            value = serialization.loads(data)
        elif self.remote_data_plane:
            _shm_name, size = reply["shm"]
            try:
                raw = self._read_remote_object(ref.id, size)
            except rpc.RpcError:
                # Stale location (freed/evicted between resolve and read): one
                # re-resolve, mirroring the shared-memory branch below.
                reply = self.raylet_call("resolve_object", ref.id, ref.owner, remaining, 0)
                if reply.get("error") or "shm" not in reply:
                    raise ObjectLostError(ref.id, f"failed to re-resolve {ref}")
                _shm_name, size = reply["shm"]
                try:
                    raw = self._read_remote_object(ref.id, size)
                except rpc.RpcError as e:
                    raise ObjectLostError(
                        ref.id, f"object location stale twice for {ref}: {e}"
                    )
            value = serialization.loads(raw)
        else:
            shm_name, size = reply["shm"]
            try:
                buf = self.reader.read(shm_name, size)
            except (KeyError, FileNotFoundError, OSError):
                # Location went stale between resolve and read (the store spilled,
                # evicted, or freed+unlinked the object); one re-resolve gets the
                # new location. A second stale read means the object is gone.
                reply = self.raylet_call("resolve_object", ref.id, ref.owner, remaining, 0)
                if reply.get("error") or "shm" not in reply:
                    raise ObjectLostError(ref.id, f"failed to re-resolve {ref}")
                shm_name, size = reply["shm"]
                try:
                    buf = self.reader.read(shm_name, size)
                except (KeyError, FileNotFoundError, OSError) as e:
                    raise ObjectLostError(
                        ref.id, f"object location stale twice for {ref}: {e}"
                    )
            value = serialization.loads(buf)
        if isinstance(value, RayTpuTaskError):
            raise value.as_instanceof_cause()
        if isinstance(value, RayTpuError):
            raise value
        return value

    def wait(self, refs: list[ObjectRef], num_returns=1, timeout=None, fetch_local=True):
        self.reference_counter.drain_deferred()
        deadline = None if timeout is None else time.monotonic() + timeout
        pending = list(refs)
        ready: list[ObjectRef] = []
        while True:
            still = []
            for ref in pending:
                if self._is_ready(ref):
                    ready.append(ref)
                else:
                    still.append(ref)
            pending = still
            if len(ready) >= num_returns or not pending:
                break
            if deadline is not None and time.monotonic() >= deadline:
                break
            time.sleep(CONFIG.get_poll_interval_s)
        return ready, pending

    def _is_ready(self, ref: ObjectRef) -> bool:
        rec = self.memory_store.get(ref.id)
        if rec is not None and rec.resolved:
            return True  # inline value present, or plasma object sealed (owner saw completion)
        owner = ref.owner
        if rec is not None and (
            owner is None or owner.get("worker_id") == self.worker_id
        ):
            # Self-owned pending object: completion lands in the memstore via
            # the task-reply/push path, so polling raylet/GCS per wait() cycle
            # adds pure RPC load (it cannot learn anything the memstore won't).
            return False
        # Borrowed ref: check the local/global store.
        try:
            info = self.raylet_call("store_info", ref.id)
        except rpc.RpcError:
            return False
        if info is not None:
            return True
        try:
            loc = self.gcs_call("object_locations", ref.id)
        except rpc.RpcError:
            return False
        return bool(loc and loc["locations"])

    def as_future(self, ref: ObjectRef) -> Future:
        return self._future_pool.submit(lambda: self.get([ref])[0])

    def _free_owned_object(self, object_id: ObjectID):
        rec = self.memory_store.get(object_id)
        self.memory_store.pop(object_id)
        self._drop_lineage(object_id)
        self._settle_embedded_on_free(object_id)
        for eid in self._put_embedded_pins.pop(object_id, ()):
            self.reference_counter.remove_local_ref(eid)
        hook = self._owned_free_hooks.pop(object_id, None)
        if hook is not None:
            try:
                hook()
            except Exception:
                pass
        if rec is not None and rec.in_plasma and self._connected:
            # Direct-arena eviction first: the block returns to the freelist
            # synchronously, so the next put reuses its (warm) pages instead of
            # faulting fresh ones. Pinned readers defer recycle to release.
            # The raylet notify keeps location bookkeeping + GCS in sync
            # (its own store.free of the already-evicted key is a no-op).
            if self._store_arena is not None:
                from ray_tpu._private.object_store import _native_key

                try:
                    self.reader._arena(self._store_arena).free(
                        _native_key(object_id), eager=True
                    )
                except Exception:
                    pass  # arena gone/object already evicted: the raylet free below is authoritative
            try:
                self._queue_store_op(("free", object_id))
            except Exception:
                pass

    def _report_borrow(self, object_id: ObjectID, owner: dict, delta: int,
                       borrower_key=None):
        """Route a borrow count change to `owner`. `borrower_key` defaults to
        this process; transitive mirrors pass the SUB-borrower's key so the
        true owner's table lists the actual holder."""
        if not self._connected or self.raylet is None:
            return
        key = borrower_key if borrower_key is not None else _addr_key(
            self._owner_address()
        )

        async def _send():
            delay = CONFIG.test_delay_borrow_report_ms
            if delay:  # fault injection: stress the reorder the sequenced
                await asyncio.sleep(delay / 1000)  # protocol must be immune to
            await self.raylet.notify(
                "report_borrow", object_id, owner, delta, key,
            )

        try:
            self.io.spawn(_send())
        except Exception:
            pass

    # ---------------------------------------------------- sequenced borrowing

    def _task_borrow_sink(self) -> dict | None:
        """The per-task borrow sink of the calling thread, if it is executing
        a task (executors defer borrow registration to the reply)."""
        return getattr(self._tls, "borrow_sink", None)

    def _note_serialized_ref(self, object_id: ObjectID, owner: dict | None):
        """ObjectRef.__reduce__ hook: capture refs pickled into task results."""
        cap = getattr(self._tls, "ref_capture", None)
        if cap is not None and owner is not None:
            cap.append((object_id, owner))

    def _note_embedded_materialized(self, object_id: ObjectID):
        """A pre-seeded result ref took its first local ref: its release now
        rides the normal borrow lifecycle, not the unmaterialized settle."""
        with self._embedded_lock:
            self._embedded_materialized.add(object_id)

    def _register_reply_embeds(self, payload: dict):
        """Caller side, BEFORE arg-pin release: absorb the reply's sequenced
        borrow handoffs."""
        src = payload.get("src")
        if src is None:
            return
        src_key = _addr_key(src)
        for oid in payload.get("borrows", ()):
            # The executor kept a borrowed arg ref beyond the task: count it
            # before releasing our pins (we are its borrow parent now).
            self.reference_counter.update_borrow(oid, +1, src_key)
        embeds = payload.get("result_refs") or ()
        pending = []
        for oid, _owner in embeds:
            if _owner is not None:
                self.reference_counter.record_true_owner(oid, _owner)
            if self.reference_counter.pre_register_borrow(oid, src):
                pending.append(oid)
            else:
                # We already own or borrow this id: the executor's pre-count
                # for us is unneeded — release it immediately (our existing
                # ref keeps the object alive through our own lifecycle).
                self._report_borrow(oid, src, -1)
        if pending:
            # Only returns still alive can carry the embedded refs to user
            # code; if every return was already dropped (fire-and-forget
            # submission), settle straight away.
            returns = {
                r["object_id"] for r in payload.get("results", ())
                if self.memory_store.get(r["object_id"]) is not None
            }
            if returns:
                with self._embedded_lock:
                    self._reply_embedded[payload["task_id"]] = {
                        "refs": pending, "returns": returns, "src": src,
                    }
            else:
                for oid in pending:
                    parent = self.reference_counter.settle_unmaterialized(oid)
                    if parent is not None:
                        self._report_borrow(oid, parent, -1)

    def _settle_embedded_on_free(self, freed_oid: ObjectID):
        """A result record was freed: embedded refs never materialized release
        back to the executor that pre-counted us."""
        if not self._reply_embedded:
            return
        candidates = []
        with self._embedded_lock:
            for task_id, entry in list(self._reply_embedded.items()):
                entry["returns"].discard(freed_oid)
                if entry["returns"]:
                    continue
                del self._reply_embedded[task_id]
                for oid in entry["refs"]:
                    if oid in self._embedded_materialized:
                        self._embedded_materialized.discard(oid)
                        continue
                    candidates.append(oid)
        # settle outside _embedded_lock: it takes the rc lock, and add_local_ref
        # orders rc._lock -> (after release) _embedded_lock.
        for oid in candidates:
            parent = self.reference_counter.settle_unmaterialized(oid)
            if parent is not None:
                self._report_borrow(oid, parent, -1)

    async def _borrow_audit_loop(self):
        """Reconcile borrowers that died without releasing: ping each borrower
        address; persistent unreachability drops its counts (reference:
        reference_counter subscribes to borrower death via the raylet)."""
        failures: dict[str, int] = {}
        stale: dict[tuple, int] = {}  # (borrower_key, oid) -> not-held strikes
        while self._connected:
            await asyncio.sleep(CONFIG.borrow_audit_interval_s)
            snapshot = self.reference_counter.borrower_snapshot()
            # Prune strikes whose borrower left entirely AND strikes whose oid
            # is no longer borrowed by that borrower (normal release between
            # audits) — otherwise (borrower, oid) keys accrete forever.
            stale = {k: v for k, v in stale.items()
                     if k[0] in snapshot and k[1] in snapshot[k[0]]}
            for key in snapshot:
                node_hex, worker_hex = key
                if node_hex == "?":
                    continue  # legacy unkeyed entry: no address to audit
                try:
                    alive = await self.raylet.call(
                        "check_worker_alive", node_hex, worker_hex, timeout=10.0
                    )
                except Exception:
                    continue  # raylet unreachable: no verdict this round
                if alive is None:
                    continue  # unreachable != dead: never free on a maybe
                if alive:
                    failures.pop(key, None)
                    # Liveness is not enough: a borrower that released into a
                    # crashed parent's void still has a count here (the -1
                    # never arrived). Ask what it actually still holds; three
                    # consecutive not-held verdicts (plus a wall-clock floor,
                    # below) reconcile the entry — fewer would race an
                    # in-flight handoff the holder hasn't learned about yet.
                    try:
                        resp = await self.raylet.call(
                            "check_borrows", node_hex, worker_hex,
                            snapshot[key], timeout=15.0,
                        )
                    except Exception:
                        resp = None
                    if not isinstance(resp, dict) or "held" not in resp:
                        continue
                    held = set(resp["held"])
                    now = time.monotonic()
                    for oid in snapshot[key]:
                        sk = (key, oid)
                        if oid in held:
                            stale.pop(sk, None)
                            continue
                        strikes, first_t = stale.get(sk, (0, now))
                        strikes += 1
                        # N consecutive not-held rounds AND a minimum
                        # wall-clock age: a sequenced handoff still in flight
                        # (reply not yet processed by the holder) must never
                        # be reconciled away on a fast audit interval.
                        if (strikes >= CONFIG.borrow_audit_strikes
                                and now - first_t >= CONFIG.borrow_audit_min_age_s):
                            stale.pop(sk, None)
                            self.reference_counter.drop_borrow_entry(oid, key)
                        else:
                            stale[sk] = (strikes, first_t)
                    continue
                failures[key] = failures.get(key, 0) + 1
                if failures[key] >= 2:  # two strikes: not a transient blip
                    failures.pop(key, None)
                    self.reference_counter.drop_borrower(key)

    # ------------------------------------------------------------------ lineage

    def _record_lineage(self, spec, promoted: list[ObjectID]):
        """Retain the producing task spec (+ pins on its promoted plasma args) until
        every return object is out of scope, so a lost object can be rebuilt by
        re-running the task (reference: TaskManager lineage, task_manager.h:177)."""
        if CONFIG.max_object_reconstructions <= 0 or not spec["return_ids"]:
            return False
        entry = {"spec": spec, "live": set(spec["return_ids"]), "promoted": promoted}
        with self._lineage_lock:
            for oid in spec["return_ids"]:
                self._lineage[oid] = entry
            overflow = len(self._lineage) - CONFIG.max_lineage_entries
            evicted = []
            if overflow > 0:
                for oid in list(self._lineage):
                    if overflow <= 0:
                        break
                    ev = self._lineage.pop(oid)
                    ev["live"].discard(oid)
                    if not ev["live"]:
                        evicted.append(ev)
                    overflow -= 1
        for ev in evicted:
            for pid in ev.get("promoted", ()):
                self.reference_counter.remove_local_ref(pid)
        return True

    def _drop_lineage(self, object_id: ObjectID):
        release = None
        with self._lineage_lock:
            self._recon_attempts.pop(object_id, None)
            self._reconstructing.discard(object_id)
            entry = self._lineage.pop(object_id, None)
            if entry is None:
                return
            entry["live"].discard(object_id)
            if not entry["live"]:
                release = entry.get("promoted", ())
        if release:
            for pid in release:
                self.reference_counter.remove_local_ref(pid)

    def _try_reconstruct_owned(self, object_id: ObjectID) -> bool:
        """Re-submit the producing task of a lost owned object. Returns True if a
        rebuild was started or is already in flight (reference:
        object_recovery_manager.h:41)."""
        with self._lineage_lock:
            entry = self._lineage.get(object_id)
            if entry is None:
                return False
            if object_id in self._reconstructing:
                return True
            attempts = self._recon_attempts.get(object_id, 0)
            if attempts >= CONFIG.max_object_reconstructions:
                return False
            spec = dict(entry["spec"])
            for oid in entry["live"]:
                self._recon_attempts[oid] = attempts + 1
                self._reconstructing.add(oid)
        spec["retries_left"] = max(1, spec.get("retries_left", 1))
        spec.pop("__direct__", None)  # rebuild rides the raylet, not a stale lease
        self._record_event(
            task_id=spec["task_id"].hex(), name=spec["name"], state="RECONSTRUCTING"
        )

        def unwedge():
            # The resubmission never reached the raylet: clear the in-flight marker
            # so a later get() attempts reconstruction again instead of spinning.
            with self._lineage_lock:
                for oid in spec["return_ids"]:
                    self._reconstructing.discard(oid)

        self._submit_when_ready(spec, on_send_failure=unwedge)
        return True

    def _try_reconstruct(self, ref: ObjectRef) -> bool:
        """Owner: rebuild locally. Borrower: ask the owner to rebuild."""
        if ref.owner and ref.owner.get("worker_id") != self.worker_id:
            try:
                reply = self.raylet_call(
                    "call_worker", ref.owner, "reconstruct_object",
                    {"object_id": ref.id},
                )
            except rpc.RpcError:
                return False
            return bool(isinstance(reply, dict) and reply.get("ok"))
        return self._try_reconstruct_owned(ref.id)

    # ------------------------------------------------------------------ task submission

    def _serialize_args(self, args, kwargs):
        """Each arg: inline bytes, plasma-promoted ref, or passed-through ObjectRef.

        Returns (args, kwargs, promoted_ids); the caller must release the promoted ids'
        refcounts once the consuming task completes (or pin them for actor lifetime).
        """
        promoted: list[ObjectID] = []

        def one(value):
            if isinstance(value, ObjectRef):
                # Pin every ref arg for the task's lifetime so a caller dropping its
                # handle right after .remote() can't free the arg out from under the
                # queued task. For borrowed refs the pin keeps this process's borrow
                # registered with the owner until the task completes.
                self.reference_counter.add_local_ref(value.id, value.owner)
                promoted.append(value.id)
                return {"ref": (value.id, value.owner)}
            pickled, raw_buffers, total = serialization.serialized_size(value)
            if total > CONFIG.max_direct_call_object_size:
                object_id = ObjectID.from_task(
                    self.current_task_id, 0x20000000 + self._put_counter.next()
                )
                self._write_plasma(
                    object_id, pickled, raw_buffers, total, self._owner_address()
                )
                self.reference_counter.add_owned(object_id)
                self.reference_counter.add_local_ref(object_id)
                promoted.append(object_id)
                rec = self.memory_store.create_pending(object_id)
                rec.in_plasma = True
                rec.resolved = True
                rec.event.set()
                return {"ref": (object_id, self._owner_address()), "promoted": True}
            header_parts = serialization.assemble(pickled, raw_buffers)
            return {"v": header_parts}

        return [one(a) for a in args], {k: one(v) for k, v in kwargs.items()}, promoted

    def submit_task(
        self,
        fn_key: bytes,
        name: str,
        args,
        kwargs,
        num_returns: int = 1,
        resources: dict | None = None,
        placement_group: dict | None = None,
        max_retries: int | None = None,
        scheduling_strategy=None,
        runtime_env: dict | None = None,
    ) -> list[ObjectRef]:
        self.reference_counter.drain_deferred()
        task_id = TaskID.from_random()
        ser_args, ser_kwargs, promoted = self._serialize_args(args, kwargs)
        streaming = num_returns == "streaming"
        return_ids = (
            [] if streaming else [ObjectID.from_task(task_id, i) for i in range(num_returns)]
        )
        owner = self._owner_address()
        spec = {
            "type": "task",
            "task_id": task_id,
            "name": name,
            "fn_key": fn_key,
            "args": ser_args,
            "kwargs": ser_kwargs,
            "num_returns": num_returns,
            "return_ids": return_ids,
            "resources": resources if resources is not None else {"CPU": 1},
            "placement_group": placement_group,
            "owner": owner,
            "retries_left": (
                max_retries if max_retries is not None else CONFIG.max_task_retries_default
            ),
            "scheduling_strategy": scheduling_strategy,
            "runtime_env": runtime_env,
        }
        refs = []
        for oid in return_ids:
            self.reference_counter.add_owned(oid)
            self.memory_store.create_pending(oid)
            refs.append(ObjectRef(oid, owner))
        # Two independent pins on promoted args: the flight pin (released when the
        # task's result arrives, guaranteeing args outlive the queued/running task)
        # and, when lineage is retained, a lineage pin (released when the last
        # return object dies, so a rebuild can re-materialize args).
        # Streamed items are not lineage-reconstructable (the stream is consumed
        # incrementally), so streaming tasks keep only the flight pin.
        if not streaming and self._record_lineage(spec, promoted):
            for pid in promoted:
                self.reference_counter.add_local_ref(pid)
        if promoted:
            self._pending_promoted[task_id] = promoted
        from ray_tpu.util import tracing

        tctx = tracing.propagation_context()
        if tctx:
            spec["trace_ctx"] = tctx
        self._record_event(task_id=task_id.hex(), name=name, state="SUBMITTED",
                           **tracing.event_fields(tctx))
        if streaming:
            self._streams[task_id] = _StreamState()
        if self._lease_eligible(spec):
            self._when_args_ready(spec, lambda: self._lease_submit(spec))
        else:
            self._submit_when_ready(spec)
        if streaming:
            return ObjectRefGenerator(task_id, self)
        return refs

    def _when_args_ready(self, spec, fn):
        """Dependency gating: run fn once owned pending ref-args resolve
        (DependencyResolver parity). fn may run on the caller thread (no deps)
        or on whatever thread resolves the last dependency."""
        dep_ids = []
        for loc in list(spec["args"]) + list(spec["kwargs"].values()):
            if "ref" in loc:
                oid = loc["ref"][0]
                rec = self.memory_store.get(oid)
                if rec is not None and not rec.resolved:
                    dep_ids.append(oid)
        if not dep_ids:
            fn()
            return
        remaining = {"n": len(dep_ids)}
        lock = threading.Lock()

        def on_done(_oid, _rec):
            with lock:
                remaining["n"] -= 1
                done = remaining["n"] == 0
            if done:
                fn()

        for oid in dep_ids:
            if not self.memory_store.add_done_callback(oid, on_done):
                on_done(oid, None)

    def _submit_when_ready(self, spec, target="submit_task", on_send_failure=None):
        async def send():
            try:
                await self.raylet.notify(target, spec)
            except Exception:
                if on_send_failure is not None:
                    on_send_failure()

        self._when_args_ready(spec, lambda: self.io.spawn(send()))

    # ------------------------------------------------------------------ actors

    def create_actor(
        self,
        cls_key: bytes,
        class_name: str,
        args,
        kwargs,
        *,
        name=None,
        namespace="",
        get_if_exists=False,
        num_returns: int = 0,
        resources=None,
        placement_group=None,
        max_restarts=0,
        max_concurrency=1,
        is_async=False,
        scheduling_strategy=None,
        method_names=(),
        runtime_env=None,
        concurrency_groups=None,
        method_groups=None,
        method_opts=None,
        allow_out_of_order_execution=False,
    ) -> ActorID:
        actor_id = ActorID.from_random()
        # Promoted/borrowed init args stay pinned while the actor can restart
        # (restarts re-run __init__); released when the creator's handle dies.
        ser_args, ser_kwargs, promoted = self._serialize_args(args, kwargs)
        spec = {
            "type": "actor_creation",
            "actor_id": actor_id,
            "cls_key": cls_key,
            "class_name": class_name,
            "args": ser_args,
            "kwargs": ser_kwargs,
            "name": name,
            "namespace": namespace,
            "get_if_exists": get_if_exists,
            "resources": dict(resources or {}),
            "placement_group": placement_group,
            "max_restarts": max_restarts,
            "max_concurrency": max_concurrency,
            "is_async": is_async,
            "scheduling_strategy": scheduling_strategy,
            "owner": self._owner_address(),
            "method_names": list(method_names),
            "runtime_env": runtime_env,
            "concurrency_groups": dict(concurrency_groups or {}),
            "method_groups": dict(method_groups or {}),
            "method_opts": dict(method_opts or {}),
            "allow_out_of_order_execution": bool(allow_out_of_order_execution),
        }
        reply = self.gcs_call("register_actor", actor_id, spec)
        actual_id = reply["actor_id"]
        existing = bool(reply.get("existing"))
        if promoted:
            if existing:
                # get_if_exists hit an existing actor: our spec (and its arg pins)
                # will never be used for a restart.
                for pid in promoted:
                    self.reference_counter.remove_local_ref(pid)
            else:
                self._actor_arg_pins[actual_id] = promoted
        # The caller's handle owns the arg pins only when this call actually
        # created the actor; a get_if_exists hit must return a non-owning handle
        # (its __del__ must not release the first creator's pins).
        return actual_id, not existing

    def release_actor_arg_pins(self, actor_id: ActorID):
        """The creator's handle died: the actor can still run, but this process no
        longer guards its init args (a restart after this frees-then-fails like the
        reference when the owner of the args is gone)."""
        for pid in self._actor_arg_pins.pop(actor_id, ()):  # noqa: B020
            self.reference_counter.remove_local_ref(pid)

    def submit_actor_task(
        self,
        actor_id: ActorID,
        method_name: str,
        args,
        kwargs,
        num_returns: int = 1,
        concurrency_group: str | None = None,
        out_of_order: bool = False,
    ) -> list[ObjectRef]:
        self.reference_counter.drain_deferred()
        task_id = TaskID.from_random()
        ser_args, ser_kwargs, promoted = self._serialize_args(args, kwargs)
        if promoted:
            self._pending_promoted[task_id] = promoted
        streaming = num_returns == "streaming"
        return_ids = (
            [] if streaming else [ObjectID.from_task(task_id, i) for i in range(num_returns)]
        )
        owner = self._owner_address()
        counter = self._actor_seq.setdefault(actor_id, _Counter())
        spec = {
            "type": "actor_task",
            "task_id": task_id,
            "actor_id": actor_id,
            "name": method_name,
            "method_name": method_name,
            "args": ser_args,
            "kwargs": ser_kwargs,
            "num_returns": num_returns,
            "return_ids": return_ids,
            "owner": owner,
            "caller_id": self.worker_id.binary(),
            "seq": counter.next(),
        }
        if concurrency_group:
            spec["concurrency_group"] = concurrency_group
        if out_of_order:
            spec["ooo"] = True
        refs = []
        for oid in return_ids:
            self.reference_counter.add_owned(oid)
            self.memory_store.create_pending(oid)
            refs.append(ObjectRef(oid, owner))
        from ray_tpu.util import tracing

        tctx = tracing.propagation_context()
        if tctx:
            spec["trace_ctx"] = tctx
        if streaming:
            self._streams[task_id] = _StreamState()
        # Hot path: push the call straight to the actor process over a cached
        # direct connection — no raylet hop per call (reference:
        # actor_task_submitter.h:67 direct gRPC to the actor after creation).
        # Streaming specs ride the SAME ordered direct queue (a raylet detour
        # would leave a hole at their seq and wedge every later call) but are
        # not flagged __direct__: their items/end still route via the raylet.
        use_direct = not self.remote_data_plane and self._submit_actor_direct(
            actor_id, spec
        )
        if not use_direct:
            self._submit_when_ready(spec, target="submit_actor_task")
        if streaming:
            return ObjectRefGenerator(task_id, self)
        return refs

    # ------------------------------------------------------------------ lease caching (normal tasks)

    def _lease_eligible(self, spec) -> bool:
        """The lease fast path serves plain tasks; anything needing the
        scheduler's policy zoo (placement groups, affinity, spread) or stream
        bookkeeping takes the classic raylet route."""
        return (
            not self.remote_data_plane
            and spec.get("placement_group") is None
            and spec.get("scheduling_strategy") is None
            and spec.get("num_returns") != "streaming"
        )

    def _lease_shape(self, spec) -> tuple:
        from ray_tpu._private import runtime_env as runtime_env_mod

        return (
            tuple(sorted((spec.get("resources") or {}).items())),
            runtime_env_mod.env_key(spec.get("runtime_env")),
        )

    def _lease_submit(self, spec):
        shape = self._lease_shape(spec)
        with self._lease_lock:
            st = self._leases.setdefault(
                shape, {"workers": {}, "queue": deque(), "requesting": False,
                        "classic_until": 0.0, "depth": _lease_depth_min()},
            )
            if time.monotonic() < st["classic_until"]:
                classic = True
            else:
                classic = False
                st["queue"].append(spec)
        if classic:
            self.io.spawn(self.raylet.notify("submit_task", spec))
            return
        self._lease_pump(shape)

    def _lease_pump(self, shape):
        """Assign queued specs to leased workers with free pipeline slots;
        request more leases while work outstrips them (one outstanding request
        per shape).

        Each worker takes up to lease_worker_slots in-flight tasks (reference:
        the owner pipelines pushes ahead of completions so small tasks never
        pay a full owner<->worker round trip between executions), and pushes
        ride a per-worker send queue whose drainer packs everything accumulated
        into one push_batch frame — a burst of .remote() calls coalesces into
        a few frames instead of one frame (and one event-loop wakeup) per task."""
        to_wake, request = [], False
        with self._lease_lock:
            st = self._leases.get(shape)
            if st is None or not st["queue"]:
                # Completion hot path: nothing queued means nothing to assign,
                # and any non-empty sendq already has its send loop running.
                return
            # Adaptive pipeline depth: start shallow (lease_pipeline_min_depth) so a
            # burst leaves work queued and lease requests fan it out across
            # workers; _lease_request doubles the depth toward
            # lease_worker_slots each time the raylet DENIES a lease with work
            # still queued (the node is saturated — parallelism is exhausted,
            # so pipeline deeper instead: bigger frames, fewer wakeups).
            slots = max(1, min(st.get("depth", _lease_depth_min()),
                               CONFIG.lease_worker_slots))
            # Round-robin one task per worker per pass: a greedy fill would
            # park a whole burst on the first worker while the rest idle;
            # breadth-first keeps execution parallel and the per-worker sendq
            # still coalesces everything a pass assigns into one frame.
            live = [
                w for w in st["workers"].values()
                if not w["conn"].closed and len(w["inflight"]) < slots
            ]
            while st["queue"] and live:
                for w in list(live):
                    if not st["queue"]:
                        break
                    spec = st["queue"].popleft()
                    spec["__direct__"] = True
                    w["inflight"][spec["task_id"]] = spec
                    w["sendq"].append(spec)
                    self._lease_inflight[spec["task_id"]] = (shape, w["worker_id"])
                    if len(w["inflight"]) >= slots:
                        live.remove(w)
            for w in st["workers"].values():
                if w["sendq"] and not w["sending"]:
                    w["sending"] = True
                    to_wake.append(w)
            if st["queue"] and not st["requesting"]:
                st["requesting"] = True
                request = True
        for w in to_wake:
            self.io.spawn(self._lease_send_loop(shape, w))
        if request:
            self.io.spawn(self._lease_request(shape))

    async def _lease_send_loop(self, shape, w):
        """Drain the worker's send queue, one frame per accumulated batch."""
        while True:
            with self._lease_lock:
                batch = list(w["sendq"])
                w["sendq"].clear()
                if not batch:
                    w["sending"] = False
                    return
            try:
                await w["conn"].notify("push_batch", batch)
            except Exception:
                with self._lease_lock:
                    w["sending"] = False
                self._lease_worker_lost(shape, w["worker_id"], w["conn"])
                return

    async def _lease_request(self, shape):
        resources, env_key = dict(shape[0]), shape[1]
        with self._lease_lock:
            st = self._leases.get(shape)
            sample = st["queue"][0] if st and st["queue"] else None
        renv = sample.get("runtime_env") if sample else None
        try:
            resp = await self.raylet.call(
                "request_lease", resources or {"CPU": 1}, renv, self.worker_id
            )
        except Exception:
            resp = None
        conn = None
        if resp and resp.get("ok"):
            try:
                conn = await rpc.connect(
                    *resp["direct_addr"], handler=self, name="lease-worker",
                    via=self.proxy,
                )
            except Exception:  # OSError or connect timeout: give the lease back
                conn = None
                self.io.spawn(self.raylet.notify("release_lease", resp["worker_id"]))
        drain_classic = []
        with self._lease_lock:
            st = self._leases.get(shape)
            if st is None:
                if conn is not None:
                    # The lease state vanished while we were connecting: give
                    # the lease back AND close the socket — nothing will ever
                    # use this conn, and an unclosed one lingers until GC.
                    self.io.spawn(self.raylet.notify("release_lease", resp["worker_id"]))
                    self.io.spawn(conn.close())
                return
            st["requesting"] = False
            if conn is not None:
                wid = resp["worker_id"]
                w = {"worker_id": wid, "conn": conn, "inflight": {},
                     "sendq": deque(), "sending": False}
                st["workers"][wid] = w
                st["retries"] = 0
                # Capacity exists again: go back to shallow pipelines so the
                # next burst spreads before it deepens.
                st["depth"] = _lease_depth_min()
                conn.on_close(lambda c: self._lease_worker_lost(shape, wid, c))
            elif resp and resp.get("infeasible"):
                # This node can never run the shape: hand everything queued to
                # the raylet (spillback machinery) and stop fast-pathing it
                # for a while.
                st["classic_until"] = time.monotonic() + 10.0
                while st["queue"]:
                    drain_classic.append(st["queue"].popleft())
            elif st["queue"]:
                # Denied with work queued: the node can't lease more workers
                # for this shape right now. Deepen the per-worker pipeline so
                # the backlog rides existing leases in large frames.
                st["depth"] = min(
                    max(st.get("depth", _lease_depth_min()), 1) * 2,
                    CONFIG.lease_worker_slots,
                )
                st["retries"] = st.get("retries", 0) + 1
                if st["retries"] > 40 and not st["workers"]:
                    # Long-denied with no leased worker: the node may be wedged
                    # by blocked parents (nested zero-slot tasks). The classic
                    # scheduler has the deadlock-avoidance spawn logic; use it.
                    st["classic_until"] = time.monotonic() + 10.0
                    st["retries"] = 0
                    while st["queue"]:
                        drain_classic.append(st["queue"].popleft())
                else:
                    # Busy node: retry while demand remains.
                    st["requesting"] = True
                    self.io.loop.call_later(
                        0.05, lambda: self.io.spawn(self._lease_request(shape))
                    )
        for spec in drain_classic:
            self.io.spawn(self.raylet.notify("submit_task", spec))
        # Pump in both cases: a grant added a worker; a denial deepened the
        # pipeline, so the backlog rides existing workers at the new depth
        # (`requesting` was re-armed above — pump won't double-request).
        self._lease_pump(shape)
        if conn is not None:
            # The queue may have drained while this grant was in flight (an
            # existing leased worker took the work): an unused grant must not
            # pin the worker forever.
            with self._lease_lock:
                st = self._leases.get(shape)
                w = st["workers"].get(resp["worker_id"]) if st else None
                idle = w is not None and not w["inflight"] and (not st["queue"])
            if idle:
                self._schedule_lease_release(shape, resp["worker_id"])

    def _schedule_lease_release(self, shape, wid):
        """Return the lease after a short grace if the worker is still idle —
        bursty submitters keep their warm worker. Must run on the io thread."""

        def maybe_release():
            with self._lease_lock:
                st = self._leases.get(shape)
                if st is None:
                    return
                w = st.get("workers", {}).get(wid)
                if w is None or w["inflight"] or st["queue"]:
                    return
                st["workers"].pop(wid, None)
                conn = w["conn"]
            self.io.spawn(self.raylet.notify("release_lease", wid))
            self.io.spawn(conn.close())

        self.io.loop.call_later(0.25, maybe_release)

    def _lease_task_finished(self, task_id):
        entry = self._lease_inflight.pop(task_id, None)
        if entry is None:
            return
        shape, wid = entry
        with self._lease_lock:
            st = self._leases.get(shape)
            if st is None:
                return
            w = st["workers"].get(wid)
            if w is not None:
                w["inflight"].pop(task_id, None)
                if not st["queue"] and not w["inflight"]:
                    self._schedule_lease_release(shape, wid)
        self._lease_pump(shape)

    def _lease_worker_lost(self, shape, wid, conn):
        """A leased worker died: retry its in-flight tasks or fail them."""
        failed = []
        with self._lease_lock:
            st = self._leases.get(shape)
            if st is None:
                return
            w = st["workers"].pop(wid, None)
            if w is None:
                return
            for respec in w["inflight"].values():
                self._lease_inflight.pop(respec["task_id"], None)
                if respec.get("retries_left", 0) > 0:
                    respec["retries_left"] -= 1
                    respec.pop("__direct__", None)
                    st["queue"].appendleft(respec)
                else:
                    failed.append(respec)
        if failed:
            from ray_tpu.exceptions import OutOfMemoryError, WorkerCrashedError

            oom_cause = self._lease_oom.pop(wid, None)
            for respec in failed:
                if oom_cause is not None:
                    err_obj = OutOfMemoryError(
                        f"task {respec.get('name')} failed: {oom_cause}"
                    )
                else:
                    err_obj = WorkerCrashedError(
                        f"task {respec.get('name')} failed: leased worker died during execution"
                    )
                err = serialization.dumps(err_obj)
                for oid in respec["return_ids"]:
                    self.memory_store.resolve(oid, err, True, False)
        self._lease_pump(shape)

    async def rpc_lease_oom(self, conn, payload):
        """Raylet forewarning: a leased worker is being OOM-killed for cause."""
        self._lease_oom[payload["worker_id"]] = payload["cause"]
        if len(self._lease_oom) > 256:  # bound stale entries
            self._lease_oom.pop(next(iter(self._lease_oom)))
        return True

    # ------------------------------------------------------------------ direct actor path

    def _submit_actor_direct(self, actor_id: ActorID, spec) -> bool:
        """Route an actor call over the direct worker connection.

        Returns True when the direct path owns delivery (possibly queued behind
        address resolution). The first submission per actor decides the path
        STICKILY — mixing transports would break per-caller seq ordering at the
        executor. Sends flush strictly in seq order, so the executor's
        first-arrival-sets-baseline logic always sees the lowest outstanding seq
        first (reference: ActorSubmitQueue sends in order even when dependencies
        resolve out of order).
        """
        with self._direct_lock:
            st = self._direct_send.get(actor_id)
            if st is None:
                if self._direct_actor.get(actor_id, "?") is None:
                    return False  # resolved earlier: raylet path forever
                st = self._direct_send[actor_id] = {
                    "next": spec["seq"], "ready": {}, "state": "resolving",
                }
                self.io.spawn(self._resolve_actor_direct(actor_id))
            elif st["state"] == "raylet":
                # Fallback decided: keep every later call on the raylet too.
                return False
        self._when_args_ready(spec, lambda: self._direct_mark_ready(actor_id, spec))
        return True

    def _direct_mark_ready(self, actor_id: ActorID, spec):
        with self._direct_lock:
            st = self._direct_send.get(actor_id)
            if st is None:
                self._submit_when_ready(spec, target="submit_actor_task")
                return
            if spec.pop("ooo", None):
                st["ooo"] = True
            st["ready"][spec["seq"]] = spec
        self._direct_flush(actor_id)

    def _direct_flush(self, actor_id: ActorID):
        fallback, drain = [], False
        with self._direct_lock:
            st = self._direct_send.get(actor_id)
            if st is None:
                return
            if st["state"] == "connected":
                while st["next"] in st["ready"]:
                    spec = st["ready"].pop(st["next"])
                    st["next"] += 1
                    if spec.get("num_returns") != "streaming":
                        spec["__direct__"] = True
                    self._direct_inflight[spec["task_id"]] = spec
                    st.setdefault("sendq", deque()).append(spec)
                # Out-of-order actors take no ordering guarantee end to end:
                # ship whatever is ready (args resolved) regardless of seq
                # continuity — the executor side skips gating symmetrically.
                # The flag is STICKY per actor (set by the first tagged spec),
                # so every pending spec ships even if some arrived through a
                # handle that predates the flag.
                if st.get("ooo"):
                    for seq in sorted(st["ready"]):
                        spec = st["ready"].pop(seq)
                        st["next"] = max(st["next"], seq + 1)
                        if spec.get("num_returns") != "streaming":
                            spec["__direct__"] = True
                        self._direct_inflight[spec["task_id"]] = spec
                        st.setdefault("sendq", deque()).append(spec)
                if st.get("sendq") and not st.get("draining"):
                    st["draining"] = True
                    drain = True
            elif st["state"] == "raylet":
                # Resolution failed after calls queued: replay them via the
                # raylet in seq order (legacy transport, legacy semantics).
                for seq in sorted(st["ready"]):
                    fallback.append(st["ready"].pop(seq))
        if drain:
            self.io.spawn(self._direct_drain(actor_id))
        for spec in fallback:
            self.io.spawn(self.raylet.notify("submit_actor_task", spec))

    async def _direct_drain(self, actor_id: ActorID):
        """Single in-flight drainer per actor: ships everything queued since the
        last write in ONE frame (push_batch) — a submit burst coalesces into a
        few pickles/syscalls instead of one per call."""
        while True:
            with self._direct_lock:
                st = self._direct_send.get(actor_id)
                if st is None:
                    return
                batch = list(st.get("sendq") or ())
                if st.get("sendq"):
                    st["sendq"].clear()
                if not batch:
                    st["draining"] = False
                    return
                conn = self._direct_actor.get(actor_id)
            if conn is None or getattr(conn, "closed", True):
                with self._direct_lock:
                    if st is self._direct_send.get(actor_id):
                        st["draining"] = False
                return
            try:
                if len(batch) == 1:
                    await conn.notify("push_task", batch[0])
                else:
                    await conn.notify("push_batch", batch)
            except Exception:
                with self._direct_lock:
                    st["draining"] = False
                self._direct_conn_lost(actor_id, conn)
                return

    async def _resolve_actor_direct(self, actor_id: ActorID):
        """Resolve the actor's direct address via the GCS and connect (io thread)."""
        conn = None
        dead = False
        try:
            for _attempt in range(3):
                info = await self.gcs.call("wait_actor_alive", actor_id, 60.0)
                if info is None or info["state"] == "DEAD":
                    dead = True
                    break
                if info["state"] == "ALIVE":
                    daddr = (info.get("address") or {}).get("direct_addr")
                    if daddr:
                        conn = await rpc.connect(
                            *daddr, handler=self,
                            name=f"direct->{actor_id.hex()[:8]}",
                            via=self.proxy,
                        )
                    break
                # PENDING/RESTARTING: wait again
        except Exception:
            conn = None
        with self._direct_lock:
            st = self._direct_send.get(actor_id)
            if conn is not None:
                self._direct_actor[actor_id] = conn
                if st is not None:
                    st["state"] = "connected"
                conn.on_close(lambda c: self._direct_conn_lost(actor_id, c))
            else:
                self._direct_actor[actor_id] = None
                if st is not None:
                    st["state"] = "raylet"
        self._direct_flush(actor_id)
        if dead:
            # Only for DEAD actors: a LIVE actor's "raylet" tombstone must stay
            # (dropping it would let a later call retry direct mid-stream and
            # break per-caller seq ordering across transports).
            self._direct_gc(actor_id)

    def _direct_conn_lost(self, actor_id: ActorID, conn):
        """Direct connection dropped (actor death or restart): fail the calls it
        carried — with the GCS-recorded cause — and re-resolve for later calls."""
        with self._direct_lock:
            if self._direct_actor.get(actor_id) is not conn:
                return  # stale callback (already re-resolved)
            self._direct_actor.pop(actor_id, None)
            st = self._direct_send.get(actor_id)
            if st is not None and st["state"] == "connected":
                if self._connected:
                    st["state"] = "resolving"
                    self.io.spawn(self._resolve_actor_direct(actor_id))
                else:
                    st["state"] = "raylet"  # shutting down: no re-resolution
            inflight = []
            for tid, s in list(self._direct_inflight.items()):
                if s.get("actor_id") == actor_id:
                    self._direct_inflight.pop(tid, None)
                    inflight.append(s)
        if inflight and self._connected:
            self.io.spawn(self._fail_direct_inflight(actor_id, inflight))
        else:
            # No in-flight calls to fail: reclaim the per-actor state here
            # (the only other gc site is _fail_direct_inflight).
            self._direct_gc(actor_id)

    async def _fail_direct_inflight(self, actor_id: ActorID, inflight: list):
        from ray_tpu.exceptions import ActorDiedError

        await asyncio.sleep(0.3)  # let the raylet report the death cause to GCS
        reason = "actor died (direct connection lost)"
        try:
            info = await self.gcs.call("get_actor_info", actor_id)
            if info is not None and info.get("death_cause"):
                reason = f"actor died: {info['death_cause']}"
            elif info is not None and info["state"] == "RESTARTING":
                reason = "actor died during method call (restarting)"
        except Exception:
            pass  # GCS unreachable: fall through to the generic death reason
        exc = ActorDiedError(actor_id, reason)
        err = serialization.dumps(exc)
        for spec in inflight:
            if spec.get("num_returns") == "streaming":
                st = self._streams.get(spec["task_id"])
                if st is not None:
                    with st.cond:
                        st.abort_error = exc
                        st.cond.notify_all()
            else:
                for oid in spec["return_ids"]:
                    self.memory_store.resolve(oid, err, True, False)
        self._direct_gc(actor_id)

    def _direct_gc(self, actor_id: ActorID):
        """Drop per-actor direct state once it holds nothing live — long-lived
        drivers churning thousands of short-lived actors must not accumulate
        send-state dicts and dead Connection objects forever."""
        with self._direct_lock:
            st = self._direct_send.get(actor_id)
            if st is not None and (st["ready"] or st.get("sendq") or
                                   st.get("draining") or
                                   st["state"] == "resolving"):
                return  # pending work or a resolver in flight: not yet
            conn = self._direct_actor.get(actor_id)
            if conn is not None and not getattr(conn, "closed", True):
                return
            self._direct_send.pop(actor_id, None)
            self._direct_actor.pop(actor_id, None)

    # ------------------------------------------------------------------ RPC handlers (io thread)

    async def rpc_task_results(self, conn, payloads: list):
        for payload in payloads:
            await self.rpc_task_result(conn, payload)

    async def rpc_task_result(self, conn, payload):
        with self._direct_lock:
            self._direct_inflight.pop(payload.get("task_id"), None)
        self._lease_task_finished(payload.get("task_id"))
        # Sequenced borrow handoff: the executor's kept borrows and result-ref
        # pre-registrations MUST be absorbed before the arg pins release — same
        # message, strict order, no reorder window (the race the round-1
        # fire-and-forget registration admitted).
        self._register_reply_embeds(payload)
        promoted = self._pending_promoted.pop(payload.get("task_id"), None)
        if promoted:
            for oid in promoted:
                self.reference_counter.remove_local_ref(oid)
        with self._lineage_lock:
            for result in payload["results"]:
                self._reconstructing.discard(result["object_id"])
        for result in payload["results"]:
            oid = result["object_id"]
            in_plasma = bool(result.get("in_plasma"))
            live = self.memory_store.resolve(
                oid, None if in_plasma else result["inline"],
                result.get("error", False), in_plasma,
            )
            if not live and in_plasma:
                # All refs were dropped before the result landed: free the orphan.
                try:
                    await self.raylet.notify("store_free", oid)
                except rpc.RpcError:
                    pass

    async def rpc_stream_item(self, conn, payload):
        """Owner side: one item of a streaming task arrived."""
        task_id, index, result = payload["task_id"], payload["index"], payload["result"]
        oid = result["object_id"]
        in_plasma = bool(result.get("in_plasma"))
        st = self._streams.get(task_id)
        if st is None:
            # Generator was dropped before this item landed: free an orphan.
            if in_plasma:
                try:
                    await self.raylet.notify("store_free", oid)
                except rpc.RpcError:
                    pass
            return True
        self.reference_counter.add_owned(oid)
        self.memory_store.create_pending(oid)
        # Sequenced handoff for refs yielded inside the item (mirrors
        # _register_reply_embeds for task results): pre-seed parents before
        # user code can deserialize them; settle when this item is freed.
        src = result.get("src")
        if src is not None:
            pending = []
            for roid, _o in result.get("result_refs") or ():
                if _o is not None:
                    self.reference_counter.record_true_owner(roid, _o)
                if self.reference_counter.pre_register_borrow(roid, src):
                    pending.append(roid)
                else:
                    self._report_borrow(roid, src, -1)
            if pending:
                with self._embedded_lock:
                    self._reply_embedded[("stream", oid)] = {
                        "refs": pending, "returns": {oid}, "src": src,
                    }
        self.memory_store.resolve(
            oid, None if in_plasma else result["inline"],
            result.get("error", False), in_plasma,
        )
        ref = ObjectRef(oid, self._owner_address())
        with st.cond:
            st.items[index] = ref
            st.cond.notify_all()
        return True

    async def rpc_stream_end(self, conn, payload):
        with self._direct_lock:
            self._direct_inflight.pop(payload.get("task_id"), None)
        st = self._streams.get(payload["task_id"])
        if st is not None:
            with st.cond:
                st.total = payload["count"]
                st.cond.notify_all()
        return True

    async def rpc_stream_abort(self, conn, payload):
        """The producing worker died with retries exhausted: unblock the consumer."""
        from ray_tpu.exceptions import WorkerCrashedError

        st = self._streams.get(payload["task_id"])
        if st is not None:
            with st.cond:
                st.abort_error = WorkerCrashedError(payload.get("reason", "stream lost"))
                st.cond.notify_all()
        return True

    async def rpc_borrow_check(self, conn, payload):
        """Audit probe: which of these ids does this process still hold (as a
        local ref, a sub-borrower parent, or an in-flight handoff)?"""
        rc = self.reference_counter
        held = []
        with rc._lock:
            for oid in payload["object_ids"]:
                if (
                    rc._counts.get(oid, 0) > 0
                    or rc._borrow_total_locked(oid) > 0
                    or oid in rc._preregistered
                    or oid in rc._task_deferred
                    or oid in rc._pending_upstream
                ):
                    held.append(oid)
        return {"held": held}

    async def rpc_borrow_update(self, conn, payload):
        self.reference_counter.update_borrow(
            payload["object_id"], payload["delta"],
            tuple(payload.get("borrower") or ("?", "?")),
        )
        return True

    async def rpc_reconstruct_object(self, conn, payload):
        """A borrower lost an object we own: rebuild it from lineage."""
        return {"ok": self._try_reconstruct_owned(payload["object_id"])}

    async def rpc_fetch_inline(self, conn, payload):
        rec = self.memory_store.get(payload["object_id"])
        if rec is None:
            return {"error": "unknown"}
        if not rec.resolved:
            return {"pending": True}
        if rec.in_plasma:
            return {"plasma": True}
        return {"data": rec.data}

    async def rpc_publish(self, conn, channel, message):
        if channel == "worker_logs" and self.mode != "worker":
            # Scope to this driver: a worker's lines are shipped tagged with the
            # owner of the work it is running (reference: log_monitor publishes
            # per-job and drivers subscribe to their own job's channel). Lines
            # from work owned by another driver are dropped; untagged lines
            # (idle-worker chatter, system actors) go to every driver.
            owner = message.get("owner")
            if owner is not None and owner != self.worker_id.hex():
                return True
            try:
                prefix = f"({message.get('kind', 'worker')} pid={message.get('pid')}, node={message.get('node', '')[:8]})"
                out = self._log_dedup.ingest(
                    prefix, message.get("pid"), message.get("lines", ())
                )
                if out:
                    sys.stderr.write(out)
                    sys.stderr.flush()
            except Exception:
                pass  # stderr may be closed at interpreter teardown; drop the lines
        return True

    async def rpc_push_task(self, conn, spec):
        if spec.get("__direct__") and conn is not self.raylet:
            # Pushed straight from the owner: results reply over this very
            # connection, no raylet hop (reference: PushTask replies carry
            # small results inline to the caller). The raylet guard covers a
            # retried/reconstructed spec whose stale flag survived — those are
            # raylet-dispatched and must answer via task_done.
            spec["__reply_conn__"] = conn
        if spec["type"] == "actor_task":
            self._enqueue_actor_task(spec)
        elif spec.get("__direct__"):
            self._lease_executor.submit(self._execute_task_guarded, spec)
        else:
            self._task_executor.submit(self._execute_task_guarded, spec)

    async def rpc_push_batch(self, conn, specs):
        for spec in specs:
            await self.rpc_push_task(conn, spec)

    async def rpc_chan_pull(self, conn, name, reader, index, poll: float = 25.0):
        """Cross-node channel long-poll: serve one ring item to a remote reader
        (ring lives in this process — see experimental/channel.py RpcChannel).
        Poll interval backs off 0.5ms -> 10ms so a hot pipeline sees sub-ms
        latency while an idle one doesn't spin the shared event loop."""
        from ray_tpu.experimental.channel import _ring_pull

        deadline = time.monotonic() + min(poll, 25.0)
        delay = CONFIG.channel_poll_min_s
        while True:
            resp = _ring_pull(name, reader, index)
            if "wait" not in resp and "unknown" not in resp:
                return resp
            if time.monotonic() > deadline:
                return resp  # reader loop retries (keeps conns live/cancellable)
            await asyncio.sleep(delay)
            delay = min(delay * 1.5, CONFIG.channel_poll_max_s)

    async def rpc_chan_close(self, conn, name):
        from ray_tpu.experimental.channel import _ring_close

        return _ring_close(name)

    async def rpc_chan_detach(self, conn, name, reader):
        """Multicast dead-subscriber unwind: stop counting one reader slot
        toward the named ring's back-pressure (experimental/channel.py)."""
        from ray_tpu.experimental.channel import _ring_detach

        return _ring_detach(name, reader)

    async def rpc_init_actor(self, conn, actor_id: ActorID, spec):
        fut = self._task_executor.submit(self._init_actor, actor_id, spec)
        return await asyncio.wrap_future(fut)

    async def rpc_exit(self, conn):
        os._exit(0)

    # ------------------------------------------------------------------ execution

    def _materialize(self, loc):
        if "v" in loc:
            value = serialization.loads(loc["v"])
            return value
        oid, owner = loc["ref"]
        ref = ObjectRef(oid, owner)
        return self.get([ref])[0]

    def _materialize_args(self, spec):
        args = [self._materialize(a) for a in spec["args"]]
        kwargs = {k: self._materialize(v) for k, v in spec["kwargs"].items()}
        return args, kwargs

    def _init_actor(self, actor_id: ActorID, spec) -> dict:
        try:
            from ray_tpu._private import runtime_env as runtime_env_mod

            # The actor owns this worker process: its runtime env applies for life.
            runtime_env_mod.apply_permanent(spec.get("runtime_env"))
            cls = self.functions.load(spec["cls_key"])
            args, kwargs = self._materialize_args(spec)
            instance = cls.__new__(cls)
            # Identity is visible DURING __init__ (reference:
            # get_runtime_context().get_actor_id() works in constructors —
            # e.g. replicas registering themselves with coordinators).
            self.actor_id = actor_id
            instance.__init__(*args, **kwargs)
            self.actor_runtime = _ActorRuntime(
                instance, spec.get("max_concurrency", 1), spec.get("is_async", False),
                concurrency_groups=spec.get("concurrency_groups"),
                method_groups=spec.get("method_groups"),
                out_of_order=spec.get("allow_out_of_order_execution", False),
            )
            return {"ok": True}
        except Exception:
            self.actor_id = None
            return {"ok": False, "error": traceback.format_exc()}

    def _enqueue_actor_task(self, spec):
        """Per-caller sequence ordering (ActorSchedulingQueue parity). Runs on io thread."""
        rt = self.actor_runtime
        if rt is None:
            return
        if rt.out_of_order:
            # Explicit out-of-order mode (reference:
            # out_of_order_actor_submit_queue.cc): dispatch on arrival, no
            # seq gating — threaded actors trade ordering for latency.
            self._dispatch_actor_task(rt, spec)
            return
        caller = spec["caller_id"]
        # First message from a caller sets the baseline: after an actor restart the
        # caller's sequence counter keeps counting, and the old incarnation's numbers
        # must not wedge the new one. Per-caller transport is ordered, so the first
        # arrival is the lowest outstanding seq.
        expected = rt.expected_seq.get(caller)
        if expected is None:
            expected = spec["seq"]
        rt.buffered[(caller, spec["seq"])] = spec
        while (caller, expected) in rt.buffered:
            ready = rt.buffered.pop((caller, expected))
            expected += 1
            rt.expected_seq[caller] = expected
            self._dispatch_actor_task(rt, ready)

    def _dispatch_actor_task(self, rt, spec):
        """Route a released call to its concurrency group's executor. Dispatch
        never blocks on execution, so a wedged group cannot stall another."""
        group = rt.group_of(spec)
        if group is not None and group not in rt.concurrency_groups:
            # Unknown group: fail THIS call with a proper error result instead
            # of wedging the queue (validated caller-side too when declared).
            spec["__invalid_group__"] = (
                f"actor has no concurrency group {group!r} "
                f"(declared: {sorted(rt.concurrency_groups)})"
            )
            group = None
        if rt.is_async:
            asyncio.run_coroutine_threadsafe(
                self._execute_async_actor_task(spec), rt.async_loop
            )
        else:
            executor = rt.group_executors.get(group, rt.executor)
            executor.submit(self._execute_task_guarded, spec)

    def _resolve_actor_method(self, instance, method_name: str):
        """Method lookup plus the __rtpu_apply__ escape hatch: run an arbitrary
        function against the actor instance (parity: the reference's __ray_call__,
        used by compiled DAGs to install their pinned exec loops)."""
        if method_name == "__rtpu_apply__":
            def apply(fn, *args, **kwargs):
                res = fn(instance, *args, **kwargs)
                if asyncio.iscoroutine(res):
                    # Coroutine fns let callers avoid stalling an async
                    # actor's event loop (the async executor awaits the
                    # returned coroutine); on sync actors run it to completion
                    # on this executor thread.
                    try:
                        asyncio.get_running_loop()
                        return res
                    except RuntimeError:
                        return asyncio.run(res)
                return res

            return apply
        return getattr(instance, method_name)

    async def _execute_async_actor_task(self, spec):
        from ray_tpu.util import tracing

        rt = self.actor_runtime
        group = rt.group_of(spec)
        sem = rt.group_semaphores.get(group, rt.semaphore)
        async with sem:
            method = self._resolve_actor_method(rt.instance, spec["method_name"])
            # Trace-context parity with the sync executor path: each call runs
            # inside its own asyncio.Task, so activating the caller's span here
            # is Task-scoped (contextvars) and nested .remote() calls made by
            # the async method continue ONE trace across processes — the serve
            # proxy -> router -> replica chain is async actors end to end.
            trace_token = tracing.activate(spec.get("trace_ctx"))
            self._record_event(
                task_id=spec["task_id"].hex(), name=spec["name"],
                state="RUNNING", **tracing.event_fields(spec.get("trace_ctx")))
            # The sink outlives the materializer thread: refs the async method
            # keeps past completion ride the reply's sequenced handoff exactly
            # like sync tasks (packaging and handoff are synchronous sections
            # on the loop thread, so their thread-locals cannot interleave).
            sink: dict = {}

            def _materialize_sinked():
                self._tls.borrow_sink = sink
                try:
                    return self._materialize_args(spec)
                finally:
                    self._tls.borrow_sink = None

            args = kwargs = result = None
            try:
                if "__invalid_group__" in spec:
                    raise ValueError(spec["__invalid_group__"])
                args, kwargs = await asyncio.get_running_loop().run_in_executor(
                    None, _materialize_sinked
                )
                result = method(*args, **kwargs)
                if asyncio.iscoroutine(result):
                    result = await result
                if spec.get("num_returns") == "streaming":
                    await self._run_streaming_async(spec, result)
                    results = []
                else:
                    results = self._package_results(spec, result)
                state = "FINISHED"
            except Exception as e:
                if spec.get("num_returns") == "streaming":
                    await asyncio.get_running_loop().run_in_executor(
                        None, self._stream_failure, spec, e
                    )
                    results = []
                else:
                    results = self._package_error(spec, e)
                state = "FAILED"
            args = kwargs = result = None  # noqa: F841 — drop frame refs first
            tracing.deactivate(trace_token)
            self._record_event(
                task_id=spec["task_id"].hex(), name=spec["name"], state=state,
                **tracing.event_fields(spec.get("trace_ctx")))
            self.reference_counter.drain_deferred()
            self._reply_actor_result(spec, results, self._borrow_handoff(spec, sink))

    def _reply_actor_result(self, spec, results, extra: dict | None = None):
        """Route actor-call results: straight back over the owner's direct
        connection when the call arrived on one, else via the raylet."""
        extra = extra or {}
        rconn = spec.pop("__reply_conn__", None)
        if rconn is not None and not rconn.closed:
            self.io.spawn(
                rconn.notify("task_result",
                             {"task_id": spec["task_id"], "results": results, **extra})
            )
            return
        self.io.spawn(
            self.raylet.notify("actor_task_done", spec["owner"], spec["task_id"],
                               results, extra)
        )

    def _execute_task_guarded(self, spec):
        try:
            self._execute_task(spec)
        except Exception:
            traceback.print_exc()

    def _execute_task(self, spec):
        from ray_tpu.util import tracing

        prev_task = getattr(self._tls, "task_id", None)
        prev_sink = getattr(self._tls, "borrow_sink", None)
        self._tls.task_id = spec["task_id"]
        # Borrowed refs first seen during this task defer registration to the
        # reply (the caller's arg pins protect them meanwhile).
        self._tls.borrow_sink = {}
        trace_token = tracing.activate(spec.get("trace_ctx"))
        self._record_event(task_id=spec["task_id"].hex(), name=spec["name"], state="RUNNING",
                           **tracing.event_fields(spec.get("trace_ctx")))
        try:
            from ray_tpu._private import runtime_env as runtime_env_mod

            # The env applies BEFORE function load / arg deserialization: both may
            # depend on py_modules/working_dir being importable.
            with runtime_env_mod.applied(spec.get("runtime_env")):
                if spec["type"] == "actor_task":
                    if "__invalid_group__" in spec:
                        raise ValueError(spec["__invalid_group__"])
                    fn = self._resolve_actor_method(
                        self.actor_runtime.instance, spec["method_name"]
                    )
                else:
                    fn = self.functions.load(spec["fn_key"])
                args, kwargs = self._materialize_args(spec)
                result = fn(*args, **kwargs)
            if spec.get("num_returns") == "streaming":
                self._run_streaming(spec, result)
                results = []
            else:
                results = self._package_results(spec, result)
            state = "FINISHED"
        except Exception as e:  # noqa: BLE001 - report any user failure to the owner
            from ray_tpu._private import debugger

            if debugger.post_mortem_enabled():
                # Park the failing frame: advertise a debug session and block
                # this task (only this task) until an operator's `ray_tpu
                # debug` drives pdb over the socket, or the wait expires;
                # the error then propagates exactly as it would have
                # (reference: RAY_DEBUG_POST_MORTEM + util/rpdb.py).
                try:
                    debugger.park_post_mortem(self, spec, e)
                except Exception:
                    pass
            if spec.get("num_returns") == "streaming":
                # Pre-iteration failure (fn load / arg materialization): the
                # stream must still terminate with an error ref, not hang.
                self._stream_failure(spec, e)
                results = []
            else:
                results = self._package_error(spec, e)
            state = "FAILED"
        finally:
            # Drop the frame's own arg/result refs and apply their deferred
            # releases BEFORE snapshotting the sink: `kept` must mean "the task
            # body stored the ref somewhere", not "the executing frame hasn't
            # exited yet" — otherwise every borrowed arg ships a useless
            # +1/-1 pair per call.
            args = kwargs = result = None  # noqa: F841
            self.reference_counter.drain_deferred()
            sink = getattr(self._tls, "borrow_sink", None) or {}
            self._tls.task_id = prev_task
            self._tls.borrow_sink = prev_sink
            tracing.deactivate(trace_token)
        extra = self._borrow_handoff(spec, sink)
        self._record_event(task_id=spec["task_id"].hex(), name=spec["name"], state=state,
                           **tracing.event_fields(spec.get("trace_ctx")))
        if spec["type"] == "actor_task":
            self._reply_actor_result(spec, results, extra)
        else:
            rconn = spec.pop("__reply_conn__", None)
            if rconn is not None and not rconn.closed:
                # Leased direct task: results go straight to the owner; the
                # raylet holds no per-task state for it. Batched per
                # connection — a burst of small-task completions coalesces
                # into a few frames instead of one send per result.
                self._queue_direct_result(
                    rconn, {"task_id": spec["task_id"], "results": results, **extra}
                )
            else:
                self.io.spawn(self.raylet.notify(
                    "task_done", spec["task_id"], results, extra
                ))

    def _borrow_handoff(self, spec, sink: dict) -> dict:
        """Build the reply's sequenced borrow metadata (see ReferenceCounter).

        - `borrows`: borrowed arg refs this executor still holds; the caller
          counts us as borrower before releasing its arg pins.
        - `result_refs`: refs pickled into the results; we pre-count the caller
          as sub-borrower HERE, before the reply leaves, so its first local ref
          is already covered whenever it lands.
        """
        caller = spec.get("owner")
        if caller is None:
            return {}
        kept = {
            oid: owner for oid, owner in sink.items()
            if self.reference_counter.num_refs(oid) > 0
            or self.reference_counter.num_borrows(oid) > 0
        }
        if kept:
            self.reference_counter.promote_task_borrows(kept, caller)
        # The sub-borrows were pre-counted at capture time (_package_results);
        # the reply only needs the lists. captured_kept = borrowed args that
        # were returned in the results (promoted at capture, must be in
        # `borrows` even though the frame dropped their last local ref).
        result_refs = list(getattr(self._tls, "result_refs", None) or ())
        captured_kept = list(getattr(self._tls, "captured_kept", None) or ())
        self._tls.result_refs = None
        self._tls.captured_kept = None
        borrows = list({*kept.keys(), *captured_kept})
        if not borrows and not result_refs:
            return {}
        return {
            "borrows": borrows,
            "result_refs": result_refs,
            "src": self._owner_address(),
        }

    def _queue_direct_result(self, rconn, payload: dict):
        key = id(rconn)
        with self._result_lock:
            self._result_queues.setdefault(key, (rconn, []))[1].append(payload)
            if key in self._result_sending:
                return
            self._result_sending.add(key)
        self.io.spawn(self._result_send_loop(key))

    async def _result_send_loop(self, key):
        while True:
            with self._result_lock:
                entry = self._result_queues.get(key)
                if entry is None or not entry[1]:
                    self._result_sending.discard(key)
                    self._result_queues.pop(key, None)
                    return
                rconn, pending = entry
                batch = pending[:]
                pending.clear()
            try:
                await rconn.notify("task_results", batch)
            except Exception:
                with self._result_lock:
                    self._result_sending.discard(key)
                    self._result_queues.pop(key, None)
                return  # owner gone: its raylet re-routes or fails the tasks

    def _package_results(self, spec, result) -> list:
        num_returns = spec["num_returns"]
        if num_returns == 0:
            values = []
        elif num_returns == 1:
            values = [result]
        else:
            values = list(result)
            if len(values) != num_returns:
                raise ValueError(
                    f"task {spec['name']} declared num_returns={num_returns} "
                    f"but returned {len(values)} values"
                )
        # Capture refs pickled into the results: the reply hands the caller a
        # sequenced borrow on each (see _borrow_handoff).
        self._tls.ref_capture = cap = []
        try:
            packaged = [
                self._package_one(oid, value, spec["owner"])
                for oid, value in zip(spec["return_ids"], values)
            ]
        finally:
            self._tls.ref_capture = None
        caller = spec.get("owner")
        if caller is not None:
            # Pre-count the caller RIGHT HERE, while the executing frame still
            # holds its own refs: the frame's refs drop (and may free) before
            # the reply is built, and the sub-borrow must already be in place.
            caller_key = _addr_key(caller)
            for oid, _owner in cap:
                self.reference_counter.add_sub_borrow(oid, caller_key)
            # A returned BORROWED arg must survive the frame drop with its
            # registration intact: re-parent it to the caller now and force it
            # into the reply's `borrows` list (the frame may hold its only ref).
            self._tls.captured_kept = self.reference_counter.promote_captured(
                [oid for oid, _ in cap], caller
            )
        self._tls.result_refs = cap
        return packaged

    def _package_one(self, oid: ObjectID, value, owner: dict) -> dict:
        pickled, raw_buffers, total = serialization.serialized_size(value)
        if total > CONFIG.max_direct_call_object_size:
            # Rides the zero-RPC direct-arena path when available.
            self._write_plasma(oid, pickled, raw_buffers, total, owner)
            return {"object_id": oid, "in_plasma": True, "size": total}
        return {"object_id": oid, "inline": serialization.assemble(pickled, raw_buffers)}

    def _stream_results(self, spec) -> "callable":
        """Build the per-item sender for a streaming task: each yielded value is
        packaged and pushed to the owner immediately (ObjectRefStream parity)."""
        owner = spec["owner"]
        task_id = spec["task_id"]
        state = {"index": 0}

        def send(value, error: bool = False):
            index = state["index"]
            state["index"] = index + 1
            oid = ObjectID.from_task(task_id, 0x10000000 + index)
            if error:
                out = {"object_id": oid, "inline": serialization.dumps(value), "error": True}
            else:
                # Refs yielded into the stream ride the same sequenced handoff
                # as task results: pre-count the consumer before the item
                # leaves, and re-parent deferred arg borrows so they survive
                # the generator frame (see ReferenceCounter docstring).
                self._tls.ref_capture = cap = []
                try:
                    out = self._package_one(oid, value, owner)
                finally:
                    self._tls.ref_capture = None
                if cap:
                    okey = _addr_key(owner)
                    for roid, _o in cap:
                        self.reference_counter.add_sub_borrow(roid, okey)
                    self.reference_counter.promote_captured(
                        [roid for roid, _o in cap], owner
                    )
                    out["result_refs"] = cap
                    out["src"] = self._owner_address()
            self.io.run(self.raylet.notify("stream_item", owner, task_id, index, out))

        def finish():
            self.io.run(self.raylet.notify("stream_end", owner, task_id, state["index"]))

        return send, finish

    def _run_streaming(self, spec, result):
        """Drive a (sync) generator result, pushing each item to the owner.

        Never raises: a broken raylet link means this worker is about to die
        (worker mode exits when its raylet conn closes) and the raylet-side
        failure path will abort the owner's stream. Raising into the caller's
        generic handler would restart the stream at index 0 and silently
        truncate it at the owner.
        """
        try:
            send, finish = self._stream_results(spec)
            try:
                for value in result:
                    send(value)
            except rpc.RpcError:
                return
            except Exception as e:  # noqa: BLE001 - mid-stream error becomes an error ref
                send(RayTpuTaskError.from_exception(spec["name"], e), error=True)
            finish()
        except Exception:
            traceback.print_exc()

    def _stream_failure(self, spec, exc: Exception):
        send, finish = self._stream_results(spec)
        send(RayTpuTaskError.from_exception(spec["name"], exc), error=True)
        finish()

    async def _run_streaming_async(self, spec, result):
        """Drive an async (or sync) generator inside an async actor. Never raises
        (see _run_streaming)."""
        loop = asyncio.get_running_loop()
        try:
            send, finish = self._stream_results(spec)
            try:
                if hasattr(result, "__anext__"):
                    async for value in result:
                        await loop.run_in_executor(None, send, value)
                else:
                    for value in result:
                        await loop.run_in_executor(None, send, value)
            except rpc.RpcError:
                return
            except asyncio.CancelledError:
                # Stream cancelled mid-flight (serve cancel plane / actor
                # teardown): close the producer so its finally-blocks release
                # what they hold, then finish the stream cleanly — the owner
                # sees a short stream, not a failed task.
                try:
                    if hasattr(result, "aclose"):
                        await result.aclose()
                    elif hasattr(result, "close"):
                        result.close()
                except Exception:
                    pass  # producer teardown is best-effort: the stream still
                    # finishes below, and the generator's own finally already
                    # released what it held before the close raised
                await loop.run_in_executor(None, finish)
                return
            except Exception as e:  # noqa: BLE001
                err = RayTpuTaskError.from_exception(spec["name"], e)
                await loop.run_in_executor(None, lambda: send(err, error=True))
            await loop.run_in_executor(None, finish)
        except Exception:
            traceback.print_exc()

    def _package_error(self, spec, exc: Exception) -> list:
        err = RayTpuTaskError.from_exception(spec["name"], exc)
        data = serialization.dumps(err)
        return [
            {"object_id": oid, "inline": data, "error": True} for oid in spec["return_ids"]
        ]
