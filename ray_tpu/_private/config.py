"""Central flag table, env-var overridable.

Design parity: reference `src/ray/common/ray_config_def.h` (RAY_CONFIG(type, name, default)
table, 226 entries, each overridable by a `RAY_<name>` env var) compiled into a `RayConfig`
singleton (`ray_config.h:60`). Here the table is a plain dict of typed defaults; every entry
is overridable via `RAY_TPU_<NAME>` environment variables, resolved once at first access.
"""

from __future__ import annotations

import json
import os
from typing import Any

_ENV_PREFIX = "RAY_TPU_"

# name -> (type, default, doc)
_DEFS: dict[str, tuple[type, Any, str]] = {
    # --- core runtime ---
    "max_direct_call_object_size": (int, 100 * 1024, "objects <= this many bytes are returned inline through the owner's memory store instead of the shared-memory store"),
    "max_task_retries_default": (int, 3, "default max_retries for remote functions"),
    "max_object_reconstructions": (int, 3, "how many times a lost plasma object may be rebuilt by re-running its producing task (0 disables lineage reconstruction)"),
    "max_lineage_entries": (int, 10000, "max owned objects whose producing task spec is retained for reconstruction; oldest entries are evicted first"),
    "worker_register_timeout_s": (float, 60.0, "how long the raylet waits for a spawned worker to register (covers slow interpreter+jax imports on loaded hosts)"),
    "idle_worker_kill_s": (float, 300.0, "kill idle workers after this many seconds"),
    "get_poll_interval_s": (float, 0.002, "poll interval for blocking gets"),
    "heartbeat_interval_s": (float, 1.0, "raylet -> GCS resource/health report interval"),
    "node_death_timeout_s": (float, 5.0, "GCS marks a node dead after missing heartbeats for this long"),
    "object_store_memory_fraction": (float, 0.3, "fraction of system memory for the per-node shared-memory object store"),
    "store_pretouch_bytes": (int, 1 << 30, "fault in this much of the shm arena at store startup so first puts run at warm-page speed (0 disables)"),
    "object_report_flush_s": (float, 0.02, "raylet batching window for GCS object-directory reports/frees"),
    "pull_chunk_window": (int, 8, "pipelined in-flight chunk requests per remote object pull"),
    "pull_budget_bytes": (int, 1 << 30, "cap on total bytes of concurrently in-flight remote pulls (backpressure)"),
    "object_store_min_chunk_bytes": (int, 1024 * 1024, "chunk size for node-to-node object transfer"),
    # --- memory / OOM defense ---
    "memory_monitor_refresh_ms": (int, 250, "node memory poll interval for the OOM monitor; 0 disables worker killing (reference: memory_monitor_refresh_ms)"),
    "memory_usage_threshold": (float, 0.95, "kill workers when node memory usage crosses this fraction (reference: memory_usage_threshold)"),
    "memory_monitor_min_wait_s": (float, 1.0, "usage must stay above threshold this long before a kill (debounce against transient spikes)"),
    "meminfo_path": (str, "/proc/meminfo", "meminfo source; tests point this at a fake file to simulate pressure"),
    # --- scheduling ---
    "lease_worker_slots": (int, 32, "tasks the owner pipelines ahead per leased worker (execution stays sequential at the worker); deep pipelines coalesce submit bursts into few large frames"),
    "lease_pipeline_min_depth": (int, 2, "starting per-worker pipeline depth for the lease fast path; lease denials ramp it toward lease_worker_slots"),
    "borrow_audit_interval_s": (float, 30.0, "how often owners audit registered borrowers for liveness (crashed borrowers are reconciled)"),
    "borrow_audit_strikes": (int, 3, "consecutive not-held audit verdicts before a live borrower's lost-release entry is reconciled away"),
    "borrow_audit_min_age_s": (float, 2.0, "minimum wall-clock age of a not-held entry before reconciliation (protects slow in-flight handoffs)"),
    "test_delay_borrow_report_ms": (int, 0, "fault injection: delay legacy borrow-report notifies by this long (stress the sequenced protocol)"),
    # --- logging / observability ---
    "event_buffer_size": (int, 10000, "per-worker task event buffer entries"),
    "metrics_report_interval_s": (float, 5.0, "metrics push interval"),
    "gcs_max_task_events": (int, 100000, "task events retained by the GCS before the oldest half is dropped (reference: task_events_max_num_task_in_gcs)"),
    "export_events_dir": (str, "", "when set, the GCS appends structured JSONL export events (tasks/actors/nodes/placement groups) under this directory (reference: export_*.proto + ray_event_recorder)"),
    "gcs_export_queue_size": (int, 1024, "bounded queue between the GCS loop and the export-event writer thread; overflow sheds oldest batches"),
    "gcs_store_fsync_window_s": (float, 0.01, "group-commit window: one fsync covers every GCS store append in the window (RAY_TPU_GCS_STORE_FSYNC picks the mode: always|group|off)"),
    "gcs_store_compact_threshold": (int, 50000, "rewrite the GCS append log once it holds this many records"),
    "gcs_rpc_timeout_s": (float, 30.0, "total deadline for one GCS request across reconnect retries (exponential backoff + jitter); the control plane may restart under live clients, so this bounds how long a call rides through the outage before surfacing ConnectionLost"),
    "gcs_replicas": (int, 1, "GCS head candidates: 1 = the classic single process (restart-recovery only), 3+ = lease-based quorum HA — the primary majority-acks every durable mutation to follower candidates and a follower promotes itself when the primary's lease lapses (docs/fault_tolerance.md)"),
    "gcs_lease_s": (float, 2.0, "primary lease window: the primary renews through the quorum at a third of this period and stops serving when it cannot confirm a majority within it; followers start an election after this much primary silence, so failover lands within ~2x the window"),
    "gcs_quorum_timeout_s": (float, 5.0, "how long a primary waits for a majority of candidates to ack a replicated mutation before demoting itself and failing the call back to the client (who retries against the new primary)"),
    "log_dedup_window_s": (float, 5.0, "repeat window for driver-side worker-log deduplication summaries"),
    "post_mortem": (bool, False, "park failing tasks at the raising frame for `ray_tpu debug` (reference: RAY_DEBUG_POST_MORTEM)"),
    "post_mortem_wait_s": (float, 120.0, "how long a parked task waits for a debugger before its error propagates"),
    "post_mortem_external": (bool, False, "bind the post-mortem pdb server on all interfaces instead of loopback; the socket is an UNAUTHENTICATED interactive interpreter — only enable inside a trusted network boundary (reference: ray debugger_external)"),
    # --- channels / client ---
    "channel_poll_min_s": (float, 0.0005, "cross-node channel long-poll floor: a hot pipeline sees sub-ms latency"),
    "channel_poll_max_s": (float, 0.01, "cross-node channel long-poll backoff ceiling for idle rings"),
    "channel_default_slots": (int, 4, "in-flight values a compiled-graph channel ring holds by default"),
    "channel_tensor_min_bytes": (int, 1024, "array leaves at least this large ride the channel tensor fast path (raw-buffer frame, no cloudpickle of array data; docs/device_channels.md); -1 disables the fast path"),
    "channel_reconnect_s": (float, 5.0, "RpcChannel readers ride transient writer-connection failures (RpcError/OSError) with backoff+jitter for this long before declaring the writer dead (ChannelClosed); dead sockets are evicted from the per-process conn cache so a restarted writer gets a fresh dial"),
    "llm_channel_chunk_bytes": (int, 1 << 20, "chunk size for DeviceChannel staged transfers (PD KV handoff, device_objects.get/transfer): device->host, wire, and host->device legs pipeline at this granularity through a small ring instead of one blocking full-tensor copy (docs/device_channels.md)"),
    "devobj_stream_slots": (int, 4, "ring depth, in chunks, of device-object transfer streams; depth > 1 is what lets the D2H / wire / H2D legs overlap"),
    "devobj_stream_min_bytes": (int, 8 << 20, "device-object fetches at least this large ride the chunked DeviceChannel stream; smaller payloads take the one-hop object-plane blob, whose fixed cost is lower than a stream setup (docs/device_channels.md)"),
    "dag_buffer_size_bytes": (int, 8 << 20, "per-edge channel slot capacity for compiled DAGs (reference: buffer_size_bytes)"),
    "dag_max_inflight_executions": (int, 10, "default bound on in-flight compiled-DAG executions (reference: RAY_CGRAPH_max_inflight_executions)"),
    "dag_execute_timeout_s": (float, 60.0, "compiled-DAG submission/read timeout"),
    "client_proxy_node_cache_s": (float, 5.0, "client proxy's cache TTL for the cluster's registered-endpoint allowlist"),
    # --- train / libraries ---
    "train_ckpt_async": (bool, True, "sharded checkpoints persist on a background writer thread; the step loop pays only one batched device->host snapshot per save (0 = write+commit inline, docs/checkpoint.md)"),
    "train_ckpt_inflight": (int, 2, "bounded in-flight async checkpoint saves per process; a save past the budget backpressures the step loop instead of growing host memory with unpersisted snapshots"),
    "train_ckpt_commit_timeout_s": (float, 120.0, "how long the committing rank waits for every process's shard spec before abandoning the commit (the directory stays manifest-less, i.e. garbage)"),
    "train_flight_records": (int, 64, "per-step flight records kept in each train worker's recorder ring (docs/observability.md): data-wait/step-compute/report-blocked/checkpoint-blocked phase attribution per report(), exported only from train_stats()/Result (0 disables)"),
    "serve_http_port": (int, 8000, "default HTTP port each node's serve proxy binds (reference: serve DEFAULT_HTTP_PORT)"),
    "serve_handle_max_retries": (int, 3, "deployment-handle resubmissions after replica death before the call fails"),
    "serve_control_loop_interval_s": (float, 0.25, "serve controller reconcile interval"),
    "serve_router_cache_ttl_s": (float, 2.0, "deployment-handle routing-table refresh TTL (scale-ups become visible to existing handles within this window)"),
    "llm_multi_step": (int, 8, "decode tokens per engine dispatch when every active slot is greedy (on-device argmax chunks; 1 disables)"),
    "llm_prefill_bucket_min": (int, 16, "smallest prompt padding bucket for compiled prefill programs"),
    "llm_kv_block_size": (int, 16, "token rows per paged KV prefix-cache block; prefixes are reused at whole-block granularity (docs/kvcache.md)"),
    "llm_prefix_cache_bytes": (int, 32 << 20, "host bytes for the per-engine paged KV prefix cache; repeated prompt prefixes attach cached KV and prefill suffix-only (0 disables)"),
    "llm_kv_device_bytes": (int, 0, "device-resident hot-tier byte budget of the tiered prefix cache (docs/kvcache.md): the hottest blocks keep a device copy (mesh-sharded on TP engines) so warm attaches skip the host->device leg entirely; LRU device copies drop back to the host tier past the budget (0 disables the hot tier)"),
    "llm_kv_spill_dir": (str, "", "local directory for the disk spill tier of the tiered prefix cache (docs/kvcache.md): host-tier eviction spills blocks here (atomic tmp+fsync+rename commits — torn spills are invisible) instead of discarding them, and later lookups promote spilled chains back through the host pool (empty disables spilling)"),
    "llm_kv_spill_bytes": (int, 256 << 20, "byte cap on the disk spill tier; the oldest committed spill files are unlinked past it (0 = unbounded)"),
    "llm_kv_remote_fetch": (bool, True, "cluster-wide prefix plane (docs/kvcache.md): when the DP router's fingerprints say another replica computed a request's prefix but the request must route elsewhere, the chosen replica fetches the prefix cross-node over a DeviceChannel stream instead of recomputing it"),
    "llm_max_queue_depth": (int, 256, "engine admission queue cap; submits beyond it raise EngineOverloadedError instead of growing memory unboundedly (0 = unbounded)"),
    "llm_max_jit_programs": (int, 64, "per-engine cap on cached jitted programs (prefill/attach/spec bucket variants); past it the oldest program is evicted so an adversarial prompt-length mix can't grow compilation memory unboundedly (0 = unbounded)"),
    "llm_router_fingerprint_blocks": (int, 8, "prefix blocks hashed into the DP router's per-replica fingerprints for cache-aware routing"),
    "llm_sched_token_budget": (int, 256, "per-iteration scheduler token budget (docs/scheduler.md): decode and spec-verify tokens are reserved first, the remainder is granted to bucketed prefill chunks, so a long prefill cannot stall in-flight decodes for more than one budget of compute (0 = unbudgeted whole-prompt prefill)"),
    "llm_spec_ngram": (int, 3, "trailing n-gram length the ngram/REST speculative draft matches against the slot history and the cross-request continuation store"),
    "llm_spec_store_entries": (int, 4096, "bounded LRU entries in the ngram draft's cross-request continuation store; repeated greedy traffic re-proposes earlier completions from it (0 disables the shared store, leaving prompt-lookup only)"),
    "llm_adapter_cache_bytes": (int, 0, "HBM byte budget for the engine's pageable LoRA adapter table (docs/multitenancy.md): device slots = budget // per-adapter slot bytes, registered-but-evicted adapters stay host-side and page back in on demand (one device_put per page-in, LRU eviction of unpinned adapters); 0 sizes the table to lora_config max_loras (every registered adapter resident, the pre-paging shape)"),
    "llm_tenant_max_queue_depth": (int, 64, "per-tenant admission quota on the engine's weighted-fair queues: one tenant's overload raises EngineOverloadedError for THAT tenant while other tenants keep flowing (0 disables the per-tenant quota, leaving only the global llm_max_queue_depth cap)"),
    "llm_flight_records": (int, 256, "finished request records kept in each engine's flight-recorder ring (docs/observability.md): per-request phase events (queue/prefill-chunk/verify/decode/adapter/PD) recorded host-side off the dispatch path, flushed to metrics and trace spans only from stats()/report paths (0 disables the recorder)"),
    "llm_slo_ttft_s": (float, 0.5, "time-to-first-token SLO: completions whose TTFT exceeds this count as SLO breaches in the llm_slo_* burn/goodput counters (docs/observability.md)"),
    "llm_slo_tpot_s": (float, 0.05, "per-request mean inter-token-latency SLO: completions whose mean TPOT exceeds this count as SLO breaches (docs/observability.md)"),
    "llm_slo_error_budget": (float, 0.01, "allowed SLO breach fraction: llm_slo_burn_rate = windowed breach fraction / this budget, so burn > 1 means the error budget is being exhausted"),
    "llm_guided_max_states": (int, 4096, "DFA state cap for guided-decoding constraint compilation (docs/generation.md): a regex/schema/grammar whose subset construction exceeds this raises at compile time instead of growing compile memory unboundedly"),
    "llm_guided_max_depth": (int, 8, "bounded-recursion inlining rounds for grammar constraints: a <rule> reference surviving this many substitution rounds is unbounded CFG recursion and fails compilation (it cannot lower to a finite token-mask DFA)"),
    "llm_guided_cache_entries": (int, 32, "compiled-constraint LRU entries per server/tokenizer (docs/generation.md): repeated guided requests against the same schema skip DFA construction and reuse the cached per-state token masks"),
    "llm_stream_buffer_tokens": (int, 4096, "undelivered buffered tokens a TokenStream holds before cancelling its own request (docs/generation.md): a stalled streaming consumer sheds the slot instead of growing host memory without bound (0 disables the guard)"),
    "llm_batch_tenant": (str, "batch", "the WFQ tenant name offline batch traffic (data/llm.py EngineStage) is admitted under on live serve replicas (docs/generation.md): this tenant is pinned to llm_batch_weight and excluded from autopilot SLO signals, so online traffic always preempts batch and batch pressure never scales the fleet"),
    "llm_batch_weight": (float, 1e-6, "the floor WFQ weight pinned on the llm_batch_tenant queues: batch admissions take enormous stride-pass steps, so they only drain when no online tenant has queued work (set_tenant_weight cannot raise it — the floor is structural)"),
    "llm_batch_max_inflight": (int, 16, "bounded in-flight window for EngineStage batch submission: at most this many rows ride the engine/serve queues concurrently, so one batch block cannot flood an online replica's admission queue (0 = submit the whole block up front)"),
    # --- serve autopilot (docs/autoscale.md) ---
    "serve_autopilot": (bool, False, "closed-loop SLO autopilot inside the serve controller: scales DP replicas on burn-rate/queue pressure, nudges per-tenant WFQ weights toward SLO attainment, and rebalances the prefill:decode split (docs/autoscale.md)"),
    "serve_autopilot_interval_s": (float, 1.0, "autopilot control-law evaluation interval; signals are probed and laws evaluated at most this often inside the controller's control loop"),
    "serve_autopilot_min_replicas": (int, 1, "default replica floor for autopilot-managed deployments without an AutoscalingConfig (0 enables scale-to-zero; a deployment's own AutoscalingConfig bounds win when set)"),
    "serve_autopilot_max_replicas": (int, 8, "default replica ceiling for autopilot-managed deployments without an AutoscalingConfig"),
    "serve_autopilot_burn_high": (float, 1.0, "scale-up pressure threshold on llm_slo_burn_rate: burn >= this (budget exhausting) counts a hot tick"),
    "serve_autopilot_queue_high": (float, 8.0, "scale-up pressure threshold on mean queued requests per replica: queue/replica >= this counts a hot tick even when burn is still low (queue growth leads breach by a window)"),
    "serve_autopilot_sustain_ticks": (int, 2, "consecutive autopilot ticks a pressure (or idle) condition must hold before any action fires — the hysteresis that keeps a one-tick spike from scaling"),
    "serve_autopilot_upscale_cooldown_s": (float, 5.0, "minimum seconds between scale-up actions on one deployment (persisted: a restarted controller honors the remaining cooldown instead of flapping)"),
    "serve_autopilot_downscale_cooldown_s": (float, 30.0, "minimum seconds between scale-down actions on one deployment; deliberately long so capacity added for a surge is not shed on the first quiet window"),
    "serve_autopilot_cold_start_guard_s": (float, 60.0, "after a scale-to-zero wake (first request found zero replicas), the deployment may not scale back to zero for this long — the cold-start guard against wake/retire thrash"),
    "serve_autopilot_weight_step": (float, 0.25, "max fractional change to one tenant's WFQ weight per autopilot action (bounded step: weight moves by at most this fraction per decision)"),
    "serve_autopilot_weight_floor": (float, 0.25, "WFQ weight floor no tenant is nudged below — the starvation guard: a compliant tenant keeps at least this share-weight while a breaching tenant is boosted"),
    "serve_autopilot_weight_max": (float, 8.0, "WFQ weight ceiling the autopilot will not boost a breaching tenant past"),
    "serve_autopilot_weight_deadband": (float, 0.25, "burn-rate deadband around 1.0 inside which tenant weights are left alone (attainment hysteresis: only clearly-breaching or clearly-healthy tenants move)"),
    "serve_autopilot_pd_ratio_tol": (float, 2.0, "prefill:decode rebalance trigger: when TTFT pressure exceeds TPOT pressure by this factor (or vice versa), one replica shifts between the prefill and decode pools"),
    "serve_autopilot_decision_log": (int, 256, "bounded entries in the autopilot decision log surfaced through serve_stats()/`ray_tpu status` (rule fired, signal values, action taken)"),
    "metrics_series_ttl_s": (float, 300.0, "collect-time TTL for cluster metric series: entries whose reporting worker is gone (not the driver, no live actor) AND whose last flush is older than this are pruned from the GCS KV metrics namespace instead of living forever"),
    "tune_checkpoint_period_s": (float, 1.0, "experiment-state snapshot interval for Tuner.restore"),
    "data_block_target_bytes": (int, 128 * 1024 * 1024, "target block size for ray_tpu.data"),
    "data_output_queue_size": (int, 8, "blocks buffered between the streaming executor and the consuming iterator (backpressure depth)"),
    "data_max_inflight_factor": (int, 2, "per-operator in-flight task cap as a multiple of its actor/worker pool size"),
    "tune_trial_poll_timeout_s": (float, 60.0, "driver-side timeout for polling a trial actor's buffered results"),
}


_MISSING = object()


def _unknown_flag_message(name: str) -> str:
    """KeyError text for a flag absent from _DEFS, with a did-you-mean
    suggestion so a typo'd read points straight at the intended flag
    instead of silently running on a default (raylint RL1004 catches the
    static cases; this is the runtime complement)."""
    import difflib

    close = difflib.get_close_matches(name, list(_DEFS), n=1)
    hint = f" — did you mean {close[0]!r}?" if close else ""
    return f"unknown config flag {name!r}{hint}"


class _Config:
    """Singleton flag table with env overrides (RAY_TPU_<NAME>=value)."""

    def __init__(self):
        self._cache: dict[str, Any] = {}

    def __getattr__(self, name: str):
        if name.startswith("_"):
            # Dunder/underscore probes (hasattr, copy, pickle protocols)
            # must keep raising AttributeError, never KeyError.
            raise AttributeError(name)
        cache = self.__dict__["_cache"]
        if name in cache:
            return cache[name]
        if name not in _DEFS:
            raise KeyError(_unknown_flag_message(name))
        typ, default, _doc = _DEFS[name]
        raw = os.environ.get(_ENV_PREFIX + name.upper())
        if raw is None:
            value = default
        elif typ is bool:
            value = raw.lower() in ("1", "true", "yes", "on")
        elif typ in (dict, list):
            value = json.loads(raw)
        else:
            value = typ(raw)
        cache[name] = value
        return value

    def get(self, name: str, default: Any = _MISSING):
        """Dynamic read with the same typo defense as attribute access:
        unknown flags raise KeyError with a did-you-mean suggestion unless
        an explicit default is supplied."""
        if name in _DEFS:
            return getattr(self, name)
        if default is not _MISSING:
            return default
        raise KeyError(_unknown_flag_message(name))

    def _reset(self):
        self.__dict__["_cache"] = {}

    def _all(self) -> dict[str, Any]:
        return {name: getattr(self, name) for name in _DEFS}


CONFIG = _Config()


_LOOPBACK = ("127.0.0.1", "localhost", "::1", "0.0.0.0")


def get_node_ip(probe_host: str | None = None) -> str:
    """The IP this node should advertise to cluster peers.

    Resolution order (reference: `python/ray/_private/services.py`
    get_node_ip_address — UDP-connect trick, env overridable):
    1. `RAY_TPU_NODE_IP` env var, set by the autoscaler startup script or the
       operator on multi-host deployments.
    2. If the GCS (or any probe host) is non-loopback, the source IP the kernel
       picks to reach it — the interface actually routable from the cluster.
    3. Loopback, for single-host clusters and tests.
    """
    ip = os.environ.get(_ENV_PREFIX + "NODE_IP")
    if ip:
        return ip
    if probe_host and probe_host not in _LOOPBACK:
        import socket

        try:
            s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            try:
                s.connect((probe_host, 80))
                return s.getsockname()[0]
            finally:
                s.close()
        except OSError:
            # Registering loopback on a multi-host cluster makes every peer
            # dial itself for this node — degrade loudly, not silently.
            import logging

            logging.getLogger("ray_tpu").warning(
                "could not determine a routable node IP (probe host %s); "
                "falling back to 127.0.0.1 — set RAY_TPU_NODE_IP on "
                "multi-host clusters", probe_host,
            )
    return "127.0.0.1"


def bind_host_for(node_ip: str) -> str:
    """Listen host for a server whose address is advertised as `node_ip`.

    Loopback nodes stay loopback-only. Routable nodes listen on all interfaces
    rather than `node_ip` alone: local peers (workers, drivers, the raylet's
    own GCS connection) dial 127.0.0.1 while remote peers dial the advertised
    IP, and both must reach the same socket. The RPC plane is unauthenticated —
    same trust model as the reference's gRPC servers, which also listen
    beyond loopback inside the cluster's network boundary."""
    return "127.0.0.1" if node_ip in _LOOPBACK else "0.0.0.0"
