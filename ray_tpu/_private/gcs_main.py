"""GCS server process entry point.

Design parity: reference `src/ray/gcs/gcs_server_main.cc:51` — the cluster control
plane runs as its own process so it can crash and restart independently of any raylet;
with a persistent store (--store-dir) a restarted GCS re-learns cluster state from
storage plus raylet re-registration (reference `gcs_init_data.cc`).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import signal
import sys

from ray_tpu._private import rpc
from ray_tpu._private.config import bind_host_for, get_node_ip
from ray_tpu._private.gcs import GcsService
from ray_tpu._private.gcs_store import FileStoreClient, InMemoryStoreClient


async def amain(args):
    store = FileStoreClient(args.store_dir) if args.store_dir else InMemoryStoreClient()
    gcs = GcsService(store=store)
    server = rpc.RpcServer(lambda conn: gcs)
    # Raylets on other hosts must be able to register: listen beyond loopback
    # whenever this node advertises a routable IP (RAY_TPU_NODE_IP).
    await server.start(host=bind_host_for(get_node_ip()), port=args.port)
    gcs.start_background()

    if args.ready_file:
        tmp = args.ready_file + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"gcs_port": server.port, "pid": os.getpid()}, f)
        os.replace(tmp, args.ready_file)

    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for s in (signal.SIGTERM, signal.SIGINT):
        loop.add_signal_handler(s, stop.set)
    await stop.wait()
    await server.close()
    store.close()


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--store-dir", default="")
    p.add_argument("--ready-file", default="")
    args = p.parse_args()
    asyncio.run(amain(args))


if __name__ == "__main__":
    sys.exit(main())
