"""GCS server process entry point.

Design parity: reference `src/ray/gcs/gcs_server_main.cc:51` — the cluster control
plane runs as its own process so it can crash and restart independently of any raylet;
with a persistent store (--store-dir) a restarted GCS re-learns cluster state from
storage plus raylet re-registration (reference `gcs_init_data.cc`).

With `--peers` naming more than one candidate this process instead runs one
replicated-GCS head candidate (`gcs_replication.GcsCandidate`): a warm standby
that replays the primary's log and serves clients only while it holds the
quorum lease (docs/fault_tolerance.md). A single-candidate invocation is the
classic single GcsService, unchanged.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import signal
import sys

from ray_tpu._private import rpc
from ray_tpu._private.config import bind_host_for, get_node_ip
from ray_tpu._private.gcs import GcsService
from ray_tpu._private.gcs_replication import GcsCandidate, parse_addrs
from ray_tpu._private.gcs_store import FileStoreClient, InMemoryStoreClient


def _write_ready(path: str, port: int):
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"gcs_port": port, "pid": os.getpid()}, f)
    os.replace(tmp, path)


async def amain(args):
    peers = parse_addrs(args.peers)
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for s in (signal.SIGTERM, signal.SIGINT):
        loop.add_signal_handler(s, stop.set)

    if len(peers) > 1:
        if not args.store_dir:
            raise SystemExit("replicated GCS candidates require --store-dir")
        cand = GcsCandidate(args.candidate_id, peers, args.store_dir)
        server = rpc.RpcServer(lambda conn: cand.facade(conn))
        # Raylets on other hosts must be able to register: listen beyond
        # loopback whenever this node advertises a routable IP.
        await server.start(host=bind_host_for(get_node_ip()), port=args.port)
        cand.server = server
        cand.start_background()
        if args.ready_file:
            _write_ready(args.ready_file, server.port)
        await stop.wait()
        await cand.shutdown()
        return

    store = FileStoreClient(args.store_dir) if args.store_dir else InMemoryStoreClient()
    gcs = GcsService(store=store)
    server = rpc.RpcServer(lambda conn: gcs)
    await server.start(host=bind_host_for(get_node_ip()), port=args.port)
    gcs.start_background()

    if args.ready_file:
        _write_ready(args.ready_file, server.port)

    await stop.wait()
    await server.close()
    store.close()


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--store-dir", default="")
    p.add_argument("--ready-file", default="")
    p.add_argument("--candidate-id", type=int, default=0)
    p.add_argument("--peers", default="",
                   help="comma host:port list of ALL candidates (self included); "
                        "more than one entry enables quorum-HA candidate mode")
    args = p.parse_args()
    asyncio.run(amain(args))


if __name__ == "__main__":
    sys.exit(main())
