"""ResultGrid: the outcome of a Tuner.fit().

Parity: reference `python/ray/tune/result_grid.py` — indexable results with
get_best_result, get_dataframe, and per-trial metrics/config/checkpoint access.
"""

from __future__ import annotations

from typing import List, Optional

from ray_tpu.train.config import Result


class ResultGrid:
    def __init__(self, results: List[Result], *, default_metric=None, default_mode=None):
        self._results = results
        self._metric = default_metric
        self._mode = default_mode

    def __len__(self):
        return len(self._results)

    def __getitem__(self, i: int) -> Result:
        return self._results[i]

    def __iter__(self):
        return iter(self._results)

    @property
    def errors(self):
        return [r.error for r in self._results if r.error is not None]

    @property
    def num_errors(self) -> int:
        return len(self.errors)

    def get_best_result(
        self, metric: Optional[str] = None, mode: Optional[str] = None
    ) -> Result:
        metric = metric or self._metric
        mode = mode or self._mode or "max"
        if metric is None:
            raise ValueError("get_best_result requires a metric")
        candidates = [
            r for r in self._results if r.metrics and metric in r.metrics
        ]
        if not candidates:
            raise RuntimeError(f"no trial reported metric {metric!r}")
        key = lambda r: r.metrics[metric]  # noqa: E731
        return max(candidates, key=key) if mode == "max" else min(candidates, key=key)

    def get_dataframe(self):
        import pandas as pd

        rows = []
        for r in self._results:
            row = dict(r.metrics or {})
            for k, v in (r.config or {}).items():
                row[f"config/{k}"] = v
            rows.append(row)
        return pd.DataFrame(rows)
