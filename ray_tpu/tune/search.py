"""Search spaces and search algorithms.

Design parity: reference `python/ray/tune/search/` — sample-space primitives
(uniform/loguniform/choice/randint/grid_search), the `Searcher` SPI, and
`BasicVariantGenerator` (grid cross-product x num_samples random sampling,
`search/basic_variant.py`).
"""

from __future__ import annotations

import itertools
import random
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional


class Domain:
    """A samplable hyperparameter domain."""

    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


@dataclass
class Uniform(Domain):
    low: float
    high: float

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


@dataclass
class LogUniform(Domain):
    low: float
    high: float

    def sample(self, rng):
        import math

        return math.exp(rng.uniform(math.log(self.low), math.log(self.high)))


@dataclass
class Randint(Domain):
    low: int
    high: int

    def sample(self, rng):
        return rng.randrange(self.low, self.high)


@dataclass
class Choice(Domain):
    options: List[Any]

    def sample(self, rng):
        return rng.choice(self.options)


@dataclass
class SampleFrom(Domain):
    fn: Callable[[dict], Any]

    def sample(self, rng):
        return self.fn


def uniform(low: float, high: float) -> Uniform:
    return Uniform(low, high)


def loguniform(low: float, high: float) -> LogUniform:
    return LogUniform(low, high)


def randint(low: int, high: int) -> Randint:
    return Randint(low, high)


def choice(options: List[Any]) -> Choice:
    return Choice(list(options))


def sample_from(fn: Callable) -> SampleFrom:
    return SampleFrom(fn)


def grid_search(values: List[Any]) -> Dict[str, List[Any]]:
    return {"grid_search": list(values)}


def _is_grid(v) -> bool:
    return isinstance(v, dict) and set(v.keys()) == {"grid_search"}


class Searcher:
    """SPI parity: reference `python/ray/tune/search/searcher.py`."""

    def suggest(self, trial_id: str) -> Optional[dict]:
        raise NotImplementedError

    def on_trial_complete(self, trial_id: str, result: Optional[dict], error: bool = False):
        pass


class BasicVariantGenerator(Searcher):
    """Grid cross-product x num_samples; distributions sampled per variant."""

    def __init__(self, param_space: dict, num_samples: int = 1, seed: Optional[int] = None):
        self._space = param_space
        self._num_samples = num_samples
        self._rng = random.Random(seed)
        self._variants = self._expand()
        self._idx = 0

    def _expand(self) -> List[dict]:
        grid_keys: List[str] = []
        grid_vals: List[List[Any]] = []

        def find_grids(space: dict, prefix=()):
            for k, v in space.items():
                if _is_grid(v):
                    grid_keys.append((*prefix, k))
                    grid_vals.append(v["grid_search"])
                elif isinstance(v, dict) and not _is_grid(v):
                    find_grids(v, (*prefix, k))

        find_grids(self._space)
        combos = list(itertools.product(*grid_vals)) if grid_vals else [()]
        variants = []
        for _ in range(self._num_samples):
            for combo in combos:
                cfg = self._materialize(self._space)
                for key_path, value in zip(grid_keys, combo):
                    node = cfg
                    for k in key_path[:-1]:
                        node = node[k]
                    node[key_path[-1]] = value
                variants.append(cfg)
        return variants

    def _materialize(self, space: dict) -> dict:
        out = {}
        deferred = []
        for k, v in space.items():
            if _is_grid(v):
                out[k] = None  # filled by grid combo
            elif isinstance(v, SampleFrom):
                deferred.append((k, v))
            elif isinstance(v, Domain):
                out[k] = v.sample(self._rng)
            elif isinstance(v, dict):
                out[k] = self._materialize(v)
            else:
                out[k] = v
        # conditional params see the rest of the config
        for k, v in deferred:
            out[k] = v.fn(out)
        return out

    @property
    def total_variants(self) -> int:
        return len(self._variants)

    def suggest(self, trial_id: str) -> Optional[dict]:
        if self._idx >= len(self._variants):
            return None
        cfg = self._variants[self._idx]
        self._idx += 1
        return cfg


class TPESearch(Searcher):
    """Tree-structured Parzen Estimator search, dependency-free.

    Parity target: the reference's search-algorithm integrations
    (python/ray/tune/search/hyperopt/hyperopt_search.py wraps hyperopt's TPE;
    optuna's default sampler is also TPE). This native implementation covers
    the same Domain space (uniform/loguniform/randint/choice) so adaptive
    search works on air-gapped TPU pods; OptunaSearch/HyperOptSearch below
    adapt the external libraries when they are installed.

    Algorithm: after n_initial random trials, completed trials split into the
    top-gamma "good" set and the rest; numeric params draw candidates from a
    Gaussian around good observations (per-observation kernels, Parzen style)
    and keep the candidate maximizing the good/bad density ratio; categorical
    params sample from good-set counts with add-one smoothing.
    """

    def __init__(self, space: dict, *, metric: str, mode: str = "max",
                 n_initial: int = 5, gamma: float = 0.25,
                 n_candidates: int = 24, seed: Optional[int] = None):
        if mode not in ("min", "max"):
            raise ValueError("mode must be 'min' or 'max'")
        self._space = space
        self._metric = metric
        self._mode = mode
        self._n_initial = n_initial
        self._gamma = gamma
        self._n_candidates = n_candidates
        self._rng = random.Random(seed)
        self._observed: List[tuple] = []  # (flat_config, score)
        self._suggested: Dict[str, dict] = {}

    # -- flat param helpers -------------------------------------------------
    def _flatten(self, space, prefix=()):
        for k, v in space.items():
            if isinstance(v, dict) and not _is_grid(v):
                yield from self._flatten(v, (*prefix, k))
            else:
                yield (*prefix, k), v

    @staticmethod
    def _set_path(cfg, path, value):
        node = cfg
        for k in path[:-1]:
            node = node.setdefault(k, {})
        node[path[-1]] = value

    def _random_config(self) -> dict:
        cfg: dict = {}
        deferred = []
        for path, v in self._flatten(self._space):
            if isinstance(v, SampleFrom):
                deferred.append((path, v))
            elif isinstance(v, Domain):
                self._set_path(cfg, path, v.sample(self._rng))
            elif _is_grid(v):
                self._set_path(cfg, path, self._rng.choice(v["grid_search"]))
            else:
                self._set_path(cfg, path, v)
        for path, v in deferred:
            self._set_path(cfg, path, v.fn(cfg))
        return cfg

    def _sample_param(self, path, domain, good_vals, bad_vals):
        import math as _math

        if isinstance(domain, Choice):
            counts = {repr(o): 1.0 for o in domain.options}  # add-one smoothing
            for v in good_vals:
                counts[repr(v)] = counts.get(repr(v), 1.0) + 1.0
            total = sum(counts.values())
            r = self._rng.random() * total
            acc = 0.0
            for opt in domain.options:
                acc += counts[repr(opt)]
                if r <= acc:
                    return opt
            return domain.options[-1]
        if not isinstance(domain, (Uniform, LogUniform, Randint)):
            return domain.sample(self._rng)
        log = isinstance(domain, LogUniform)
        lo, hi = (domain.low, domain.high)
        tlo, thi = (_math.log(lo), _math.log(hi)) if log else (lo, hi)
        xform = _math.log if log else (lambda x: x)
        good = [xform(v) for v in good_vals] or [(tlo + thi) / 2]
        bad = [xform(v) for v in bad_vals]
        width = (thi - tlo) or 1.0
        bw = max(width / 6.0 / max(1, len(good)) ** 0.5, 1e-9)

        def density(x, pts):
            if not pts:
                return 1.0 / width
            return sum(
                _math.exp(-0.5 * ((x - p) / bw) ** 2) for p in pts
            ) / (len(pts) * bw)

        best, best_score = None, -float("inf")
        for _ in range(self._n_candidates):
            center = self._rng.choice(good)
            x = min(max(self._rng.gauss(center, bw * 2), tlo), thi)
            score = density(x, good) / max(density(x, bad), 1e-12)
            if score > best_score:
                best, best_score = x, score
        value = _math.exp(best) if log else best
        if isinstance(domain, Randint):
            value = min(max(int(round(value)), domain.low), domain.high - 1)
        return value

    # -- Searcher SPI -------------------------------------------------------
    def suggest(self, trial_id: str) -> Optional[dict]:
        if len(self._observed) < self._n_initial:
            cfg = self._random_config()
        else:
            ranked = sorted(
                self._observed, key=lambda t: t[1],
                reverse=(self._mode == "max"),
            )
            n_good = max(1, int(len(ranked) * self._gamma))
            good, bad = ranked[:n_good], ranked[n_good:]
            cfg = {}
            deferred = []
            for path, v in self._flatten(self._space):
                if isinstance(v, SampleFrom):
                    deferred.append((path, v))
                elif isinstance(v, Domain):
                    gv = [g[0][path] for g in good if path in g[0]]
                    bv = [b[0][path] for b in bad if path in b[0]]
                    self._set_path(cfg, path, self._sample_param(path, v, gv, bv))
                elif _is_grid(v):
                    self._set_path(cfg, path, self._rng.choice(v["grid_search"]))
                else:
                    self._set_path(cfg, path, v)
            for path, v in deferred:
                self._set_path(cfg, path, v.fn(cfg))
        flat = {p: self._get_path(cfg, p) for p, _ in self._flatten(self._space)}
        self._suggested[trial_id] = flat
        return cfg

    @staticmethod
    def _get_path(cfg, path):
        node = cfg
        for k in path:
            node = node[k]
        return node

    def on_trial_complete(self, trial_id: str, result: Optional[dict],
                          error: bool = False):
        flat = self._suggested.pop(trial_id, None)
        if flat is None or error or not result or self._metric not in result:
            return
        self._observed.append((flat, float(result[self._metric])))


class TuneBOHB(TPESearch):
    """The BOHB model searcher (reference: python/ray/tune/search/bohb/
    bohb_search.py wraps hpbandster's KDE): a Parzen-density model that also
    learns from PARTIAL-budget rung results fed by HyperBandForBOHB, so
    suggestions improve before any trial finishes its full budget. Pair with
    `HyperBandForBOHB` as the scheduler."""

    def on_rung_result(self, trial_id: str, config: dict, metric: float):
        flat = self._suggested.get(trial_id)
        if flat is None:
            return
        # Latest (largest-budget) observation per live trial; completion
        # supersedes it (BOHB's per-budget models collapsed to freshest-wins).
        self._rung_obs = getattr(self, "_rung_obs", {})
        self._rung_obs[trial_id] = (dict(flat), float(metric))

    def on_trial_complete(self, trial_id: str, result: Optional[dict],
                          error: bool = False):
        super().on_trial_complete(trial_id, result, error)
        self._rung_obs = getattr(self, "_rung_obs", {})
        self._rung_obs.pop(trial_id, None)

    def suggest(self, trial_id: str) -> Optional[dict]:
        # The model sees completed observations PLUS the freshest rung result
        # of every live trial for this one proposal.
        saved = self._observed
        try:
            self._observed = saved + list(
                getattr(self, "_rung_obs", {}).values()
            )
            return super().suggest(trial_id)
        finally:
            self._observed = saved


class HyperOptSearch(Searcher):
    """Adapter over hyperopt's TPE (reference:
    python/ray/tune/search/hyperopt/hyperopt_search.py). Requires
    `pip install hyperopt`; air-gapped pods use the dependency-free
    TPESearch, which implements the same algorithm natively."""

    def __init__(self, space: dict, *, metric: str, mode: str = "max",
                 n_initial_points: int = 20, seed: Optional[int] = None):
        try:
            import hyperopt  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "HyperOptSearch requires `pip install hyperopt`; on "
                "air-gapped pods use the dependency-free TPESearch instead"
            ) from e
        import numpy as np
        from hyperopt import hp

        self._hyperopt = hyperopt
        self._metric = metric
        self._mode = mode
        hp_space = {}
        for key, v in space.items():
            if isinstance(v, Uniform):
                hp_space[key] = hp.uniform(key, v.low, v.high)
            elif isinstance(v, LogUniform):
                import math

                hp_space[key] = hp.loguniform(key, math.log(v.low),
                                              math.log(v.high))
            elif isinstance(v, Randint):
                hp_space[key] = hp.randint(key, v.low, v.high)
            elif isinstance(v, Choice):
                hp_space[key] = hp.choice(key, v.options)
            elif isinstance(v, (dict, SampleFrom)) or _is_grid(v):
                raise ValueError(
                    f"HyperOptSearch supports flat Domain spaces; {key!r} is "
                    f"{type(v).__name__} — use TPESearch or flatten the space"
                )
            else:
                hp_space[key] = v
        self._space = space
        self._domain = hyperopt.Domain(lambda c: 0, hp_space)
        self._trials = hyperopt.Trials()
        self._rng = np.random.default_rng(seed)
        self._n_initial = n_initial_points
        self._live: Dict[str, int] = {}

    def suggest(self, trial_id: str) -> Optional[dict]:
        import numpy as np

        tid = len(self._trials.trials)
        if tid < self._n_initial:
            algo = self._hyperopt.rand.suggest
        else:
            algo = self._hyperopt.tpe.suggest
        seed_int = int(self._rng.integers(2**31 - 1))
        new = algo(
            [tid], self._domain, self._trials, seed_int
        )
        self._trials.insert_trial_docs(new)
        self._trials.refresh()
        vals = {k: v[0] for k, v in new[0]["misc"]["vals"].items() if v}
        cfg = {}
        for key, v in self._space.items():
            if isinstance(v, Choice):
                cfg[key] = v.options[int(vals[key])]
            elif isinstance(v, Randint):
                cfg[key] = int(vals[key])
            elif isinstance(v, (Uniform, LogUniform)):
                cfg[key] = float(vals[key])
            else:
                cfg[key] = v
        self._live[trial_id] = tid
        _ = np  # keep the numpy import local to adapters
        return cfg

    def on_trial_complete(self, trial_id: str, result: Optional[dict],
                          error: bool = False):
        tid = self._live.pop(trial_id, None)
        if tid is None:
            return
        doc = self._trials.trials[tid]
        if error or not result or self._metric not in result:
            doc["result"] = {"status": self._hyperopt.STATUS_FAIL}
        else:
            value = float(result[self._metric])
            loss = -value if self._mode == "max" else value
            doc["result"] = {"status": self._hyperopt.STATUS_OK, "loss": loss}
        doc["state"] = self._hyperopt.JOB_STATE_DONE
        self._trials.refresh()


class OptunaSearch(Searcher):
    """Adapter over optuna's sampler (reference:
    python/ray/tune/search/optuna/optuna_search.py). Requires `optuna`."""

    def __init__(self, space: dict, *, metric: str, mode: str = "max",
                 seed: Optional[int] = None, sampler=None):
        try:
            import optuna
        except ImportError as e:
            raise ImportError(
                "OptunaSearch requires `pip install optuna`; on air-gapped "
                "pods use the dependency-free TPESearch instead"
            ) from e
        self._optuna = optuna
        self._space = space
        self._metric = metric
        direction = "maximize" if mode == "max" else "minimize"
        self._study = optuna.create_study(
            direction=direction,
            sampler=sampler or optuna.samplers.TPESampler(seed=seed),
        )
        self._trials: Dict[str, Any] = {}

    def suggest(self, trial_id: str) -> Optional[dict]:
        ot = self._study.ask()
        cfg = {}
        for key, v in self._space.items():
            if isinstance(v, Uniform):
                cfg[key] = ot.suggest_float(key, v.low, v.high)
            elif isinstance(v, LogUniform):
                cfg[key] = ot.suggest_float(key, v.low, v.high, log=True)
            elif isinstance(v, Randint):
                cfg[key] = ot.suggest_int(key, v.low, v.high - 1)
            elif isinstance(v, Choice):
                cfg[key] = ot.suggest_categorical(key, v.options)
            elif isinstance(v, (dict, SampleFrom)) or _is_grid(v):
                raise ValueError(
                    f"OptunaSearch supports flat Domain spaces; {key!r} is "
                    f"{type(v).__name__} — use TPESearch or flatten the space"
                )
            else:
                cfg[key] = v
        self._trials[trial_id] = ot
        return cfg

    def on_trial_complete(self, trial_id: str, result: Optional[dict],
                          error: bool = False):
        ot = self._trials.pop(trial_id, None)
        if ot is None:
            return
        if error or not result or self._metric not in result:
            self._study.tell(ot, state=self._optuna.trial.TrialState.FAIL)
        else:
            self._study.tell(ot, float(result[self._metric]))
