"""Search spaces and search algorithms.

Design parity: reference `python/ray/tune/search/` — sample-space primitives
(uniform/loguniform/choice/randint/grid_search), the `Searcher` SPI, and
`BasicVariantGenerator` (grid cross-product x num_samples random sampling,
`search/basic_variant.py`).
"""

from __future__ import annotations

import itertools
import random
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional


class Domain:
    """A samplable hyperparameter domain."""

    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


@dataclass
class Uniform(Domain):
    low: float
    high: float

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


@dataclass
class LogUniform(Domain):
    low: float
    high: float

    def sample(self, rng):
        import math

        return math.exp(rng.uniform(math.log(self.low), math.log(self.high)))


@dataclass
class Randint(Domain):
    low: int
    high: int

    def sample(self, rng):
        return rng.randrange(self.low, self.high)


@dataclass
class Choice(Domain):
    options: List[Any]

    def sample(self, rng):
        return rng.choice(self.options)


@dataclass
class SampleFrom(Domain):
    fn: Callable[[dict], Any]

    def sample(self, rng):
        return self.fn


def uniform(low: float, high: float) -> Uniform:
    return Uniform(low, high)


def loguniform(low: float, high: float) -> LogUniform:
    return LogUniform(low, high)


def randint(low: int, high: int) -> Randint:
    return Randint(low, high)


def choice(options: List[Any]) -> Choice:
    return Choice(list(options))


def sample_from(fn: Callable) -> SampleFrom:
    return SampleFrom(fn)


def grid_search(values: List[Any]) -> Dict[str, List[Any]]:
    return {"grid_search": list(values)}


def _is_grid(v) -> bool:
    return isinstance(v, dict) and set(v.keys()) == {"grid_search"}


class Searcher:
    """SPI parity: reference `python/ray/tune/search/searcher.py`."""

    def suggest(self, trial_id: str) -> Optional[dict]:
        raise NotImplementedError

    def on_trial_complete(self, trial_id: str, result: Optional[dict], error: bool = False):
        pass


class BasicVariantGenerator(Searcher):
    """Grid cross-product x num_samples; distributions sampled per variant."""

    def __init__(self, param_space: dict, num_samples: int = 1, seed: Optional[int] = None):
        self._space = param_space
        self._num_samples = num_samples
        self._rng = random.Random(seed)
        self._variants = self._expand()
        self._idx = 0

    def _expand(self) -> List[dict]:
        grid_keys: List[str] = []
        grid_vals: List[List[Any]] = []

        def find_grids(space: dict, prefix=()):
            for k, v in space.items():
                if _is_grid(v):
                    grid_keys.append((*prefix, k))
                    grid_vals.append(v["grid_search"])
                elif isinstance(v, dict) and not _is_grid(v):
                    find_grids(v, (*prefix, k))

        find_grids(self._space)
        combos = list(itertools.product(*grid_vals)) if grid_vals else [()]
        variants = []
        for _ in range(self._num_samples):
            for combo in combos:
                cfg = self._materialize(self._space)
                for key_path, value in zip(grid_keys, combo):
                    node = cfg
                    for k in key_path[:-1]:
                        node = node[k]
                    node[key_path[-1]] = value
                variants.append(cfg)
        return variants

    def _materialize(self, space: dict) -> dict:
        out = {}
        deferred = []
        for k, v in space.items():
            if _is_grid(v):
                out[k] = None  # filled by grid combo
            elif isinstance(v, SampleFrom):
                deferred.append((k, v))
            elif isinstance(v, Domain):
                out[k] = v.sample(self._rng)
            elif isinstance(v, dict):
                out[k] = self._materialize(v)
            else:
                out[k] = v
        # conditional params see the rest of the config
        for k, v in deferred:
            out[k] = v.fn(out)
        return out

    @property
    def total_variants(self) -> int:
        return len(self._variants)

    def suggest(self, trial_id: str) -> Optional[dict]:
        if self._idx >= len(self._variants):
            return None
        cfg = self._variants[self._idx]
        self._idx += 1
        return cfg
