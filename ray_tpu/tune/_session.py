"""Per-trial session: carries report()/get_checkpoint() inside a trial thread.

Parity: reference `python/ray/tune/trainable/session` semantics (the function-trainable
session). Thread-local because each trial actor runs its function on a worker thread.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Callable, Optional

from ray_tpu.train.checkpoint import Checkpoint

_local = threading.local()


@dataclass
class TuneSession:
    report_fn: Callable
    checkpoint: Optional[Checkpoint]
    trial_id: str
    trial_dir: str


def set(session: Optional[TuneSession]):  # noqa: A001
    _local.session = session


def get() -> Optional[TuneSession]:
    return getattr(_local, "session", None)
