"""ray_tpu.tune: hyperparameter tuning over the distributed runtime.

Parity: reference `python/ray/tune/__init__.py` — Tuner/TuneConfig, tune.report,
search-space primitives (uniform/loguniform/choice/randint/grid_search/sample_from),
schedulers (ASHA, PBT, median stopping), with_parameters/with_resources, ResultGrid.
A Trainer instance can be passed as the trainable (HPO over Train runs), matching the
reference's Tuner(trainer) flow.
"""

from __future__ import annotations

import functools
import os
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional

from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import Result, RunConfig
from ray_tpu.tune import _session
from ray_tpu.tune._trial_runner import ERROR, TERMINATED, Trial, TuneController
from ray_tpu.tune.result_grid import ResultGrid
from ray_tpu.tune.schedulers import (
    AsyncHyperBandScheduler,
    FIFOScheduler,
    HyperBandForBOHB,
    HyperBandScheduler,
    MedianStoppingRule,
    PopulationBasedTraining,
    TrialScheduler,
)
from ray_tpu.tune.search import (
    BasicVariantGenerator,
    Domain,
    HyperOptSearch,
    OptunaSearch,
    Searcher,
    TPESearch,
    TuneBOHB,
    choice,
    grid_search,
    loguniform,
    randint,
    sample_from,
    uniform,
)

ASHAScheduler = AsyncHyperBandScheduler


def report(metrics: dict, *, checkpoint: Optional[Checkpoint] = None):
    """Report metrics (and optionally a checkpoint) from inside a trial.

    Parity: `ray.tune.report` / `train.report` inside tune functions.
    """
    session = _session.get()
    if session is None:
        raise RuntimeError("tune.report() called outside a Tune trial")
    session.report_fn(metrics, checkpoint)


def get_checkpoint() -> Optional[Checkpoint]:
    session = _session.get()
    if session is None:
        raise RuntimeError("tune.get_checkpoint() called outside a Tune trial")
    return session.checkpoint


def get_trial_id() -> Optional[str]:
    session = _session.get()
    return session.trial_id if session else None


def get_trial_dir() -> Optional[str]:
    session = _session.get()
    return session.trial_dir if session else None


def with_parameters(fn: Callable, **params) -> Callable:
    """Bind large constant objects to a trainable. Parity: tune.with_parameters —
    the reference puts params in the object store; here the closure rides the
    function export through the store the same way."""

    @functools.wraps(fn)
    def inner(config):
        return fn(config, **params)

    return inner


def with_resources(fn: Callable, resources: Dict[str, float]) -> Callable:
    fn._tune_resources = resources
    return fn


@dataclass
class TuneConfig:
    """Parity: reference `python/ray/tune/tune_config.py`."""

    metric: Optional[str] = None
    mode: Optional[str] = None
    num_samples: int = 1
    max_concurrent_trials: Optional[int] = None
    scheduler: Optional[TrialScheduler] = None
    search_alg: Optional[Searcher] = None
    seed: Optional[int] = None
    resources_per_trial: Optional[dict] = None

    def __post_init__(self):
        if self.mode is not None and self.mode not in ("min", "max"):
            raise ValueError("mode must be 'min' or 'max'")


class Tuner:
    """Parity: reference `python/ray/tune/tuner.py` Tuner(trainable, param_space=...,
    tune_config=..., run_config=...).fit() -> ResultGrid."""

    def __init__(
        self,
        trainable,
        *,
        param_space: Optional[dict] = None,
        tune_config: Optional[TuneConfig] = None,
        run_config: Optional[RunConfig] = None,
    ):
        self._trainable = self._normalize_trainable(trainable)
        self._param_space = param_space or {}
        self._tune_config = tune_config or TuneConfig()
        self._run_config = run_config or RunConfig()
        self._restore_state: Optional[dict] = None
        self._restart_errored = False
        if self._tune_config.resources_per_trial is None:
            res = getattr(trainable, "_tune_resources", None)
            if res:
                self._tune_config.resources_per_trial = {
                    "num_cpus": res.get("CPU", res.get("num_cpus", 1)),
                    "num_tpus": res.get("TPU", res.get("num_tpus", 0)),
                }

    @staticmethod
    def _normalize_trainable(trainable):
        # A Trainer instance (has .fit and ._train_loop) → per-trial function that
        # rebuilds the trainer with the sampled train_loop_config and runs fit()
        # inside the trial actor, reporting its final metrics.
        from ray_tpu.train.data_parallel_trainer import DataParallelTrainer

        if isinstance(trainable, DataParallelTrainer):

            def trainer_fn(config, _trainer=trainable):
                import ray_tpu.tune as tune

                # Sampled hyperparams reach the train loop: either the reference's
                # nested {"train_loop_config": {...}} form, or a flat config which is
                # merged over the trainer's existing train_loop_config.
                if "train_loop_config" in config:
                    loop_cfg = config["train_loop_config"]
                else:
                    loop_cfg = {**(_trainer.train_loop_config or {}), **config}
                trainer = _trainer.with_overrides(train_loop_config=loop_cfg)
                result = trainer.fit()
                metrics = dict(result.metrics or {})
                tune.report(metrics, checkpoint=result.checkpoint)

            return trainer_fn
        if callable(trainable):
            return trainable
        raise TypeError(f"unsupported trainable: {type(trainable).__name__}")

    _TUNER_FILE = "tuner.pkl"

    def fit(self) -> ResultGrid:
        import cloudpickle

        name = self._run_config.name or f"tune_{time.strftime('%Y%m%d_%H%M%S')}"
        experiment_dir = os.path.join(self._run_config.storage_path, name)
        os.makedirs(experiment_dir, exist_ok=True)
        # Persist the tuner definition FIRST (reference: tuner.pkl written at
        # experiment start, python/ray/tune/impl/tuner_internal.py) so a killed
        # driver's experiment is restorable even before the first snapshot.
        # Written on restored fits too: a trainable override passed to
        # restore() must survive the NEXT crash/restore cycle.
        tmp = os.path.join(experiment_dir, self._TUNER_FILE + ".tmp")
        with open(tmp, "wb") as f:
            # cloudpickle throughout: configs may hold locally-defined
            # searchers/schedulers/stoppers that stdlib pickle rejects.
            cloudpickle.dump(
                {
                    "fn_blob": cloudpickle.dumps(self._trainable),
                    "param_space": self._param_space,
                    "tune_config": self._tune_config,
                    "run_config": self._run_config,
                },
                f,
            )
        os.replace(tmp, os.path.join(experiment_dir, self._TUNER_FILE))
        state_file = os.path.join(experiment_dir, TuneController._STATE_FILE)
        if self._restore_state is None and os.path.isfile(state_file):
            # Fresh run into a reused experiment name: a stale snapshot from
            # the previous experiment must not be restorable against the new
            # definition.
            os.remove(state_file)
        controller = TuneController(
            self._trainable,
            param_space=self._param_space,
            tune_config=self._tune_config,
            run_config=self._run_config,
            experiment_dir=experiment_dir,
            restoring=self._restore_state is not None,
        )
        if self._restore_state is not None:
            controller.apply_restore_state(
                self._restore_state, restart_errored=self._restart_errored
            )
        controller.run()
        results = []
        for trial in controller.trials:
            metrics = dict(trial.last_result)
            metrics["config"] = trial.config
            results.append(
                Result(
                    metrics=metrics,
                    checkpoint=trial.latest_checkpoint,
                    path=trial.local_dir,
                    error=RuntimeError(trial.error) if trial.error else None,
                )
            )
        return ResultGrid(
            results,
            default_metric=self._tune_config.metric,
            default_mode=self._tune_config.mode,
        )

    @classmethod
    def can_restore(cls, path: str) -> bool:
        """True when `path` holds a restorable experiment (reference:
        Tuner.can_restore, python/ray/tune/tuner.py)."""
        return os.path.isfile(os.path.join(path, cls._TUNER_FILE))

    @classmethod
    def restore(
        cls,
        path: str,
        trainable=None,
        *,
        restart_errored: bool = False,
    ) -> "Tuner":
        """Resume a killed/interrupted experiment from its directory
        (reference: Tuner.restore, python/ray/tune/tuner.py + the
        experiment-state snapshots of tune_controller.py:68).

        Unfinished trials resume from their latest checkpoints; finished
        trials keep their results; searcher/scheduler state (TPE
        observations, ASHA rungs) survives. `trainable` overrides the
        persisted one (pass it when the original isn't picklable across
        versions); `restart_errored=True` also reruns errored trials."""
        import pickle

        import cloudpickle

        with open(os.path.join(path, cls._TUNER_FILE), "rb") as f:
            saved = cloudpickle.load(f)
        tuner = cls.__new__(cls)
        tuner._trainable = (
            cls._normalize_trainable(trainable)
            if trainable is not None
            else cloudpickle.loads(saved["fn_blob"])
        )
        tuner._param_space = saved["param_space"]
        tuner._tune_config = saved["tune_config"]
        run_config = saved["run_config"]
        # Pin the experiment back to ITS directory, whatever storage_path the
        # restoring process has configured.
        run_config.name = os.path.basename(os.path.normpath(path))
        run_config.storage_path = os.path.dirname(os.path.normpath(path))
        tuner._run_config = run_config
        tuner._restart_errored = restart_errored
        state_file = os.path.join(path, TuneController._STATE_FILE)
        if os.path.isfile(state_file):
            with open(state_file, "rb") as f:
                tuner._restore_state = pickle.load(f)
        else:
            # Killed before the first snapshot: rerun from the definition.
            tuner._restore_state = {"trials": [], "target_samples": None}
        return tuner


__all__ = [
    "ASHAScheduler",
    "AsyncHyperBandScheduler",
    "BasicVariantGenerator",
    "Checkpoint",
    "Domain",
    "FIFOScheduler",
    "HyperBandForBOHB",
    "HyperBandScheduler",
    "HyperOptSearch",
    "MedianStoppingRule",
    "PopulationBasedTraining",
    "ResultGrid",
    "OptunaSearch",
    "Searcher",
    "TPESearch",
    "TrialScheduler",
    "TuneBOHB",
    "TuneConfig",
    "Tuner",
    "choice",
    "get_checkpoint",
    "get_trial_dir",
    "get_trial_id",
    "grid_search",
    "loguniform",
    "randint",
    "report",
    "sample_from",
    "uniform",
    "with_parameters",
    "with_resources",
]
