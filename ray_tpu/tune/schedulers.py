"""Trial schedulers: FIFO, ASHA (async successive halving), median stopping, PBT.

Design parity: reference `python/ray/tune/schedulers/` — `TrialScheduler` SPI with
on_trial_result decisions (`trial_scheduler.py`), `AsyncHyperBandScheduler`
(`async_hyperband.py` — rung milestones at grace_period * rf^k, cutoff at the top-1/rf
quantile), `MedianStoppingRule` (`median_stopping_rule.py`), and
`PopulationBasedTraining` (`pbt.py` — exploit top quantile's checkpoint + explore by
perturbing hyperparams at each perturbation interval).
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional

import numpy as np

from ray_tpu.tune.search import Domain

CONTINUE = "CONTINUE"
STOP = "STOP"
PAUSE = "PAUSE"


class TrialScheduler:
    def on_trial_result(self, controller, trial, result: dict) -> str:
        return CONTINUE

    def on_trial_complete(self, controller, trial, result: Optional[dict]):
        pass


class FIFOScheduler(TrialScheduler):
    pass


class AsyncHyperBandScheduler(TrialScheduler):
    """ASHA: stop a trial at a rung milestone if it is below the top-1/rf cutoff."""

    def __init__(
        self,
        *,
        time_attr: str = "training_iteration",
        metric: Optional[str] = None,
        mode: Optional[str] = None,
        max_t: int = 100,
        grace_period: int = 1,
        reduction_factor: float = 3,
        brackets: int = 1,
    ):
        self._time_attr = time_attr
        self.metric = metric
        self.mode = mode
        self._max_t = max_t
        self._grace = grace_period
        self._rf = reduction_factor
        # rung milestones: grace * rf^k up to max_t
        self._milestones: List[float] = []
        t = grace_period
        while t < max_t:
            self._milestones.append(t)
            t *= reduction_factor
        # recorded metric values per rung
        self._rungs: Dict[float, List[float]] = {m: [] for m in self._milestones}

    def on_trial_result(self, controller, trial, result: dict) -> str:
        t = result.get(self._time_attr)
        metric = result.get(self.metric)
        if t is None or metric is None:
            return CONTINUE
        if t >= self._max_t:
            return STOP
        score = metric if self.mode == "max" else -metric
        for m in self._milestones:
            if t >= m and m not in trial.rungs_passed:
                trial.rungs_passed.add(m)
                rung = self._rungs[m]
                rung.append(score)
                if len(rung) >= self._rf:
                    cutoff = np.quantile(rung, 1 - 1 / self._rf)
                    if score < cutoff:
                        return STOP
        return CONTINUE


class HyperBandScheduler(TrialScheduler):
    """Synchronous HyperBand (reference: python/ray/tune/schedulers/
    hyperband.py). Trials join brackets with geometrically-spaced budgets; a
    trial reaching its bracket's current rung PAUSES at the barrier, and once
    every live member reports, the top 1/eta resume with eta-times the budget
    while the rest stop. The PAUSE/resume ride the controller's
    checkpoint-resume machinery (pause_trial/unpause_trial), so promoted
    trials continue from their checkpoints rather than rerunning."""

    def __init__(self, *, time_attr: str = "training_iteration",
                 metric: Optional[str] = None, mode: Optional[str] = None,
                 max_t: int = 81, reduction_factor: float = 3):
        import math

        self._time_attr = time_attr
        self.metric = metric
        self.mode = mode
        self._max_t = max_t
        self._eta = reduction_factor
        # +eps: float log of an exact power (log(1000, 10) = 2.999...) must
        # not truncate a rung away.
        self._s_max = int(math.log(max_t, reduction_factor) + 1e-9)
        # Bracket state holds trial IDS only (snapshot/restore pickles this
        # scheduler; live Trial objects would go stale across a restore).
        self._brackets: List[dict] = []
        self._next_s = self._s_max
        self._bracket_of: Dict[str, int] = {}  # trial_id -> bracket index

    def _new_bracket(self) -> dict:
        import math

        s = self._next_s
        self._next_s = self._s_max if s == 0 else s - 1
        n = int(math.ceil((self._s_max + 1) / (s + 1) * self._eta ** s))
        r0 = self._max_t * self._eta ** (-s)
        milestones = [max(1, int(round(r0 * self._eta ** k)))
                      for k in range(s + 1)]
        return {"capacity": n, "members": [], "rung": 0,
                "milestones": milestones, "scores": {}, "done": set()}

    def on_trial_add(self, controller, trial):
        """Cohort membership forms at trial CREATION (reference:
        hyperband.py on_trial_add), so the rung barrier waits for every
        member — including ones max_concurrent hasn't started yet — instead
        of deciding on whatever partial cohort reported first."""
        if trial.trial_id in self._bracket_of:
            return  # restore: membership survived in the pickled scheduler
        if not self._brackets or len(self._brackets[-1]["members"]) >= \
                self._brackets[-1]["capacity"]:
            self._brackets.append(self._new_bracket())
        self._brackets[-1]["members"].append(trial.trial_id)
        self._bracket_of[trial.trial_id] = len(self._brackets) - 1

    def _bracket(self, trial_id) -> Optional[dict]:
        idx = self._bracket_of.get(trial_id)
        return None if idx is None else self._brackets[idx]

    def _sign(self, value: float) -> float:
        return value if self.mode == "max" else -value

    def on_trial_result(self, controller, trial, result: dict) -> str:
        t = result.get(self._time_attr)
        metric = result.get(self.metric)
        if t is None or metric is None:
            return CONTINUE
        self.on_trial_add(controller, trial)  # direct use without controller hook
        b = self._bracket(trial.trial_id)
        if b["rung"] >= len(b["milestones"]):
            return STOP
        milestone = b["milestones"][b["rung"]]
        if t < milestone:
            return CONTINUE
        b["scores"][trial.trial_id] = self._sign(float(metric))
        if b["rung"] == len(b["milestones"]) - 1:
            return STOP  # full budget spent
        return PAUSE  # barrier: promotion happens in trial_paused_hook

    def on_trial_complete(self, controller, trial, result: Optional[dict]):
        b = self._bracket(trial.trial_id)
        if b is None:
            return
        b["done"].add(trial.trial_id)
        self._maybe_promote(controller, b)

    def trial_paused_hook(self, controller, trial):
        """Controller callback right after a PAUSE lands: statuses are
        consistent now, so the rung barrier can be evaluated."""
        b = self._bracket(trial.trial_id)
        if b is not None:
            self._maybe_promote(controller, b)

    def _maybe_promote(self, controller, bracket):
        """When every live member is parked at the current rung, release the
        top 1/eta into the next rung (eta-times the budget) and stop the
        rest."""
        import math

        from ray_tpu.tune import _trial_runner as tr

        by_id = {t.trial_id: t for t in controller.trials}
        live = [
            by_id[tid] for tid in bracket["members"]
            if tid in by_id and tid not in bracket["done"]
            and by_id[tid].status not in (tr.TERMINATED, tr.ERROR)
        ]
        waiting = [
            m for m in live
            if m.trial_id in bracket["scores"] and m.status == tr.PAUSED
        ]
        if not live or len(waiting) < len(live):
            return
        keep = max(1, int(math.floor(len(waiting) / self._eta)))
        ranked = sorted(waiting, key=lambda m: bracket["scores"][m.trial_id],
                        reverse=True)
        promoted, demoted = ranked[:keep], ranked[keep:]
        bracket["rung"] += 1
        bracket["scores"] = {}
        for m in demoted:
            bracket["done"].add(m.trial_id)
            # notify_scheduler=False: the bracket bookkeeping is right here;
            # the searcher still observes the demoted outcome.
            controller.finalize_trial(m, tr.TERMINATED, notify_scheduler=False)
        for m in promoted:
            controller.unpause_trial(m)


class HyperBandForBOHB(HyperBandScheduler):
    """HyperBand whose rung results feed the searcher's model (reference:
    python/ray/tune/schedulers/hb_bohb.py): BOHB couples the bandit budget
    allocation with a density-model searcher, so configurations proposed later
    benefit from partial-budget observations, not just completed trials."""

    def on_trial_result(self, controller, trial, result: dict) -> str:
        decision = super().on_trial_result(controller, trial, result)
        metric = result.get(self.metric)
        searcher = getattr(controller, "_searcher", None)
        if metric is not None and hasattr(searcher, "on_rung_result"):
            searcher.on_rung_result(trial.trial_id, trial.config,
                                    float(metric))
        return decision


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose best result so far is worse than the median of running means."""

    def __init__(
        self,
        *,
        time_attr: str = "training_iteration",
        metric: Optional[str] = None,
        mode: Optional[str] = None,
        grace_period: int = 1,
        min_samples_required: int = 3,
    ):
        self._time_attr = time_attr
        self.metric = metric
        self.mode = mode
        self._grace = grace_period
        self._min_samples = min_samples_required
        self._means: Dict[str, float] = {}

    def on_trial_result(self, controller, trial, result: dict) -> str:
        t = result.get(self._time_attr, 0)
        metric = result.get(self.metric)
        if metric is None:
            return CONTINUE
        sign = 1 if self.mode == "max" else -1
        scores = [sign * r[self.metric] for r in trial.results if self.metric in r]
        self._means[trial.trial_id] = float(np.mean(scores))
        if t < self._grace or len(self._means) < self._min_samples:
            return CONTINUE
        median = float(np.median(list(self._means.values())))
        if max(scores) < median:
            return STOP
        return CONTINUE


class PopulationBasedTraining(TrialScheduler):
    """PBT: at each perturbation interval, bottom-quantile trials exploit a top-quantile
    trial's checkpoint+config and explore by perturbing hyperparameters."""

    def __init__(
        self,
        *,
        time_attr: str = "training_iteration",
        metric: Optional[str] = None,
        mode: Optional[str] = None,
        perturbation_interval: int = 5,
        hyperparam_mutations: Optional[Dict[str, object]] = None,
        quantile_fraction: float = 0.25,
        resample_probability: float = 0.25,
        seed: Optional[int] = None,
    ):
        self._time_attr = time_attr
        self.metric = metric
        self.mode = mode
        self._interval = perturbation_interval
        self._mutations = hyperparam_mutations or {}
        self._quantile = quantile_fraction
        self._resample_prob = resample_probability
        self._rng = random.Random(seed)

    def _score(self, trial) -> Optional[float]:
        if not trial.last_result or self.metric not in trial.last_result:
            return None
        v = trial.last_result[self.metric]
        return v if self.mode == "max" else -v

    def explore(self, config: dict) -> dict:
        out = dict(config)
        for key, mutation in self._mutations.items():
            if self._rng.random() < self._resample_prob or key not in out:
                if isinstance(mutation, Domain):
                    out[key] = mutation.sample(self._rng)
                elif isinstance(mutation, list):
                    out[key] = self._rng.choice(mutation)
                elif callable(mutation):
                    out[key] = mutation()
            else:
                cur = out[key]
                if isinstance(cur, (int, float)) and not isinstance(cur, bool):
                    factor = self._rng.choice([0.8, 1.2])
                    out[key] = type(cur)(cur * factor) if isinstance(cur, float) else max(
                        1, int(cur * factor)
                    )
                elif isinstance(mutation, list):
                    out[key] = self._rng.choice(mutation)
        return out

    def on_trial_result(self, controller, trial, result: dict) -> str:
        t = result.get(self._time_attr, 0)
        if t - trial.last_perturbation_t < self._interval:
            return CONTINUE
        trial.last_perturbation_t = t
        # Rank current population.
        scored = [
            (self._score(other), other)
            for other in controller.trials
            if self._score(other) is not None
        ]
        if len(scored) < 2:
            return CONTINUE
        scored.sort(key=lambda x: x[0])
        n = len(scored)
        k = max(1, int(n * self._quantile))
        bottom = [tr for _, tr in scored[:k]]
        top = [tr for _, tr in scored[-k:]]
        if trial in bottom:
            donor = self._rng.choice([tr for tr in top if tr is not trial] or [None])
            if donor is not None and donor.latest_checkpoint is not None:
                new_config = self.explore(donor.config)
                controller.request_exploit(trial, donor, new_config)
        return CONTINUE
